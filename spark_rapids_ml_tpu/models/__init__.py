from spark_rapids_ml_tpu.models.pca import PCA, PCAModel
from spark_rapids_ml_tpu.models.kmeans import KMeans, KMeansModel
from spark_rapids_ml_tpu.models.gaussian_mixture import (
    GaussianMixture,
    GaussianMixtureModel,
)
from spark_rapids_ml_tpu.models.mlp import (
    MultilayerPerceptronClassifier,
    MultilayerPerceptronModel,
)
from spark_rapids_ml_tpu.models.linear_regression import (
    LinearRegression,
    LinearRegressionModel,
)
from spark_rapids_ml_tpu.models.logistic_regression import (
    LogisticRegression,
    LogisticRegressionModel,
)
from spark_rapids_ml_tpu.models.linear_svc import LinearSVC, LinearSVCModel
from spark_rapids_ml_tpu.models.glm import (
    GeneralizedLinearRegression,
    GeneralizedLinearRegressionModel,
)
from spark_rapids_ml_tpu.models.nearest_neighbors import (
    NearestNeighbors,
    NearestNeighborsModel,
)
from spark_rapids_ml_tpu.models.dbscan import DBSCAN, DBSCANModel
from spark_rapids_ml_tpu.models.naive_bayes import NaiveBayes, NaiveBayesModel
from spark_rapids_ml_tpu.models.ovr import OneVsRest, OneVsRestModel
from spark_rapids_ml_tpu.models.umap import UMAP, UMAPModel
from spark_rapids_ml_tpu.models.feature_scalers import (
    MaxAbsScaler,
    MaxAbsScalerModel,
    MinMaxScaler,
    MinMaxScalerModel,
    Normalizer,
)
from spark_rapids_ml_tpu.models.gbt import (
    GBTClassificationModel,
    GBTClassifier,
    GBTRegressionModel,
    GBTRegressor,
)
from spark_rapids_ml_tpu.models.random_forest import (
    RandomForestClassificationModel,
    RandomForestClassifier,
    RandomForestRegressionModel,
    RandomForestRegressor,
)
from spark_rapids_ml_tpu.models.feature_scalers import (
    Binarizer,
    RobustScaler,
    RobustScalerModel,
)
from spark_rapids_ml_tpu.models.imputer import Imputer, ImputerModel
from spark_rapids_ml_tpu.models.pipeline import Pipeline, PipelineModel
from spark_rapids_ml_tpu.models.evaluation import (
    BinaryClassificationEvaluator,
    MulticlassClassificationEvaluator,
    RegressionEvaluator,
)
from spark_rapids_ml_tpu.models.tuning import (
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    TrainValidationSplit,
    TrainValidationSplitModel,
)

__all__ = [
    "PCA",
    "PCAModel",
    "KMeans",
    "KMeansModel",
    "GaussianMixture",
    "GaussianMixtureModel",
    "MultilayerPerceptronClassifier",
    "MultilayerPerceptronModel",
    "LinearRegression",
    "LinearRegressionModel",
    "LogisticRegression",
    "LogisticRegressionModel",
    "LinearSVC",
    "LinearSVCModel",
    "GeneralizedLinearRegression",
    "GeneralizedLinearRegressionModel",
    "DBSCAN",
    "DBSCANModel",
    "NearestNeighbors",
    "NearestNeighborsModel",
    "NaiveBayes",
    "NaiveBayesModel",
    "OneVsRest",
    "MinMaxScaler",
    "MinMaxScalerModel",
    "MaxAbsScaler",
    "MaxAbsScalerModel",
    "Normalizer",
    "GBTClassifier",
    "GBTClassificationModel",
    "GBTRegressor",
    "GBTRegressionModel",
    "RandomForestClassifier",
    "RandomForestClassificationModel",
    "RandomForestRegressor",
    "RandomForestRegressionModel",
    "UMAP",
    "UMAPModel",
    "OneVsRestModel",
    "Pipeline",
    "PipelineModel",
    "Binarizer",
    "RobustScaler",
    "RobustScalerModel",
    "Imputer",
    "ImputerModel",
    "RegressionEvaluator",
    "BinaryClassificationEvaluator",
    "MulticlassClassificationEvaluator",
    "ParamGridBuilder",
    "CrossValidator",
    "CrossValidatorModel",
    "TrainValidationSplit",
    "TrainValidationSplitModel",
]
