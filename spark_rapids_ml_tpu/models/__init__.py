from spark_rapids_ml_tpu.models.pca import PCA, PCAModel

__all__ = ["PCA", "PCAModel"]
