from spark_rapids_ml_tpu.models.pca import PCA, PCAModel
from spark_rapids_ml_tpu.models.kmeans import KMeans, KMeansModel
from spark_rapids_ml_tpu.models.linear_regression import (
    LinearRegression,
    LinearRegressionModel,
)
from spark_rapids_ml_tpu.models.logistic_regression import (
    LogisticRegression,
    LogisticRegressionModel,
)
from spark_rapids_ml_tpu.models.nearest_neighbors import (
    NearestNeighbors,
    NearestNeighborsModel,
)
from spark_rapids_ml_tpu.models.pipeline import Pipeline, PipelineModel

__all__ = [
    "PCA",
    "PCAModel",
    "KMeans",
    "KMeansModel",
    "LinearRegression",
    "LinearRegressionModel",
    "LogisticRegression",
    "LogisticRegressionModel",
    "NearestNeighbors",
    "NearestNeighborsModel",
    "Pipeline",
    "PipelineModel",
]
