"""MultilayerPerceptronClassifier Estimator / Model.

Spark ``org.apache.spark.ml.classification.MultilayerPerceptron
Classifier`` param surface: layers (required, e.g. [in, h1, out]),
maxIter, tol, seed, solver ('l-bfgs' default | 'gd'), stepSize,
featuresCol(=inputCol), labelCol, predictionCol, probabilityCol,
rawPredictionCol, weightCol. blockSize is accepted for surface parity
and ignored — it tunes Spark's row-stacking BLAS batching, which is
moot when the whole batch lives on the accelerator.

The full training loop runs as ONE compiled XLA program
(``ops/mlp_kernel.py``): sigmoid hidden layers + softmax cross-entropy,
L-BFGS with zoom linesearch (optax) or plain GD, loss-tolerance stop
evaluated on device. Labels are class indices 0..numClasses-1 like
Spark. The fitted model persists Spark's layout: (layers, flat weight
vector).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from spark_rapids_ml_tpu.data.frame import VectorFrame, as_vector_frame
from spark_rapids_ml_tpu.models.params import (
    HasDeviceId,
    HasInputCol,
    HasWeightCol,
    Param,
)
from spark_rapids_ml_tpu.models.pca import _resolve_device, _resolve_dtype
from spark_rapids_ml_tpu.ops.mlp_kernel import (
    flatten_weights,
    init_weights,
    mlp_train_kernel,
    unflatten_weights,
)
from spark_rapids_ml_tpu.utils.timing import PhaseTimer
from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange
from spark_rapids_ml_tpu.obs import observed_transform


def _valid_layers(v) -> bool:
    return (isinstance(v, (list, tuple)) and len(v) >= 2
            and all(isinstance(i, int) and i >= 1 for i in v))


class MultilayerPerceptronParams(HasInputCol, HasDeviceId, HasWeightCol):
    layers = Param("layers",
                   "layer sizes input..output, e.g. [4, 8, 3]", None,
                   validator=lambda v: v is None or _valid_layers(v))
    labelCol = Param("labelCol",
                     "class-index label column (0..numClasses-1)", "label")
    predictionCol = Param("predictionCol", "predicted class column",
                          "prediction")
    probabilityCol = Param("probabilityCol",
                           "softmax class-probability vector column",
                           "probability")
    rawPredictionCol = Param("rawPredictionCol",
                             "pre-softmax logits vector column",
                             "rawPrediction")
    maxIter = Param("maxIter", "maximum optimizer iterations", 100,
                    validator=lambda v: isinstance(v, int) and v >= 0)
    tol = Param("tol", "loss-change convergence tolerance", 1e-6,
                validator=lambda v: v >= 0)
    seed = Param("seed", "weight-init seed", 0,
                 validator=lambda v: isinstance(v, int))
    solver = Param("solver", "optimizer: 'l-bfgs' (default) or 'gd'",
                   "l-bfgs", validator=lambda v: v in ("l-bfgs", "gd"))
    stepSize = Param("stepSize", "gd learning rate", 0.03,
                     validator=lambda v: v > 0)
    blockSize = Param(
        "blockSize",
        "accepted for Spark surface parity; ignored (BLAS row-stacking "
        "is moot on an accelerator holding the whole batch)",
        128, validator=lambda v: isinstance(v, int) and v >= 1)
    dtype = Param("dtype", "device compute dtype", "auto",
                  validator=lambda v: v in ("auto", "float32", "float64"))


class MultilayerPerceptronClassifier(MultilayerPerceptronParams):
    """``MultilayerPerceptronClassifier(layers=[4, 8, 3]).fit(df)``."""

    def __init__(self, uid: Optional[str] = None, **params):
        super().__init__(uid=uid)
        for name, value in params.items():
            self.set(name, value)

    def save(self, path: str, overwrite: bool = False) -> None:
        from spark_rapids_ml_tpu.io.persistence import save_params

        save_params(self, path, overwrite=overwrite)

    @staticmethod
    def load(path: str) -> "MultilayerPerceptronClassifier":
        from spark_rapids_ml_tpu.io.persistence import load_params

        return load_params(MultilayerPerceptronClassifier, path)

    def fit(self, dataset, labels=None) -> "MultilayerPerceptronModel":
        import jax
        import jax.numpy as jnp

        timer = PhaseTimer()
        layers = self.get_or_default("layers")
        if layers is None:
            raise ValueError("layers must be set, e.g. layers=[4, 8, 3]")
        layers = [int(v) for v in layers]
        frame = as_vector_frame(dataset, self.getInputCol())
        with timer.phase("densify"):
            x = frame.vectors_as_matrix(self.getInputCol()).astype(
                np.float64, copy=False)
            if labels is not None:
                y = np.asarray(labels, dtype=np.float64).reshape(-1)
            else:
                y = np.asarray(frame.column(self.getLabelCol()),
                               dtype=np.float64)
        from spark_rapids_ml_tpu.ops.mlp_kernel import (
            validate_and_onehot,
        )

        y_onehot = validate_and_onehot(x, y, layers)
        w = self._extract_weights(frame, x.shape[0])
        if w is None:
            w = np.ones(x.shape[0])

        device = _resolve_device(self.getDeviceId())
        dtype = _resolve_dtype(self.getDtype())
        params0 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(a, dtype=dtype),
            init_weights(layers, int(self.getSeed())))
        with timer.phase("h2d"):
            x_dev = jax.device_put(jnp.asarray(x, dtype=dtype), device)
            y_dev = jnp.asarray(y_onehot, dtype=dtype)
            w_dev = jnp.asarray(w, dtype=dtype)
        with timer.phase("fit_kernel"), TraceRange("mlp train",
                                                   TraceColor.GREEN):
            params, n_iter, loss = jax.block_until_ready(mlp_train_kernel(
                params0, x_dev, y_dev, w_dev,
                solver=self.get_or_default("solver"),
                max_iter=int(self.getMaxIter()),
                tol=float(self.getTol()),
                step_size=float(self.getStepSize()),
            ))
        model = MultilayerPerceptronModel(
            layers=layers,
            weights=[{k: np.asarray(v, dtype=np.float64)
                      for k, v in layer.items()} for layer in params],
        )
        model.uid = self.uid
        model.copy_values_from(self)
        model.num_iterations_ = int(n_iter)
        model.final_loss_ = float(loss)
        model.fit_timings_ = timer.as_dict()
        return model


class MultilayerPerceptronModel(MultilayerPerceptronParams):
    def __init__(self, layers: Optional[List[int]] = None,
                 weights: Optional[List[dict]] = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.layers_ = layers
        self.weights_ = weights
        self.num_iterations_ = 0
        self.final_loss_ = float("nan")
        self.fit_timings_ = {}

    @property
    def classes_(self) -> np.ndarray:
        return np.arange(self.layers_[-1], dtype=np.float64)

    @property
    def flat_weights(self) -> np.ndarray:
        """Spark's MLPModel weight layout (per layer: W row-major, b)."""
        return flatten_weights(self.weights_)

    def _copy_internal_state(self, other) -> None:
        other.layers_ = self.layers_
        other.weights_ = self.weights_
        other.num_iterations_ = self.num_iterations_
        other.final_loss_ = self.final_loss_

    def _forward(self, x: np.ndarray):
        import jax.numpy as jnp

        if self.weights_ is None:
            raise ValueError("model has no weights; fit first or load")
        dtype = _resolve_dtype(self.getDtype())
        params = [{k: jnp.asarray(v, dtype=dtype)
                   for k, v in layer.items()} for layer in self.weights_]
        from spark_rapids_ml_tpu.ops.mlp_kernel import forward_logits

        logits = forward_logits(params, jnp.asarray(x, dtype=dtype))
        return np.asarray(logits, dtype=np.float64)

    @observed_transform
    def predict_proba(self, x) -> np.ndarray:
        logits = self._forward(np.asarray(x, dtype=np.float64))
        e = np.exp(logits - logits.max(axis=1, keepdims=True))
        return e / e.sum(axis=1, keepdims=True)

    @observed_transform
    def transform(self, dataset) -> VectorFrame:
        frame = as_vector_frame(dataset, self.getInputCol())
        x = frame.vectors_as_matrix(self.getInputCol())
        logits = self._forward(x)
        e = np.exp(logits - logits.max(axis=1, keepdims=True))
        proba = e / e.sum(axis=1, keepdims=True)
        out = frame
        raw_col = self.get_or_default("rawPredictionCol")
        if raw_col:
            out = out.with_column(raw_col, list(logits))
        proba_col = self.get_or_default("probabilityCol")
        if proba_col:
            out = out.with_column(proba_col, list(proba))
        pred_col = self.get_or_default("predictionCol")
        if pred_col:
            out = out.with_column(
                pred_col, np.argmax(logits, axis=1).astype(np.float64))
        return out

    def save(self, path: str, overwrite: bool = False) -> None:
        from spark_rapids_ml_tpu.io.persistence import save_mlp_model

        save_mlp_model(self, path, overwrite=overwrite)

    @staticmethod
    def load(path: str) -> "MultilayerPerceptronModel":
        from spark_rapids_ml_tpu.io.persistence import load_mlp_model

        return load_mlp_model(path)


def weights_from_flat(flat: np.ndarray, layers: List[int]) -> List[dict]:
    """Rebuild the per-layer pytree from Spark's flat vector."""
    return unflatten_weights(np.asarray(flat, dtype=np.float64), layers)
