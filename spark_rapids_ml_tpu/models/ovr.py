"""OneVsRest — multiclass reduction over any binary classifier.

Parity with ``org.apache.spark.ml.classification.OneVsRest``: K binary
sub-models (class k vs rest), prediction by argmax of the sub-models'
scores. Spark fits the K sub-models as independent jobs; here they are
independent device fits in sequence (each already saturates the chip —
see the parallelism note in ``models/tuning.py``).

Works with any estimator exposing the binary-classifier surface this
framework uses (``fit(frame)`` reading labelCol, model ``predict_proba``
or a probability output column) — LogisticRegression in practice.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from spark_rapids_ml_tpu.data.frame import VectorFrame, as_vector_frame
from spark_rapids_ml_tpu.models.params import HasInputCol, Param
from spark_rapids_ml_tpu.obs import observed_transform


class OneVsRestParams(HasInputCol):
    labelCol = Param("labelCol", "label column name", "label")
    predictionCol = Param(
        "predictionCol", "predicted class-index output column", "prediction"
    )
    rawPredictionCol = Param(
        "rawPredictionCol",
        "per-class score vector output column",
        "rawPrediction",
    )


class OneVsRest(OneVsRestParams):
    """``OneVsRest(classifier=LogisticRegression()).fit(df)``."""

    def __init__(self, classifier=None, uid: Optional[str] = None, **kwargs):
        super().__init__(uid=uid)
        self.classifier = classifier
        for name, value in kwargs.items():
            self.set(name, value)

    def _copy_internal_state(self, other: "OneVsRest") -> None:
        # without this, Params.copy() (used by CrossValidator/_fit_with and
        # Pipeline stage copies) would reconstruct with classifier=None
        other.classifier = (
            self.classifier.copy()
            if hasattr(self.classifier, "copy")
            else self.classifier
        )

    def copy(self, extra=None) -> "OneVsRest":
        """``extra`` params not declared by OneVsRest itself route to the
        sub-classifier — the name-keyed analogue of tuning Spark's OvR
        with classifier-bound Params (e.g. a regParam grid)."""
        extra = dict(extra or {})
        own = {k: v for k, v in extra.items() if self.has_param(k)}
        sub = {k: v for k, v in extra.items() if not self.has_param(k)}
        out = super().copy(extra=own)
        if sub:
            if out.classifier is None:
                raise ValueError(
                    f"params {sorted(sub)} need a classifier to apply to"
                )
            out.classifier = out.classifier.copy(extra=sub)
        return out

    def fit(self, dataset) -> "OneVsRestModel":
        if self.classifier is None:
            raise ValueError("classifier must be set")
        frame = as_vector_frame(dataset, self.getInputCol())
        y = np.asarray(frame.column(self.getLabelCol()), dtype=np.float64)
        classes = np.unique(y)
        if classes.size < 2:
            raise ValueError("OneVsRest needs at least two classes")
        if not np.allclose(classes, np.round(classes)):
            raise ValueError("labels must be integer class indices")
        models: List = []
        for cls in classes:
            sub = self.classifier.copy()
            if sub.has_param("inputCol"):
                sub.set("inputCol", self.getInputCol())
            binary = frame.with_column(
                sub.getLabelCol(), (y == cls).astype(np.float64)
            )
            models.append(sub.fit(binary))
        out = OneVsRestModel(
            models=models, classes=classes.astype(np.int64)
        )
        out.uid = self.uid
        out.copy_values_from(self)
        return out


class OneVsRestModel(OneVsRestParams):
    def __init__(
        self,
        models: Optional[List] = None,
        classes: Optional[np.ndarray] = None,
        uid: Optional[str] = None,
    ):
        super().__init__(uid=uid)
        self.models = models or []
        self.classes = classes

    def _copy_internal_state(self, other: "OneVsRestModel") -> None:
        other.models = list(self.models)
        other.classes = self.classes

    def _scores(self, frame) -> np.ndarray:
        cols = []
        for m in self.models:
            if hasattr(m, "predict_proba"):
                cols.append(np.asarray(m.predict_proba(frame), dtype=np.float64))
            else:
                out = m.transform(frame)
                cols.append(
                    np.asarray(
                        out.column(m.getProbabilityCol()), dtype=np.float64
                    )
                )
        return np.stack(cols, axis=1)  # (n, K)

    @observed_transform
    def transform(self, dataset) -> VectorFrame:
        if not self.models:
            raise ValueError("no sub-models; fit first")
        frame = as_vector_frame(dataset, self.getInputCol())
        scores = self._scores(frame)
        pred = self.classes[np.argmax(scores, axis=1)]
        out = frame.with_column(
            self.getRawPredictionCol(), scores.tolist()
        )
        return out.with_column(
            self.getPredictionCol(), pred.astype(np.int64).tolist()
        )
