"""Drop-in import namespace: ``from spark_rapids_ml_tpu.feature import PCA``.

The reference's user-facing layer is a one-import-change shim — users swap
``org.apache.spark.ml.feature.PCA`` for ``com.nvidia.spark.ml.feature.PCA``
and keep the rest of their pipeline untouched (``PCA.scala:27-37``,
``README.md:12-28``). This module plays that role for Python callers coming
from ``pyspark.ml.feature``: the same class names under a ``feature``
module path, re-exported with zero added logic (the shim layer holds no
behavior in the reference either — just ``copy`` + ``load`` plumbing, which
here lives on the classes themselves).
"""

from spark_rapids_ml_tpu.models.kmeans import KMeans, KMeansModel
from spark_rapids_ml_tpu.models.linear_regression import (
    LinearRegression,
    LinearRegressionModel,
)
from spark_rapids_ml_tpu.models.pca import PCA, PCAModel
from spark_rapids_ml_tpu.models.scaler import StandardScaler, StandardScalerModel
from spark_rapids_ml_tpu.models.svd import TruncatedSVD, TruncatedSVDModel

__all__ = [
    "PCA",
    "PCAModel",
    "KMeans",
    "KMeansModel",
    "LinearRegression",
    "LinearRegressionModel",
    "TruncatedSVD",
    "TruncatedSVDModel",
    "StandardScaler",
    "StandardScalerModel",
]
