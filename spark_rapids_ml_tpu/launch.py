"""Multi-process job launcher: ``python -m spark_rapids_ml_tpu.launch``.

The reference has no launcher of its own — Spark starts executors and each
JVM joins the job implicitly (``spark.executor.resource.gpu.*``,
``/root/reference/README.md:81-89``). Here the equivalent glue is explicit:
spawn N processes on this host (or print the env for remote hosts to use),
each of which calls ``parallel.multihost.initialize_multihost()`` and joins
the PJRT coordination service, after which one compiled program spans every
process's devices.

Usage (2 simulated hosts, virtual CPU devices):

    python -m spark_rapids_ml_tpu.launch --nprocs 2 \
        --env JAX_PLATFORMS=cpu \
        --env XLA_FLAGS=--xla_force_host_platform_device_count=2 \
        script.py arg1 arg2

On a real multi-host TPU slice, run one process per host with
``--nprocs <hosts> --node-rank <i> --coordinator <host0>:<port>`` (or rely
on the pod metadata path where ``initialize()`` needs no configuration).
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

from spark_rapids_ml_tpu.parallel.multihost import (
    _ENV_COORD,
    _ENV_NPROC,
    _ENV_PID,
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="spark_rapids_ml_tpu.launch", description=__doc__
    )
    ap.add_argument("--nprocs", type=int, required=True,
                    help="total number of processes in the job")
    ap.add_argument("--coordinator", default=None,
                    help="host:port of process 0 (default: local, free port)")
    ap.add_argument("--node-rank", type=int, default=None,
                    help="launch only this process id (remote-host mode); "
                    "default launches all nprocs locally")
    ap.add_argument("--env", action="append", default=[],
                    help="extra KEY=VALUE env for the children (repeatable)")
    ap.add_argument("script", help="python script to run in each process")
    ap.add_argument("args", nargs=argparse.REMAINDER)
    ns = ap.parse_args(argv)

    if ns.node_rank is not None and ns.coordinator is None:
        # a per-host random local port can never rendezvous across hosts
        ap.error("--node-rank requires --coordinator (host0's host:port)")
    coord = ns.coordinator or f"127.0.0.1:{_free_port()}"
    for kv in ns.env:
        if "=" not in kv:
            ap.error(f"--env expects KEY=VALUE, got {kv!r}")
    extra = dict(kv.split("=", 1) for kv in ns.env)
    ranks = [ns.node_rank] if ns.node_rank is not None else range(ns.nprocs)

    procs = []
    for pid in ranks:
        env = dict(os.environ)
        env.update(extra)
        env[_ENV_COORD] = coord
        env[_ENV_NPROC] = str(ns.nprocs)
        env[_ENV_PID] = str(pid)
        procs.append(
            subprocess.Popen([sys.executable, ns.script, *ns.args], env=env)
        )

    # Fail fast: one dead rank blocks the others inside distributed init,
    # so on the first nonzero exit tear the rest down instead of waiting
    # out the rendezvous timeout.
    rc = 0
    try:
        live = list(procs)
        while live and rc == 0:
            for p in list(live):
                code = p.poll()
                if code is None:
                    continue
                live.remove(p)
                if code != 0:
                    rc = code
            if live and rc == 0:
                time.sleep(0.1)
        if rc != 0:
            for p in live:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            p.wait()
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        rc = 130
    return rc


if __name__ == "__main__":
    sys.exit(main())
