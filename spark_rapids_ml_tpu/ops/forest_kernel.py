"""Random-forest kernels: level-synchronous histogram trees on the MXU.

The reference project's later generations ship cuML-backed random
forests. CPU/GPU tree builders are pointer-and-queue machines (per-node
sample lists, recursive splits); the TPU formulation grows ALL nodes of a
level at once with dense algebra and static shapes:

* features are quantile-binned to small ints once (``quantile_bins``) —
  splits become bin thresholds, the standard histogram-tree trick; the
  SAME edge-application helper (``apply_bin_edges``) serves fit and
  predict so train/inference binning can never diverge;
* one level step builds per-channel (node, feature, bin) statistics
  histograms as dense contractions: rows scatter into their node one-hot
  (n×nodes) and matmul against the per-(feature,bin) one-hot — the MXU
  does the aggregation a CPU builder does with per-sample scatter-adds;
* split selection is a cumulative-sum scan over bins and an argmax over
  (feature, bin) per node — all vectorized, no data-dependent shapes.
  The scaffold (histograms → scan → argmax → routing) is ONE shared
  implementation; regression (variance gain) and classification (Gini)
  plug in only their channel definitions and gain functions;
* samples route to children by ``node ← 2·node + (x_bin > threshold)``,
  one gather + compare per level.

Trees are complete binary trees of fixed ``max_depth`` (inactive nodes
carry zero weight and fall out of the math); bagging draws
Poisson(subsamplingRate) sample weights per tree — the large-n limit of
rate-sized bootstrap resampling — so "resampling" is a weight vector,
never a data copy.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def quantile_bins(
    x: np.ndarray, n_bins: int = 32
) -> Tuple[np.ndarray, np.ndarray]:
    """(binned int32 (n,d), edges (d, n_bins−1)): per-feature quantile
    binning on host (one pass over the data, done once per fit)."""
    x = np.asarray(x, dtype=np.float64)
    qs = np.linspace(0, 1, n_bins + 1)[1:-1]
    edges = np.quantile(x, qs, axis=0).T  # (d, n_bins-1)
    return apply_bin_edges(x, edges), edges


def apply_bin_edges(x: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Bin rows with fitted edges — the ONE binning implementation shared
    by fit and predict (side='right': bin b ⇔ edges[b−1] < v ≤ edges[b])."""
    x = np.asarray(x, dtype=np.float64)
    binned = np.empty(x.shape, dtype=np.int32)
    for j in range(x.shape[1]):
        binned[:, j] = np.searchsorted(edges[j], x[:, j], side="right")
    return binned


class TreeEnsemble(NamedTuple):
    """Complete-binary-tree ensemble, all arrays (trees, 2**depth − 1 …).

    ``feature``/``threshold`` index internal nodes in level order;
    ``leaf_value`` holds 2**depth leaves per tree (regression: mean;
    classification: per-class probabilities with an extra trailing axis).
    """

    feature: jnp.ndarray     # (T, n_internal) int32
    threshold: jnp.ndarray   # (T, n_internal) int32 (bin id; go right if >)
    leaf_value: jnp.ndarray  # (T, n_leaves) or (T, n_leaves, n_classes)


def _bin_onehot(binned: jnp.ndarray, n_bins: int, dtype) -> jnp.ndarray:
    """(n, d·n_bins) with exactly one 1 per feature block. Feature j's
    block sits at offset j·n_bins, so a plain one_hot over bins followed
    by reshape is bit-identical to (and d× cheaper than) a one_hot over
    the combined d·n_bins index space."""
    n, d = binned.shape
    return jax.nn.one_hot(binned, n_bins, dtype=dtype).reshape(
        n, d * n_bins
    )


def _channel_histograms(node_oh, bin_oh, channels):
    """H[c, node, d·B + b] = Σ_s node_oh[s,node]·bin_oh[s,·]·channels[s,c]."""

    def one(stat):
        return lax.dot_general(
            node_oh * stat[:, None],
            bin_oh,
            (((0,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
        )

    return jnp.stack([one(channels[:, c]) for c in range(channels.shape[1])])


def variance_gain_fn(h_l, h_t):
    """Regression split criterion from (count, Σy, Σy²) channel
    histograms: gain = SSE(parent) − SSE(left) − SSE(right)."""

    def sse(h):
        c, s, q = h[0], h[1], h[2]
        return q - (s * s) / jnp.maximum(c, 1e-12)

    return sse(h_t) - sse(h_l) - sse(h_t - h_l)


def gini_gain_fn(h_l, h_t):
    """Classification split criterion from per-class weighted-count
    channel histograms: Gini impurity mass reduction."""

    def gini_mass(h):  # Σ n·gini = n − Σ_k n_k²/n
        total = jnp.sum(h, axis=0)
        return total - jnp.sum(h * h, axis=0) / jnp.maximum(total, 1e-12)

    return gini_mass(h_t) - gini_mass(h_l) - gini_mass(h_t - h_l)


def level_split(
    h, gain_fn, count_channel_slice, feat_mask_level, min_leaf, n_bins
):
    """Split selection for ONE level from its fully-reduced channel
    histograms ``h`` (C, nodes, d, bins): cumulative-sum scan over bins,
    validity masking, argmax over (feature, bin) per node. Returns
    (best_feature, best_threshold, kept_gain); no-positive-gain nodes
    become pass-through (threshold = n_bins routes every sample LEFT).

    This is the ONE split-selection implementation: the in-kernel grower
    (``_grow_tree``) calls it per compiled level step, and the Spark
    statistics plane (``spark/forest_plane.py``) calls it on the driver
    over executor-reduced histograms — selection can never diverge
    between the local, mesh-distributed, and DataFrame fits."""
    n_nodes, d = h.shape[1], h.shape[2]
    h_l = jnp.cumsum(h, axis=3)  # stats of LEFT child if split at bin b
    h_t = h_l[..., -1:]
    gain = gain_fn(h_l, h_t)
    c_l = h_l[count_channel_slice].sum(axis=0)
    c_t = h_t[count_channel_slice].sum(axis=0)
    valid = (c_l >= min_leaf) & (c_t - c_l >= min_leaf)
    valid &= feat_mask_level[None, :, None] > 0
    gain = jnp.where(valid, gain, -jnp.inf)
    flat = gain.reshape(n_nodes, d * n_bins)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    bf = (best // n_bins).astype(jnp.int32)
    bt = (best % n_bins).astype(jnp.int32)
    # no-positive-gain nodes become pass-through (threshold = n_bins
    # sends every sample LEFT; the left subtree inherits the node)
    bt = jnp.where(best_gain > 1e-12, bt, n_bins)
    bf = jnp.where(best_gain > 1e-12, bf, 0)
    kept = jnp.where(best_gain > 1e-12, best_gain, 0.0)
    return bf, bt, kept


def _grow_tree(
    binned, channels, count_channel_slice, gain_fn, feat_mask,
    max_depth, n_bins, min_leaf, axis_name=None,
):
    """Shared level-synchronous scaffold.

    ``channels`` (n, C): per-sample statistics to histogram.
    ``count_channel_slice``: channels summed to get sample counts.
    ``gain_fn(H_left, H_total) -> gain (nodes, d, bins)``: split criterion
    from the prefix-sum (left) and total histograms, both (C, nodes, d, B).
    Returns (feature, threshold, final node assignment).

    ``axis_name``: when growing under ``shard_map`` with rows sharded over
    a mesh axis, per-shard histograms are ``psum``-combined there — the
    ONLY collective the distributed tree needs, and it moves the tiny
    (C, nodes, d, bins) statistics rather than data rows (the same
    partials-aggregation shape the reference used for covariance,
    ``RapidsRowMatrix.scala:168-202``). Split selection then runs
    replicated on every shard; routing stays shard-local.
    """
    n, d = binned.shape
    dtypef = channels.dtype
    bin_oh = _bin_onehot(binned, n_bins, dtypef)
    node = jnp.zeros((n,), dtype=jnp.int32)
    feats = jnp.zeros((2 ** max_depth - 1,), dtype=jnp.int32)
    thrs = jnp.full((2 ** max_depth - 1,), n_bins, dtype=jnp.int32)
    gains = jnp.zeros((2 ** max_depth - 1,), dtype=dtypef)

    for level in range(max_depth):  # static unroll: max_depth compiled steps
        n_nodes = 2 ** level
        base = n_nodes - 1  # level-order offset of this level's nodes
        node_oh = jax.nn.one_hot(node - base, n_nodes, dtype=dtypef)
        h = _channel_histograms(node_oh, bin_oh, channels).reshape(
            channels.shape[1], n_nodes, d, n_bins
        )
        if axis_name is not None:
            h = lax.psum(h, axis_name)
        bf, bt, kept_gain = level_split(
            h, gain_fn, count_channel_slice, feat_mask[level],
            min_leaf, n_bins,
        )
        feats = lax.dynamic_update_slice(feats, bf, (base,))
        thrs = lax.dynamic_update_slice(thrs, bt, (base,))
        gains = lax.dynamic_update_slice(
            gains, kept_gain.astype(dtypef), (base,)
        )
        x_bin = jnp.take_along_axis(
            binned, bf[node - base][:, None], axis=1
        )[:, 0]
        go_right = (x_bin > bt[node - base]).astype(jnp.int32)
        node = (node - base) * 2 + go_right + (2 ** (level + 1) - 1)

    return feats, thrs, node, gains


@partial(
    jax.jit,
    static_argnames=(
        "max_depth", "n_bins", "min_leaf", "axis_name", "return_leaf_ids"
    ),
)
def grow_tree_regression(
    binned: jnp.ndarray,     # (n, d) int32 bins
    y: jnp.ndarray,          # (n,)
    w: jnp.ndarray,          # (n,) bootstrap weights (Poisson)
    feat_mask: jnp.ndarray,  # (max_depth, d) 0/1 per-level feature subsample
    max_depth: int,
    n_bins: int,
    min_leaf: int = 1,
    axis_name=None,
    return_leaf_ids: bool = False,
) -> Tuple[jnp.ndarray, ...]:
    """One regression tree; returns (feature, threshold, leaf_value,
    split_gains) — plus each row's leaf id when ``return_leaf_ids``
    (boosting callers need the assignment the grower already computed;
    re-routing would duplicate a full pass). ``split_gains`` holds each
    internal node's realized criterion gain (0 at pass-through nodes) —
    the per-feature accumulation behind Spark's featureImportances.

    Split criterion: weighted variance reduction from the (count, Σy, Σy²)
    channel histograms; gain = SSE(parent) − SSE(left) − SSE(right).
    ``axis_name``: see ``_grow_tree`` (sharded-row growth under shard_map).
    """
    channels = jnp.stack([w, w * y, w * y * y], axis=1)

    feats, thrs, node, gains = _grow_tree(
        binned, channels, slice(0, 1), variance_gain_fn, feat_mask,
        max_depth, n_bins, min_leaf, axis_name,
    )
    n_leaves = 2 ** max_depth
    leaf_oh = jax.nn.one_hot(node - (n_leaves - 1), n_leaves, dtype=y.dtype)
    cnt = leaf_oh.T @ w
    tot = leaf_oh.T @ (w * y)
    wy_sum = jnp.sum(w * y)
    w_sum = jnp.sum(w)
    if axis_name is not None:
        cnt = lax.psum(cnt, axis_name)
        tot = lax.psum(tot, axis_name)
        wy_sum = lax.psum(wy_sum, axis_name)
        w_sum = lax.psum(w_sum, axis_name)
    # empty leaves fall back to the global weighted mean
    gmean = wy_sum / jnp.maximum(w_sum, 1e-12)
    leaf = jnp.where(cnt > 0, tot / jnp.maximum(cnt, 1e-12), gmean)
    if return_leaf_ids:
        return feats, thrs, leaf, gains, node - (n_leaves - 1)
    return feats, thrs, leaf, gains


@partial(
    jax.jit,
    static_argnames=("max_depth", "n_bins", "min_leaf", "n_classes", "axis_name"),
)
def grow_tree_classification(
    binned: jnp.ndarray,
    y_onehot: jnp.ndarray,  # (n, n_classes)
    w: jnp.ndarray,
    feat_mask: jnp.ndarray,
    max_depth: int,
    n_bins: int,
    n_classes: int,
    min_leaf: int = 1,
    axis_name=None,
) -> Tuple[jnp.ndarray, ...]:
    """One classification tree (Gini impurity); leaves are per-class
    probability vectors, plus each split's realized gain (for feature
    importances). ``axis_name``: see ``_grow_tree``."""
    channels = y_onehot * w[:, None]  # (n, C): per-class weighted counts

    feats, thrs, node, gains = _grow_tree(
        binned, channels, slice(0, n_classes), gini_gain_fn, feat_mask,
        max_depth, n_bins, min_leaf, axis_name,
    )
    n_leaves = 2 ** max_depth
    leaf_oh = jax.nn.one_hot(
        node - (n_leaves - 1), n_leaves, dtype=y_onehot.dtype
    )
    cls_cnt = lax.dot_general(
        leaf_oh * w[:, None],
        y_onehot,
        (((0,), (0,)), ((), ())),
        precision=lax.Precision.HIGHEST,
    )  # (n_leaves, n_classes)
    prior = jnp.sum(y_onehot * w[:, None], axis=0)
    if axis_name is not None:
        cls_cnt = lax.psum(cls_cnt, axis_name)
        prior = lax.psum(prior, axis_name)
    tot = jnp.sum(cls_cnt, axis=1, keepdims=True)
    prior = prior / jnp.maximum(jnp.sum(prior), 1e-12)
    proba = jnp.where(
        tot > 0, cls_cnt / jnp.maximum(tot, 1e-12), prior[None, :]
    )
    return feats, thrs, proba, gains


@partial(jax.jit, static_argnames=("max_depth",))
def route_to_leaves(
    binned: jnp.ndarray,
    feature: jnp.ndarray,
    threshold: jnp.ndarray,
    max_depth: int,
) -> jnp.ndarray:
    """Leaf index (0..2**depth−1) of every row under ONE tree: vectorized
    gathers per level, no recursion. Shared by ensemble apply and the
    boosting leaf-refit (GBT Newton leaves)."""
    node = jnp.zeros((binned.shape[0],), dtype=jnp.int32)
    for level in range(max_depth):
        base = 2 ** level - 1
        f = feature[node]
        t = threshold[node]
        x_bin = jnp.take_along_axis(binned, f[:, None], axis=1)[:, 0]
        go_right = (x_bin > t).astype(jnp.int32)
        node = (node - base) * 2 + go_right + (2 ** (level + 1) - 1)
    return node - (2 ** max_depth - 1)


@partial(jax.jit, static_argnames=("max_depth",))
def forest_apply(
    binned: jnp.ndarray, ensemble: TreeEnsemble, max_depth: int
) -> jnp.ndarray:
    """Route every row through every tree; leaf values averaged over
    trees."""

    def one_tree(feature, threshold, leaf_value):
        leaf = route_to_leaves(binned, feature, threshold, max_depth)
        return leaf_value[leaf]

    per_tree = jax.vmap(one_tree)(
        ensemble.feature, ensemble.threshold, ensemble.leaf_value
    )  # (T, n) or (T, n, C)
    return jnp.mean(per_tree, axis=0)


def feature_importances(features, gains, n_features: int):
    """Split-gain feature importances, Spark's convention: per tree, sum
    each internal node's realized gain onto its split feature and
    normalize the tree to 1; average the trees; normalize again. Host
    NumPy — runs once per fit on tiny (trees, nodes) arrays."""
    import numpy as np

    features = np.asarray(features)
    gains = np.asarray(gains, dtype=np.float64)
    if features.ndim == 1:
        features = features[None, :]
        gains = gains[None, :]
    total = np.zeros(n_features)
    for f_tree, g_tree in zip(features, gains):
        per = np.bincount(
            f_tree, weights=np.maximum(g_tree, 0.0), minlength=n_features
        )
        tree_sum = per.sum()
        if tree_sum > 0:
            total += per / tree_sum
    grand = total.sum()
    return total / grand if grand > 0 else total


@partial(
    jax.jit,
    static_argnames=("max_depth", "n_bins", "min_leaf", "n_classes"),
)
def grow_trees_classification_batch(
    binned: jnp.ndarray,          # (n, d) shared across trees
    y_onehot: jnp.ndarray,        # (n, C) shared
    w_batch: jnp.ndarray,         # (T, n) per-tree bootstrap weights
    feat_mask_batch: jnp.ndarray,  # (T, max_depth, d)
    max_depth: int,
    n_bins: int,
    n_classes: int,
    min_leaf: int = 1,
) -> Tuple[jnp.ndarray, ...]:
    """Grow T classification trees in ONE compiled program.

    ``vmap`` over the tree axis turns the per-level histogram
    contraction into a batched MXU matmul across all T trees — one
    launch per forest instead of T sequential single-tree programs
    (the shapes are identical per tree, only the bootstrap weights and
    feature masks vary). Memory scales with T; callers group trees
    under a budget (``models/random_forest.py::_tree_batch_size``)."""
    def one(w, mask):
        return grow_tree_classification(
            binned, y_onehot, w, mask, max_depth, n_bins, n_classes,
            min_leaf)

    return jax.vmap(one)(w_batch, feat_mask_batch)


@partial(
    jax.jit,
    static_argnames=("max_depth", "n_bins", "min_leaf"),
)
def grow_trees_regression_batch(
    binned: jnp.ndarray,
    y: jnp.ndarray,
    w_batch: jnp.ndarray,
    feat_mask_batch: jnp.ndarray,
    max_depth: int,
    n_bins: int,
    min_leaf: int = 1,
) -> Tuple[jnp.ndarray, ...]:
    """Regression analogue of ``grow_trees_classification_batch``."""
    def one(w, mask):
        return grow_tree_regression(
            binned, y, w, mask, max_depth, n_bins, min_leaf)

    return jax.vmap(one)(w_batch, feat_mask_batch)
