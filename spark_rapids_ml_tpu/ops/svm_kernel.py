"""Linear SVM (squared-hinge) Newton kernels.

Coverage beyond the reference snapshot (which ships only PCA): Spark ML's
``LinearSVC`` is the remaining classical linear classifier in the
Estimator surface this framework mirrors. The objective is the
squared-hinge SVM

    J(w, b) = (1/n) Σᵢ max(0, 1 − ỹᵢ(xᵢ·w + b))² + (λ/2)‖w‖²

with ỹ = 2y − 1 ∈ {−1, +1} and the intercept unpenalized — the smooth
(differentiable) hinge variant, solved by generalized-Newton iterations:
the active set S = {i : 1 − ỹf > 0} gives the exact gradient and the
generalized Hessian (2/n)·X_Sᵀ X_S + λI. Each iteration is two MXU
matmuls (Xᵀr and Xᵀdiag(s)X) + one tiny replicated (n+1)² Cholesky solve
— the same shape as the logistic Newton kernel (ops/logreg_kernel.py),
with the IRLS weights replaced by the active-set indicator. Spark's own
LinearSVC runs OWLQN over the non-smooth hinge; the squared hinge keeps
the compiled while_loop free of line searches (decision boundaries agree
closely; documented deviation).

``reduce_fn`` follows the shared convention: identity on one device,
``psum`` over the mesh in the distributed form.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax


class SvcResult(NamedTuple):
    coefficients: jnp.ndarray   # (n_features,)
    intercept: jnp.ndarray      # scalar
    n_iter: jnp.ndarray         # scalar int
    converged: jnp.ndarray      # scalar bool


def _svc_grad_hess(w, x, y_pm, valid, reg_param, fit_intercept, reduce_fn):
    """(gradient, generalized Hessian) of the squared-hinge objective.

    ``w`` is (n+1,): coefficients ++ intercept slot (zero-pinned when
    ``fit_intercept`` is False). ``y_pm`` is ±1.
    """
    n_feat = x.shape[1]
    coef, b = w[:n_feat], w[n_feat]
    f = x @ coef + b
    margin = 1.0 - y_pm * f
    a = jnp.maximum(margin, 0.0) * valid          # active slack
    s = jnp.where(margin > 0, 1.0, 0.0) * valid   # active-set indicator
    ay = a * y_pm
    gx = lax.dot_general(x, ay, (((0,), (0,)), ((), ())),
                         precision=lax.Precision.HIGHEST)
    xs = x * s[:, None]
    hxx = lax.dot_general(x, xs, (((0,), (0,)), ((), ())),
                          precision=lax.Precision.HIGHEST)
    hxb = jnp.sum(xs, axis=0)
    stats = reduce_fn((gx, hxx, hxb, jnp.sum(ay), jnp.sum(s),
                       jnp.sum(valid)))
    gx, hxx, hxb, aysum, ssum, cnt = stats
    two_inv_n = 2.0 / jnp.maximum(cnt, 1.0)

    g = jnp.zeros_like(w)
    g = g.at[:n_feat].set(-two_inv_n * gx + reg_param * coef)
    # 1e-10 diagonal jitter keeps the Cholesky factorization alive when the
    # active set empties (λ=0, all margins satisfied) — the gradient is
    # zero there too, so the jittered step is a no-op
    h = 1e-10 * jnp.eye(n_feat + 1, dtype=w.dtype)
    h = h.at[:n_feat, :n_feat].add(
        two_inv_n * hxx + reg_param * jnp.eye(n_feat, dtype=w.dtype)
    )
    if fit_intercept:
        g = g.at[n_feat].set(-two_inv_n * aysum)
        h = h.at[:n_feat, n_feat].add(two_inv_n * hxb)
        h = h.at[n_feat, :n_feat].add(two_inv_n * hxb)
        h = h.at[n_feat, n_feat].add(two_inv_n * ssum)
    else:
        h = h.at[n_feat, n_feat].set(1.0)
    return g, h


def svc_newton_iterations(
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    reg_param: float,
    fit_intercept: bool,
    max_iter: int,
    tol: float,
    reduce_fn=lambda t: t,
) -> SvcResult:
    dtype = x.dtype
    valid = (
        jnp.ones(x.shape[0], dtype=dtype) if mask is None
        else mask.astype(dtype)
    )
    y_pm = 2.0 * y.astype(dtype) - 1.0
    n_feat = x.shape[1]
    w0 = jnp.zeros((n_feat + 1,), dtype=dtype)

    def step(state):
        w, _, it, _ = state
        g, h = _svc_grad_hess(
            w, x, y_pm, valid, reg_param, fit_intercept, reduce_fn
        )
        delta = jax.scipy.linalg.cho_solve(jax.scipy.linalg.cho_factor(h), g)
        w_new = w - delta
        moved = jnp.max(jnp.abs(delta))
        return w_new, moved, it + 1, moved <= tol

    def cond(state):
        _, _, it, done = state
        return jnp.logical_and(it < max_iter, jnp.logical_not(done))

    init = (w0, jnp.asarray(jnp.inf, dtype=dtype),
            jnp.asarray(0, dtype=jnp.int32), jnp.asarray(False))
    w, _, n_iter, converged = lax.while_loop(cond, step, init)
    return SvcResult(w[:n_feat], w[n_feat], n_iter, converged)


@partial(jax.jit, static_argnames=("fit_intercept", "max_iter"))
def svc_fit_kernel(
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
    max_iter: int = 100,
    tol: float = 1e-8,
) -> SvcResult:
    return svc_newton_iterations(
        x, y, mask, reg_param, fit_intercept, max_iter, tol
    )


@jax.jit
def svc_decision_kernel(x, coefficients, intercept):
    """Raw decision values x·w + b — Spark's rawPrediction margin."""
    return x @ coefficients + intercept


@partial(jax.jit, donate_argnums=(0,))
def update_svc_stats(carry, batch_z, w, b, mask=None):
    """Out-of-core Newton building block: fold one ``[X | y]`` batch's
    squared-hinge partials (Xᵀ(aỹ), XᵀSX, Xᵀs, Σaỹ, Σs, n) at the
    current (w, b) into a donated accumulator. One streamed pass with
    this per batch = one generalized-Newton gradient/Hessian evaluation
    over the full dataset — the SVC analogue of
    ``ops.logreg_kernel.update_logreg_stats``."""
    gx, hxx, hxb, aysum, ssum, cnt = carry
    x = batch_z[:, :-1].astype(gx.dtype)
    y = batch_z[:, -1].astype(gx.dtype)
    valid = (
        jnp.ones(x.shape[0], dtype=x.dtype) if mask is None
        else mask.astype(x.dtype)
    )
    y_pm = 2.0 * y - 1.0
    margin = 1.0 - y_pm * (x @ w + b)
    a = jnp.maximum(margin, 0.0) * valid
    s = jnp.where(margin > 0, 1.0, 0.0) * valid
    ay = a * y_pm
    xs = x * s[:, None]
    return (
        gx + lax.dot_general(x, ay, (((0,), (0,)), ((), ())),
                             precision=lax.Precision.HIGHEST),
        hxx + lax.dot_general(x, xs, (((0,), (0,)), ((), ())),
                              precision=lax.Precision.HIGHEST),
        hxb + jnp.sum(xs, axis=0),
        aysum + jnp.sum(ay),
        ssum + jnp.sum(s),
        cnt + jnp.sum(valid),
    )
