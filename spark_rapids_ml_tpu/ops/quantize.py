"""Symmetric int8 quantization for the reduced-precision serving kernels.

The serving hot path is GEMM/distance dominated (PCA projection, KMeans
pairwise distances, logreg logits) — exactly the shapes where the MXU's
int8 path doubles effective throughput over bf16 and quadruples it over
f32. The scheme here is the simplest one that preserves the row-level
semantics those kernels need:

* **per-tensor symmetric** scales (``scale = max|a| / 127``) — zero-point
  free, so the dequantized GEMM is a single f32 rescale of the int32
  accumulator (no correction terms);
* accumulation in **int32** via ``preferred_element_type`` — products of
  two int8 operands cannot overflow int32 until the contraction exceeds
  ~2^17 terms, far past any serving feature width here;
* quantization happens **inside the jitted kernel** from the staged
  f32/f64 input, so the serving pipeline's staging/transfer path is
  identical across precisions and the reduced-precision variant is just a
  different compiled signature per bucket.

Accuracy contract: per-tensor int8 carries ~0.4% RMS relative error on
well-conditioned inputs and degrades with dynamic range; the serving
engine therefore gates these variants behind
``SPARK_RAPIDS_ML_TPU_SERVE_PRECISION=int8`` AND an offline max-error
check against the full-precision program at enable time, plus the
numerics sentinel at runtime (``serve.engine``).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp


def quantize_symmetric(a):
    """``(q, scale)`` with ``q = clip(round(a / scale)) ∈ int8`` and
    ``a ≈ q * scale``. Traced inside the serving kernels for the BATCH
    operand (whose values change per call); the scale floor keeps an
    all-zero (padding-only) tensor from dividing by zero."""
    scale = jnp.maximum(jnp.max(jnp.abs(a)), 1e-12) / 127.0
    q = jnp.clip(jnp.round(a / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def quantize_symmetric_host(a):
    """NumPy mirror of ``quantize_symmetric`` for the constant MODEL
    weights (components / centers / coefficients): quantized ONCE at
    ``ServingProgram`` build and staged to the device as int8 + scale,
    instead of re-running the max/round/clip reduction over the full
    weight tensor on every dispatched batch."""
    a = np.asarray(a, dtype=np.float64)
    scale = max(float(np.max(np.abs(a))), 1e-12) / 127.0
    q = np.clip(np.round(a / scale), -127, 127).astype(np.int8)
    return q, np.float32(scale)
