"""KMeans device kernels: k-means++ init and Lloyd iterations.

Second-algorithm coverage (BASELINE.md config 5: "KMeans / LinearRegression
... second-algo stretch"). Same TPU shape as PCA: the hot op is an MXU
matmul (the −2·X·Cᵀ term of the pairwise distances and the one-hot
cluster-sum reduction), iteration is a ``lax.while_loop`` compiled into the
program (no per-iteration host round trip), and the distributed form
all-reduces per-cluster sufficient statistics with ``psum`` — never rows.

All shapes static; padded rows are excluded via ``mask`` everywhere
(assignment, sums, cost).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_ml_tpu.obs.xprof import tracked_jit
from spark_rapids_ml_tpu.ops.quantize import quantize_symmetric


class KMeansResult(NamedTuple):
    centers: jnp.ndarray      # (k, n_features)
    cost: jnp.ndarray         # scalar: sum of squared distances (inertia)
    n_iter: jnp.ndarray       # scalar int
    converged: jnp.ndarray    # scalar bool


def _pairwise_sqdist(x: jnp.ndarray, centers: jnp.ndarray) -> jnp.ndarray:
    """||x−c||² via the expanded form — the cross term is one MXU matmul."""
    x2 = jnp.sum(x * x, axis=1, keepdims=True)
    c2 = jnp.sum(centers * centers, axis=1)[None, :]
    cross = lax.dot_general(
        x, centers, (((1,), (1,)), ((), ())),
        precision=lax.Precision.HIGHEST,
    )
    return jnp.maximum(x2 + c2 - 2.0 * cross, 0.0)


def assign_clusters(x: jnp.ndarray, centers: jnp.ndarray) -> jnp.ndarray:
    return jnp.argmin(_pairwise_sqdist(x, centers), axis=1)


# Serving-path entry point: the standalone jitted assignment with compile
# telemetry (models.KMeansModel.transform). assign_clusters itself stays
# un-jitted so it fuses inside the training-loop programs.
assign_clusters_jit = tracked_jit(assign_clusters, label="kmeans_assign")

# Pipelined-serving variants (KMeansModel.serving_transform_program): the
# *_serve form donates the staged batch buffer (the pipeline never re-reads
# a staged buffer, and its retry path always re-stages from host rows);
# the reduced-precision forms are separate tracked signatures per bucket,
# env-gated + max-error-checked by the serving engine. Cluster assignment
# only needs the argmin ORDER of the distances, so reduced-precision error
# shows up as boundary-row flips, which the engine's mismatch-fraction
# guard bounds.
assign_clusters_serve = tracked_jit(
    assign_clusters, label="kmeans_assign_serve", donate_argnums=(0,)
)


def _assign_bf16(x: jnp.ndarray, centers_bf16: jnp.ndarray) -> jnp.ndarray:
    """bf16 cross-term matmul with f32 accumulation; norms in f32 of the
    SAME bf16-rounded operands so the expanded ||x−c||² stays
    consistent. Centers arrive PRE-CAST (staged once at program build)."""
    xb = x.astype(jnp.bfloat16)
    cross = lax.dot_general(
        xb, centers_bf16, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    xf = xb.astype(jnp.float32)
    cf = centers_bf16.astype(jnp.float32)
    x2 = jnp.sum(xf * xf, axis=1, keepdims=True)
    c2 = jnp.sum(cf * cf, axis=1)[None, :]
    return jnp.argmin(x2 + c2 - 2.0 * cross, axis=1)


assign_clusters_bf16 = tracked_jit(_assign_bf16, label="kmeans_assign_bf16")


def _assign_int8(x: jnp.ndarray, centers_q: jnp.ndarray,
                 centers_scale: jnp.ndarray) -> jnp.ndarray:
    """int8 cross term with int32 accumulation (``ops.quantize``), norms
    of the dequantized operands in f32 — distances consistent with the
    quantized geometry, argmin unchanged under the shared scales.
    Centers arrive PRE-QUANTIZED; only the batch quantizes per call."""
    xq, sx = quantize_symmetric(x)
    cross = lax.dot_general(
        xq, centers_q, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    ).astype(jnp.float32) * (sx * centers_scale)
    xf = xq.astype(jnp.float32) * sx
    cf = centers_q.astype(jnp.float32) * centers_scale
    x2 = jnp.sum(xf * xf, axis=1, keepdims=True)
    c2 = jnp.sum(cf * cf, axis=1)[None, :]
    return jnp.argmin(x2 + c2 - 2.0 * cross, axis=1)


assign_clusters_int8 = tracked_jit(_assign_int8, label="kmeans_assign_int8")

# Un-jitted stage bodies for the fused whole-pipeline serving programs
# (models._serving.build_fused_pipeline_program). Assignment is
# output-typed (labels), so KMeans composes only as the TERMINAL stage.
SERVING_STAGE_BODIES = {
    "native": assign_clusters,
    "bf16": _assign_bf16,
    "int8": _assign_int8,
}


@partial(tracked_jit, static_argnames=("n_clusters",))
def kmeans_plus_plus_init(
    x: jnp.ndarray,
    n_clusters: int,
    key: jax.Array,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """k-means++ seeding on device: next center sampled ∝ min-distance².

    Plays the role of Spark's k-means|| default init — same D²-weighting
    idea, run as a k-step ``fori_loop`` in one compiled program.
    """
    m, n = x.shape
    valid = jnp.ones(m, dtype=x.dtype) if mask is None else mask.astype(x.dtype)
    neg_inf = jnp.asarray(-jnp.inf, dtype=x.dtype)
    key, sub = jax.random.split(key)
    # first center sampled proportionally to the mask value: uniform when
    # the mask is 0/1 validity, and w-proportional when it carries
    # weightCol (the weighted k-means++ first-draw rule)
    first = jax.random.categorical(
        sub, jnp.where(valid > 0, jnp.log(jnp.maximum(valid, 1e-30)),
                       neg_inf)
    )
    centers0 = jnp.zeros((n_clusters, n), dtype=x.dtype).at[0].set(x[first])
    min_d0 = jnp.sum((x - x[first][None, :]) ** 2, axis=1) * valid

    def body(i, state):
        centers, min_d, key = state
        key, sub = jax.random.split(key)
        # sample ∝ D² over VALID rows only — masked (padding) rows must
        # stay -inf even when all valid distances are zero (duplicate-heavy
        # shards), else a zero-filled padding row becomes a center.
        logits = jnp.where(
            valid > 0, jnp.log(jnp.maximum(min_d, 1e-30)), neg_inf
        )
        idx = jax.random.categorical(sub, logits)
        c = x[idx]
        centers = centers.at[i].set(c)
        d_new = jnp.sum((x - c[None, :]) ** 2, axis=1) * valid
        return centers, jnp.minimum(min_d, d_new), key

    centers, _, _ = lax.fori_loop(1, n_clusters, body, (centers0, min_d0, key))
    return centers


def _cluster_stats(x, centers, valid):
    """One Lloyd half-step: assignment + per-cluster (Σx, count, cost).

    The one-hot reduction ``onehotᵀ·X`` is an MXU matmul, not a scatter —
    the TPU-friendly formulation of the cluster sum.
    """
    k = centers.shape[0]
    d = _pairwise_sqdist(x, centers)
    labels = jnp.argmin(d, axis=1)
    onehot = jax.nn.one_hot(labels, k, dtype=x.dtype) * valid[:, None]
    sums = lax.dot_general(
        onehot, x, (((0,), (0,)), ((), ())), precision=lax.Precision.HIGHEST
    )
    counts = jnp.sum(onehot, axis=0)
    cost = jnp.sum(jnp.min(d, axis=1) * valid)
    return sums, counts, cost


def lloyd_iterations(
    x: jnp.ndarray,
    init_centers: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    max_iter: int,
    tol: float,
    reduce_fn: Callable = lambda t: t,
) -> KMeansResult:
    """Lloyd's algorithm as a ``lax.while_loop``.

    ``reduce_fn`` combines (sums, counts, cost) across shards — identity on
    one device, ``psum`` over the mesh in the distributed path; everything
    else is shared between the two.
    """
    valid = (
        jnp.ones(x.shape[0], dtype=x.dtype) if mask is None else mask.astype(x.dtype)
    )

    def step(state):
        centers, _, it, _ = state
        sums, counts, cost = reduce_fn(_cluster_stats(x, centers, valid))
        # empty cluster: keep its previous center (Spark behavior). Divide
        # by the ACTUAL weight mass, not max(counts, 1): with weightCol
        # routed through the mask slot, a cluster's total weight can be a
        # fraction below 1 and flooring it would shrink the center
        denom = jnp.where(counts > 0, counts, 1.0)[:, None]
        new_centers = jnp.where(counts[:, None] > 0, sums / denom, centers)
        shift2 = jnp.sum((new_centers - centers) ** 2, axis=1)
        moved = jnp.sqrt(jnp.max(shift2))
        return new_centers, cost, it + 1, moved <= tol

    def cond(state):
        _, _, it, done = state
        return jnp.logical_and(it < max_iter, jnp.logical_not(done))

    init_state = (
        init_centers,
        jnp.array(jnp.inf, dtype=x.dtype),
        jnp.array(0, dtype=jnp.int32),
        jnp.array(False),
    )
    centers, _, n_iter, converged = lax.while_loop(cond, step, init_state)
    # final cost under the final centers
    _, _, cost = reduce_fn(_cluster_stats(x, centers, valid))
    return KMeansResult(centers, cost, n_iter, converged)


@partial(tracked_jit, donate_argnums=(0,))
def update_cluster_stats(
    carry,
    centers: jnp.ndarray,
    batch: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
):
    """Out-of-core Lloyd building block: fold one batch's per-cluster
    (Σx, count, cost) into a donated accumulator. One streamed pass with
    this per batch = one Lloyd assignment half-step over the full dataset,
    HBM bounded at one batch + one (k, n) accumulator."""
    sums, counts, cost = carry
    valid = (
        jnp.ones(batch.shape[0], dtype=batch.dtype)
        if mask is None
        else mask.astype(batch.dtype)
    )
    s, c, co = _cluster_stats(batch.astype(sums.dtype), centers, valid)
    # per-batch one-hot counts are exact integers in f32; accumulate in the
    # carry's integer dtype so totals stay exact past 2^24 rows
    return sums + s, counts + c.astype(counts.dtype), cost + co


@partial(tracked_jit, static_argnames=("max_iter",))
def kmeans_fit_kernel(
    x: jnp.ndarray,
    init_centers: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    max_iter: int = 20,
    tol: float = 1e-4,
) -> KMeansResult:
    return lloyd_iterations(x, init_centers, mask, max_iter, tol)
