from spark_rapids_ml_tpu.ops.covariance import column_means, covariance, gram
from spark_rapids_ml_tpu.ops.eigh import (
    eigh_descending,
    pca_from_covariance,
    pca_from_covariance_gated,
    resolve_auto_solver,
    sign_flip,
)
from spark_rapids_ml_tpu.ops.pca_kernel import pca_fit_kernel, pca_transform_kernel

__all__ = [
    "column_means",
    "covariance",
    "gram",
    "eigh_descending",
    "sign_flip",
    "pca_from_covariance",
    "pca_from_covariance_gated",
    "resolve_auto_solver",
    "pca_fit_kernel",
    "pca_transform_kernel",
]
