"""Pallas TPU kernel: fused center+scale+mask+Gram.

The covariance pipeline's HBM-bandwidth hazard is materializing the centered
matrix ``(X−μ)·s`` before the Gram matmul — an extra full read+write of X.
XLA usually fuses the subtraction into the matmul's operand load; this
kernel makes that guarantee explicit and adds the row-mask multiply in the
same pass: X is read from HBM exactly once per (i,j) output tile pair, the
center/scale/mask arithmetic happens in VMEM, and the MXU accumulates
``Gᵢⱼ += x̃ᵢᵀ x̃ⱼ`` tile by tile.

Grid: (row_tiles as the MINOR axis for revisiting-accumulation, col_tile_i,
col_tile_j). Output tile (i,j) is initialized on the first row tile and
accumulated across the rest — the standard Pallas reduction pattern.

Used on TPU when shapes are tile-aligned; everywhere else the XLA
``covariance`` path is identical semantics (tests assert equality in
interpret mode).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


# f32 min tile is (8,128). Block sizes were swept on a live TPU v5e
# (bn×br ∈ {256,512,1024,2048}×{512,1024,2048,4096}, 65536×4096 batches):
# 512×1024 wins (2.29M rows/s in the donated-accumulator bench; 256×512
# manages only ~0.4M — small output tiles starve the MXU between grid
# steps) and 2048-wide blocks fail to compile. Scoped-VMEM cost at
# 512×1024: double-buffered f32 inputs 2×2×(1024×512×4B) = 8 MB, bf16
# hi/lo split temps 4×(1024×512×2B) = 4 MB, f32 acc + output staging
# ≈ 2 MB, mean/rowmul slivers — ≈ 17 MB total, past the 16 MB default
# scoped limit, hence the vmem_limit_bytes override on the pallas_call.
_BLOCK_N = int(os.environ.get("TPUML_GRAM_BLOCK_N", "512"))
_BLOCK_R = int(os.environ.get("TPUML_GRAM_BLOCK_R", "1024"))


def gram_block_shape() -> "tuple[int, int]":
    """Current production (block_n, block_r), read at call time so env
    overrides (TPUML_GRAM_BLOCK_N/R) and bench monkeypatches reach the
    streaming dispatch — Python binds keyword defaults at def time, so
    callers that want the live constants must ask here."""
    return _BLOCK_N, _BLOCK_R


# One policy for "should this Gram use the Pallas kernel?" — shared by the
# one-shot estimator gate (models/pca.py) and the streaming dispatch
# (ops/streaming.py) so the two paths can never silently diverge.
_TPU_PLATFORMS = ("tpu", "axon")


def pallas_gram_flag() -> str:
    """TPUML_PALLAS_GRAM: '0' = force XLA, '1' = force Pallas (where it can
    lower at all), unset/other = 'auto' (measured-cost heuristic)."""
    value = os.environ.get("TPUML_PALLAS_GRAM")
    return value if value in ("0", "1") else "auto"


def symmetric_cost_wins(n_features: int) -> bool:
    """Whether the folded symmetric kernel beats XLA at this width.

    The kernel pads features to an even number of _BLOCK_N tiles and then
    does half the padded work: cost ≈ padded² / 2 vs the XLA dot_general's
    n². Selecting on a flat width threshold regresses in the bands just
    above each tile boundary (e.g. n=1100 pads to 2048: 2048²/2 ≈ 2× the
    XLA FLOPs *plus* a padded host copy), so compare actual costs.
    """
    block = 2 * _BLOCK_N
    padded = -(-n_features // block) * block
    return padded * padded <= 2 * n_features * n_features


def pallas_gram_preferred(platform: str, dtype, n_features: int) -> bool:
    """The shared policy gate: flag override, TPU-family backend, f32
    compute, and the padded-cost heuristic. Callers add their own shape
    constraints on top (the streaming path requires exact tile alignment;
    the one-shot path pads)."""
    flag = pallas_gram_flag()
    if flag == "0":
        return False
    if platform not in _TPU_PLATFORMS:
        return False  # Pallas only lowers on the TPU family
    if jnp.dtype(dtype) != jnp.float32:
        return False
    if flag == "1":
        return True
    return symmetric_cost_wins(n_features)


def _make_gram_kernel(precision, symmetric):
    # Precision follows the SAME policy as the XLA gram()
    # (TPUML_GRAM_PRECISION, default bfloat16_3x) so the bench A/B against
    # lax.dot_general compares kernels doing identical MXU work. Mosaic's
    # dot lowering accepts only DEFAULT/HIGHEST, so the 3-pass bf16 split
    # (== lax.Precision.HIGH) is spelled out by hand: x = hi + lo in bf16,
    # accumulate hiᵀhi + hiᵀlo + loᵀhi in f32 and drop the O(ε²) loᵀlo term.
    split_bf16 = precision in ("bfloat16_3x", "high", jax.lax.Precision.HIGH)
    hw_precision = (
        jax.lax.Precision.DEFAULT if split_bf16 else precision
    )

    def _dot_t(a, b, acc_dtype):
        return jax.lax.dot_general(
            a, b, (((0,), (0,)), ((), ())),
            precision=hw_precision,
            preferred_element_type=acc_dtype,
        )

    del symmetric  # tile selection lives in the grid/index maps, not here

    def _gram_kernel(x_i_ref, x_j_ref, mean_i_ref, mean_j_ref, rowmul_ref,
                     o_ref):
        r = pl.program_id(2)

        @pl.when(r == 0)
        def _init():
            o_ref[:] = jnp.zeros_like(o_ref)

        m = rowmul_ref[:]  # (BLOCK_R, 1): mask × 1/√(n−1), 0 on padding
        xi = (x_i_ref[:] - mean_i_ref[:]) * m
        xj = (x_j_ref[:] - mean_j_ref[:]) * m
        if split_bf16:
            xi_hi = xi.astype(jnp.bfloat16)
            xj_hi = xj.astype(jnp.bfloat16)
            xi_lo = (xi - xi_hi.astype(xi.dtype)).astype(jnp.bfloat16)
            xj_lo = (xj - xj_hi.astype(xj.dtype)).astype(jnp.bfloat16)
            acc = _dot_t(xi_hi, xj_hi, o_ref.dtype)
            acc += _dot_t(xi_hi, xj_lo, o_ref.dtype)
            acc += _dot_t(xi_lo, xj_hi, o_ref.dtype)
            o_ref[:] += acc
        else:
            o_ref[:] += _dot_t(xi, xj, o_ref.dtype)

    return _gram_kernel


def _folded_triangle_maps(n_tiles):
    """Index maps for a folded triangular grid over a T×T symmetric output.

    The upper triangle (j ≥ i) has T(T+1)/2 tiles. Pairing row p with row
    T−1−p gives every pair exactly T+1 tiles — row p contributes its T−p
    upper tiles, row T−1−p its p+1 — so a rectangular grid of
    ceil(T/2) × (T+1) covers the triangle with no dead cells: half the MXU
    work AND half the block fetches of the full grid (a skip-with-pl.when
    variant still streams the dead tiles' operands; measured memory-bound
    on a v5e at exactly the full grid's HBM time).

    For odd T the fold pairs the middle row with itself; the q ≥ T−p branch
    then revisits tiles of row p = T−1−p that the first branch already
    covers. Those duplicates would double-accumulate, so the caller must
    keep T even (pad features by one extra block if needed).
    """
    t = n_tiles

    def _ij(p, q):
        in_first = q < t - p
        i = jnp.where(in_first, p, t - 1 - p)
        j = jnp.where(in_first, p + q, q - (t - p) + t - 1 - p)
        return i, j

    return _ij


def fused_centered_gram(
    x: jnp.ndarray,
    mean: jnp.ndarray,
    rowmul: jnp.ndarray,
    interpret: bool = False,
    precision=None,
    symmetric: bool = True,
    block_n: "int | None" = None,
    block_r: "int | None" = None,
) -> jnp.ndarray:
    """Eager shim resolving block defaults at CALL time (None →
    ``gram_block_shape()``) — def-time keyword defaults would freeze the
    import-time constants and ignore env/bench overrides, the staleness
    class the streaming wrappers guard against. See `_fused_centered_gram`
    for the kernel contract."""
    if block_n is None or block_r is None:
        bn, br = gram_block_shape()
        block_n = bn if block_n is None else block_n
        block_r = br if block_r is None else block_r
    return _fused_centered_gram(
        x, mean, rowmul, interpret=interpret, precision=precision,
        symmetric=symmetric, block_n=block_n, block_r=block_r)


@functools.partial(
    jax.jit,
    static_argnames=(
        "interpret", "precision", "symmetric", "block_n", "block_r"
    ),
)
def _fused_centered_gram(
    x: jnp.ndarray,
    mean: jnp.ndarray,
    rowmul: jnp.ndarray,
    interpret: bool = False,
    precision=None,
    symmetric: bool = True,
    block_n: int = 512,
    block_r: int = 1024,
) -> jnp.ndarray:
    """``(diag(rowmul)·(X − mean))ᵀ (diag(rowmul)·(X − mean))`` in one pass.

    ``rowmul`` is the per-row multiplier (mask × global 1/√(n−1) scaling —
    the reference folded the same normalizer into rows before its GEMM,
    ``RapidsRowMatrix.scala:169,179-181``). Requires row/col extents padded
    to the tile grid (use ``pad_for_fused_gram``); padding rows carry
    rowmul=0 so they contribute nothing.

    ``symmetric=True`` (default) exploits Gram symmetry: a folded
    triangular grid visits only upper block tiles — half the MXU FLOPs and
    half the HBM block fetches, a structural advantage a generic
    ``dot_general`` cannot express — then the result is mirrored with an
    elementwise triu + transpose. Requires an even feature-tile count
    (``pad_for_fused_gram`` guarantees it); odd tile counts fall back to
    the full grid.
    """
    rows, n = x.shape
    if rows % block_r or n % block_n:
        raise ValueError(
            f"shape {(rows, n)} must be padded to multiples of "
            f"({block_r}, {block_n}); use pad_for_fused_gram"
        )
    from spark_rapids_ml_tpu.ops.covariance import default_gram_precision

    if precision is None:
        precision = default_gram_precision()
    n_tiles = n // block_n
    r_tiles = rows // block_r
    symmetric = symmetric and n_tiles % 2 == 0  # odd fold double-counts
    mean2d = mean.reshape(1, n).astype(x.dtype)
    rowmul2d = rowmul.reshape(rows, 1).astype(x.dtype)
    if symmetric:
        ij = _folded_triangle_maps(n_tiles)
        grid = (n_tiles // 2, n_tiles + 1, r_tiles)

        def _xi(p, q, r):
            return (r, ij(p, q)[0])

        def _xj(p, q, r):
            return (r, ij(p, q)[1])

        def _mi(p, q, r):
            return (0, ij(p, q)[0])

        def _mj(p, q, r):
            return (0, ij(p, q)[1])

        def _out(p, q, r):
            return ij(p, q)

    else:
        grid = (n_tiles, n_tiles, r_tiles)

        def _xi(i, j, r):
            return (r, i)

        def _xj(i, j, r):
            return (r, j)

        def _mi(i, j, r):
            return (0, i)

        def _mj(i, j, r):
            return (0, j)

        def _out(i, j, r):
            return (i, j)

    out = pl.pallas_call(
        _make_gram_kernel(precision, symmetric),
        out_shape=jax.ShapeDtypeStruct((n, n), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_r, block_n), _xi),
            pl.BlockSpec((block_r, block_n), _xj),
            pl.BlockSpec((1, block_n), _mi),
            pl.BlockSpec((1, block_n), _mj),
            pl.BlockSpec((block_r, 1), lambda *idx: (idx[-1], 0)),
        ],
        out_specs=pl.BlockSpec((block_n, block_n), _out),
        interpret=interpret,
        # 512×1024 blocks need ~17MB of scoped VMEM (see the block-size
        # comment above for the breakdown) — just past the 16MB default
        # scoped limit, well inside the chip's 128MB VMEM.
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024
        ),
    )(x, x, mean2d, mean2d, rowmul2d)
    if symmetric:
        # Diagonal block tiles are computed in full, so their strictly-lower
        # elements are already correct — the elementwise triu keeps one copy
        # and the transpose restores the mirrored half exactly. Lower tiles
        # the folded grid never visited are overwritten here, so their
        # (uninitialized) contents never escape.
        out = jnp.triu(out) + jnp.triu(out, 1).T
    return out


def pad_for_fused_gram(x, mask=None, dtype=None,
                       block_n: "int | None" = None,
                       block_r: "int | None" = None):
    """Pad rows to ``block_r`` and features to ``block_n`` (the same
    block arguments ``fused_centered_gram`` takes); returns
    (x_padded, rowmask_padded, n_features_original).

    One allocation + one copy total (dtype cast included): at the 1M×4096
    target a concatenate-per-axis implementation would transiently hold
    2-3 full copies of X on the host.
    """
    import numpy as np

    if block_n is None or block_r is None:
        bn, br = gram_block_shape()
        block_n = bn if block_n is None else block_n
        block_r = br if block_r is None else block_r
    x = np.asarray(x)
    dtype = x.dtype if dtype is None else np.dtype(dtype)
    rows, n = x.shape
    pr = (-rows) % block_r
    # Pad features to an EVEN number of block_n tiles so the symmetric
    # folded-triangle grid applies (an odd tile count can't fold).
    pn = (-n) % (2 * block_n)
    rowmask = (
        np.ones(rows, dtype=dtype) if mask is None
        else np.asarray(mask, dtype=dtype)
    )
    if pr:
        rowmask = np.concatenate([rowmask, np.zeros(pr, dtype=dtype)])
    if pr == 0 and pn == 0 and x.dtype == dtype:
        return x, rowmask, n
    out = np.zeros((rows + pr, n + pn), dtype=dtype)
    out[:rows, :n] = x
    return out, rowmask, n


def covariance_fused(x, mask=None, mean_centering: bool = True,
                     interpret: bool = False, device=None,
                     dtype=jnp.float32, precision=None):
    """Covariance via the fused kernel: host-side padding + on-device
    mean pass + single fused Gram. Returns (cov[n,n], mean[n]); arrays land
    on ``device`` when given (the estimator's resolved chip), else the
    default device. Padding + dtype cast happen in a single host copy."""
    import numpy as np

    bn, br = gram_block_shape()  # resolve ONCE so pad + kernel agree
    x_p, rowmask, n = pad_for_fused_gram(x, mask, dtype=np.dtype(dtype),
                                         block_n=bn, block_r=br)
    if device is not None:
        x_dev = jax.device_put(jnp.asarray(x_p), device)
        rowmask_dev = jax.device_put(jnp.asarray(rowmask), device)
    else:
        x_dev = jnp.asarray(x_p)
        rowmask_dev = jnp.asarray(rowmask)
    cnt = jnp.sum(rowmask_dev)
    if mean_centering:
        mean = jnp.sum(x_dev * rowmask_dev[:, None], axis=0) / cnt
    else:
        mean = jnp.zeros((x_p.shape[1],), dtype=x_dev.dtype)
    scale = 1.0 / jnp.sqrt(jnp.maximum(cnt - 1.0, 1.0))
    cov_full = fused_centered_gram(
        x_dev, mean, rowmask_dev * scale, interpret=interpret,
        precision=precision, block_n=bn, block_r=br,
    )
    return cov_full[:n, :n], mean[:n]
