"""Pallas TPU kernel: fused center+scale+mask+Gram.

The covariance pipeline's HBM-bandwidth hazard is materializing the centered
matrix ``(X−μ)·s`` before the Gram matmul — an extra full read+write of X.
XLA usually fuses the subtraction into the matmul's operand load; this
kernel makes that guarantee explicit and adds the row-mask multiply in the
same pass: X is read from HBM exactly once per (i,j) output tile pair, the
center/scale/mask arithmetic happens in VMEM, and the MXU accumulates
``Gᵢⱼ += x̃ᵢᵀ x̃ⱼ`` tile by tile.

Grid: (row_tiles as the MINOR axis for revisiting-accumulation, col_tile_i,
col_tile_j). Output tile (i,j) is initialized on the first row tile and
accumulated across the rest — the standard Pallas reduction pattern.

Used on TPU when shapes are tile-aligned; everywhere else the XLA
``covariance`` path is identical semantics (tests assert equality in
interpret mode).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


# f32 min tile is (8,128); 256×256 output tiles with 512-row strips keep
# VMEM well under budget: 2×(512×256) inputs + (256×256) acc ≈ 1.3 MB.
_BLOCK_N = 256
_BLOCK_R = 512


def _make_gram_kernel(precision):
    def _gram_kernel(x_i_ref, x_j_ref, mean_i_ref, mean_j_ref, rowmul_ref,
                     o_ref):
        r = pl.program_id(2)

        @pl.when(r == 0)
        def _init():
            o_ref[:] = jnp.zeros_like(o_ref)

        m = rowmul_ref[:]  # (BLOCK_R, 1): mask × 1/√(n−1), zero on padding
        xi = (x_i_ref[:] - mean_i_ref[:]) * m
        xj = (x_j_ref[:] - mean_j_ref[:]) * m
        # Precision follows the SAME policy as the XLA gram()
        # (TPUML_GRAM_PRECISION, default bfloat16_3x) so the bench A/B
        # against lax.dot_general compares kernels doing identical MXU
        # work, and a user's precision request is honored on this path too.
        o_ref[:] += jax.lax.dot_general(
            xi, xj, (((0,), (0,)), ((), ())),
            precision=precision,
            preferred_element_type=o_ref.dtype,
        )

    return _gram_kernel


@functools.partial(jax.jit, static_argnames=("interpret", "precision"))
def fused_centered_gram(
    x: jnp.ndarray,
    mean: jnp.ndarray,
    rowmul: jnp.ndarray,
    interpret: bool = False,
    precision=None,
) -> jnp.ndarray:
    """``(diag(rowmul)·(X − mean))ᵀ (diag(rowmul)·(X − mean))`` in one pass.

    ``rowmul`` is the per-row multiplier (mask × global 1/√(n−1) scaling —
    the reference folded the same normalizer into rows before its GEMM,
    ``RapidsRowMatrix.scala:169,179-181``). Requires row/col extents padded
    to the tile grid (use ``pad_for_fused_gram``); padding rows carry
    rowmul=0 so they contribute nothing.
    """
    rows, n = x.shape
    if rows % _BLOCK_R or n % _BLOCK_N:
        raise ValueError(
            f"shape {(rows, n)} must be padded to multiples of "
            f"({_BLOCK_R}, {_BLOCK_N}); use pad_for_fused_gram"
        )
    from spark_rapids_ml_tpu.ops.covariance import default_gram_precision

    if precision is None:
        precision = default_gram_precision()
    grid = (n // _BLOCK_N, n // _BLOCK_N, rows // _BLOCK_R)
    mean2d = mean.reshape(1, n).astype(x.dtype)
    rowmul2d = rowmul.reshape(rows, 1).astype(x.dtype)
    return pl.pallas_call(
        _make_gram_kernel(precision),
        out_shape=jax.ShapeDtypeStruct((n, n), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_R, _BLOCK_N), lambda i, j, r: (r, i)),
            pl.BlockSpec((_BLOCK_R, _BLOCK_N), lambda i, j, r: (r, j)),
            pl.BlockSpec((1, _BLOCK_N), lambda i, j, r: (0, i)),
            pl.BlockSpec((1, _BLOCK_N), lambda i, j, r: (0, j)),
            pl.BlockSpec((_BLOCK_R, 1), lambda i, j, r: (r, 0)),
        ],
        out_specs=pl.BlockSpec((_BLOCK_N, _BLOCK_N), lambda i, j, r: (i, j)),
        interpret=interpret,
    )(x, x, mean2d, mean2d, rowmul2d)


def pad_for_fused_gram(x, mask=None, dtype=None):
    """Pad rows to _BLOCK_R and features to _BLOCK_N; returns
    (x_padded, rowmask_padded, n_features_original).

    One allocation + one copy total (dtype cast included): at the 1M×4096
    target a concatenate-per-axis implementation would transiently hold
    2-3 full copies of X on the host.
    """
    import numpy as np

    x = np.asarray(x)
    dtype = x.dtype if dtype is None else np.dtype(dtype)
    rows, n = x.shape
    pr = (-rows) % _BLOCK_R
    pn = (-n) % _BLOCK_N
    rowmask = (
        np.ones(rows, dtype=dtype) if mask is None
        else np.asarray(mask, dtype=dtype)
    )
    if pr:
        rowmask = np.concatenate([rowmask, np.zeros(pr, dtype=dtype)])
    if pr == 0 and pn == 0 and x.dtype == dtype:
        return x, rowmask, n
    out = np.zeros((rows + pr, n + pn), dtype=dtype)
    out[:rows, :n] = x
    return out, rowmask, n


def covariance_fused(x, mask=None, mean_centering: bool = True,
                     interpret: bool = False, device=None,
                     dtype=jnp.float32):
    """Covariance via the fused kernel: host-side padding + on-device
    mean pass + single fused Gram. Returns (cov[n,n], mean[n]); arrays land
    on ``device`` when given (the estimator's resolved chip), else the
    default device. Padding + dtype cast happen in a single host copy."""
    import numpy as np

    x_p, rowmask, n = pad_for_fused_gram(x, mask, dtype=np.dtype(dtype))
    if device is not None:
        x_dev = jax.device_put(jnp.asarray(x_p), device)
        rowmask_dev = jax.device_put(jnp.asarray(rowmask), device)
    else:
        x_dev = jnp.asarray(x_p)
        rowmask_dev = jnp.asarray(rowmask)
    cnt = jnp.sum(rowmask_dev)
    if mean_centering:
        mean = jnp.sum(x_dev * rowmask_dev[:, None], axis=0) / cnt
    else:
        mean = jnp.zeros((x_p.shape[1],), dtype=x_dev.dtype)
    scale = 1.0 / jnp.sqrt(jnp.maximum(cnt - 1.0, 1.0))
    cov_full = fused_centered_gram(
        x_dev, mean, rowmask_dev * scale, interpret=interpret
    )
    return cov_full[:n, :n], mean[:n]
