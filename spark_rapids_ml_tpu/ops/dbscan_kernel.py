"""DBSCAN on device: epsilon-graph construction + min-label propagation.

Coverage beyond this reference snapshot (the reference project's later
generations ship a cuML-backed DBSCAN). The TPU formulation avoids every
pointer-chasing structure a CPU DBSCAN uses (KD-trees, BFS queues,
union-find):

* the ε-neighborhood graph is dense pairwise-distance blocks from one MXU
  rank-expansion (same kernel family as KNN, ``ops/knn_kernel.py``);
* connected components of the core-point graph come from iterated
  min-label propagation — ``label[i] ← min(label[j] : j core neighbor)``
  — a masked row-min over adjacency blocks, run under ``lax.while_loop``
  to a fixed point. Label propagation converges in O(graph diameter)
  sweeps, each one MXU/VPU-friendly dense pass, versus a sequential BFS;
* border points take the minimum core-neighbor label in one final sweep
  (deterministic, unlike queue-order-dependent CPU DBSCANs); noise = −1.

Everything is fixed-shape and jit-compiled; the n×n adjacency is
materialized in HBM as f32 (0/1), fine for the n ≲ 30k regime this dense
variant targets. Distances use HIGHEST precision (cancellation in the
rank-expansion, same policy as kmeans/knn).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_ml_tpu.ops.knn_kernel import pairwise_sqdist


@partial(jax.jit, static_argnames=("min_pts",))
def dbscan_labels(
    x: jnp.ndarray, eps: jnp.ndarray, min_pts: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(labels[n] int32, core_mask[n] bool) for one device-resident batch.

    Labels are cluster representatives (the minimum original row index in
    each cluster); the estimator relabels to consecutive ids on host.
    Noise rows get −1.
    """
    n = x.shape[0]
    d2 = pairwise_sqdist(x, x)
    adj = (d2 <= eps * eps).astype(x.dtype)  # includes self-edge
    degree = jnp.sum(adj, axis=1)
    core = degree >= min_pts
    core_f = core.astype(x.dtype)

    inf = jnp.asarray(jnp.inf, x.dtype)
    idx = jnp.arange(n, dtype=x.dtype)
    # core points start as their own representative; others inactive
    labels0 = jnp.where(core, idx, inf)

    # adjacency restricted to core columns: propagation flows only
    # through core points (border points never bridge clusters)
    adj_core = adj * core_f[None, :]

    def neighbor_min(labels):
        # min over core neighbors: mask non-edges to +inf, row-min
        cand = jnp.where(adj_core > 0, labels[None, :], inf)
        return jnp.min(cand, axis=1)

    def body(state):
        labels, _ = state
        nxt = jnp.minimum(labels, jnp.where(core, neighbor_min(labels), inf))
        return nxt, jnp.any(nxt != labels)

    def cond(state):
        return state[1]

    labels_core, _ = lax.while_loop(cond, body, (labels0, jnp.asarray(True)))

    # border points: minimum core-neighbor representative (deterministic
    # tie-break); rows with no core neighbor are noise
    border_label = jnp.min(
        jnp.where(adj_core > 0, labels_core[None, :], inf), axis=1
    )
    final = jnp.where(core, labels_core, border_label)
    labels_int = jnp.where(
        jnp.isfinite(final), final, jnp.asarray(-1, x.dtype)
    ).astype(jnp.int32)
    return labels_int, core


@partial(jax.jit, static_argnames=("min_pts", "block_rows"))
def dbscan_labels_blocked(
    x: jnp.ndarray,
    valid: jnp.ndarray,
    eps: jnp.ndarray,
    min_pts: int,
    block_rows: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``dbscan_labels`` semantics with the ε-graph TILED over row blocks.

    The dense kernel materializes the n×n adjacency in HBM (the n ≲ 30k
    envelope); here each propagation sweep recomputes one
    (block_rows × n) distance block at a time under ``lax.map`` — peak
    memory is one block, so n scales to the hundreds of thousands, and
    the recomputed blocks are MXU rank-expansions the chip is fastest at
    anyway. Identical label semantics: min-label propagation to fixpoint,
    deterministic minimum-core-neighbor border assignment, noise = −1.

    ``x`` must be padded to a multiple of ``block_rows``; ``valid`` marks
    real rows (padded rows are never core, never neighbors, label −1).
    """
    n = x.shape[0]
    assert n % block_rows == 0
    nb = n // block_rows
    dt = x.dtype
    inf = jnp.asarray(jnp.inf, dt)
    valid_f = valid.astype(dt)
    xb = x.reshape(nb, block_rows, x.shape[1])

    def degree_block(xi):
        d2 = pairwise_sqdist(xi, x)
        adj = (d2 <= eps * eps).astype(dt) * valid_f[None, :]
        return jnp.sum(adj, axis=1)

    degree = lax.map(degree_block, xb).reshape(n) * valid_f
    core = (degree >= min_pts) & valid
    core_f = core.astype(dt)

    idx = jnp.arange(n, dtype=dt)
    labels0 = jnp.where(core, idx, inf)

    def neighbor_min_block(args, labels):
        xi = args
        d2 = pairwise_sqdist(xi, x)
        adj_core = (d2 <= eps * eps).astype(dt) * core_f[None, :]
        return jnp.min(
            jnp.where(adj_core > 0, labels[None, :], inf), axis=1
        )

    def sweep(labels):
        return lax.map(
            lambda xi: neighbor_min_block(xi, labels), xb
        ).reshape(n)

    def body(state):
        labels, _ = state
        nxt = jnp.minimum(labels, jnp.where(core, sweep(labels), inf))
        return nxt, jnp.any(nxt != labels)

    labels_core, _ = lax.while_loop(
        lambda s: s[1], body, (labels0, jnp.asarray(True))
    )

    border_label = sweep(labels_core)
    final = jnp.where(core, labels_core, border_label)
    final = jnp.where(valid, final, inf)
    labels_int = jnp.where(
        jnp.isfinite(final), final, jnp.asarray(-1, dt)
    ).astype(jnp.int32)
    return labels_int, core
