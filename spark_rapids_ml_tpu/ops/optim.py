"""Shared on-device optimizer loop: L-BFGS / GD to convergence in one
compiled program.

The same whole-loop-on-device shape as ``ops/mlp_kernel.py`` — a
``lax.while_loop`` over optax updates with the loss-change stop
evaluated on device — generalized over an arbitrary loss closure and
parameter pytree, so new smooth-objective families (AFT survival,
factorization machines) get the compiled training loop for free.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("loss_fn", "solver", "max_iter"))
def minimize_kernel(params, data, *, loss_fn, solver: str, max_iter: int,
                    tol, step_size=0.01):
    """Minimize ``loss_fn(params, *data)`` from ``params``.

    ``loss_fn`` must be a MODULE-LEVEL function (it is a static jit
    argument — a per-fit closure would recompile every call); the
    training arrays travel in ``data`` as ordinary traced operands, so
    repeated fits at the same shapes reuse the compiled program.
    Returns (params, n_iter, loss).
    """

    def objective(p):
        return loss_fn(p, *data)

    # carry slots must match the loss dtype exactly (float32 data under
    # an x64 runtime would otherwise fail while_loop's type check)
    val_dtype = jax.eval_shape(objective, params).dtype
    inf = jnp.asarray(jnp.inf, dtype=val_dtype)
    zero = jnp.asarray(0.0, dtype=val_dtype)

    def cond(carry):
        _p, _s, value, prev, it = carry
        return jnp.logical_and(it < max_iter,
                               jnp.abs(value - prev) >= tol)

    if solver == "l-bfgs":
        try:
            import optax
        except ImportError as exc:
            raise ImportError(
                "solver 'l-bfgs' needs optax (pip install "
                "spark-rapids-ml-tpu[mlp]); alternatively set "
                "solver='gd'"
            ) from exc

        opt = optax.lbfgs()
        # NOT optax.value_and_grad_from_state: its reuse cond compares the
        # init state's weak-f64 inf against the objective's value and
        # rejects float32 objectives under an x64 runtime (optax 0.2.3).
        # Recomputing at p is the same math, one extra fwd+bwd per iter.
        value_and_grad = jax.value_and_grad(objective)

        def body(carry):
            p, state, value, _prev, it = carry
            new_value, grad = value_and_grad(p)
            updates, state = opt.update(
                grad, state, p, value=new_value, grad=grad,
                value_fn=objective)
            p = optax.apply_updates(p, updates)
            return (p, state, new_value, value, it + 1)

        state0 = opt.init(params)
    elif solver == "adamW":
        try:
            import optax
        except ImportError as exc:
            raise ImportError(
                "solver 'adamW' needs optax (pip install "
                "spark-rapids-ml-tpu[mlp]); alternatively set "
                "solver='gd'"
            ) from exc

        # weight_decay stays 0: regularization belongs to the loss
        # (optax's 1e-4 default would silently shrink every parameter,
        # intercepts included, on top of the objective's regParam)
        opt = optax.adamw(learning_rate=step_size, weight_decay=0.0)
        grad_fn = jax.value_and_grad(objective)

        def body(carry):
            p, state, value, _prev, it = carry
            new_value, g = grad_fn(p)
            updates, state = opt.update(g, state, p)
            p = optax.apply_updates(p, updates)
            return (p, state, new_value, value, it + 1)

        state0 = opt.init(params)
    else:
        grad_fn = jax.value_and_grad(objective)

        def body(carry):
            p, state, value, _prev, it = carry
            new_value, g = grad_fn(p)
            p = jax.tree_util.tree_map(
                lambda a, b: a - step_size * b, p, g)
            return (p, state, new_value, value, it + 1)

        state0 = ()

    p, _state, value, _prev, it = jax.lax.while_loop(
        cond, body, (params, state0, inf, zero, jnp.asarray(0)))
    return p, it, value
