"""GLM IRLS per-iteration device kernel (family × link grid).

One XLA program per (family, link, powers) combo: eta -> mu -> working
response/weights -> weighted sufficient statistics (X'WX, X'Wz, sum(wx),
sum(wz), sum(w)) plus the deviance, fused in a single pass over the batch
so the MXU does the Gram work and the VPU the elementwise family math.
The tiny (d x d) solve stays on host float64 — the same stats/solve split
as ``ops/linreg_kernel.py`` and ``ops/logreg_kernel.py``.

The reference repo (spark-rapids-ml 21.12, PCA-only — see
``/root/reference/src/main/scala/com/nvidia/spark/ml/feature/PCA.scala``)
has no GLM; this module follows the semantics of Spark's
``org.apache.spark.ml.regression.GeneralizedLinearRegression`` (family /
link grid, IRLS, deviance-based convergence) as a beyond-parity family.

Every family/link function is written against an array-module parameter
``xp`` (numpy or jax.numpy) so the device step and the host fallback run
the IDENTICAL math — the oracle tests exploit this.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Optional, Tuple

import numpy as np

FAMILIES = ("gaussian", "binomial", "poisson", "gamma", "tweedie")

# Spark's supported link grid per family (GeneralizedLinearRegression
# docs); "tweedie" takes a power link parameterized by linkPower instead
# of a named link.
FAMILY_LINKS = {
    "gaussian": ("identity", "log", "inverse"),
    "binomial": ("logit", "probit", "cloglog"),
    "poisson": ("log", "identity", "sqrt"),
    "gamma": ("inverse", "identity", "log"),
}

CANONICAL_LINK = {
    "gaussian": "identity",
    "binomial": "logit",
    "poisson": "log",
    "gamma": "inverse",
}

_EPS = 1e-10


def _ndtri(xp, q):
    if xp is np:
        from scipy.special import ndtri

        return ndtri(q)
    from jax.scipy.special import ndtri as jndtri

    return jndtri(q)


def _ndtr(xp, x):
    if xp is np:
        from scipy.special import ndtr

        return ndtr(x)
    from jax.scipy.special import ndtr as jndtr

    return jndtr(x)


def _norm_pdf(xp, x):
    return xp.exp(-0.5 * x * x) / np.sqrt(2.0 * np.pi)


def link_funcs(link: str, link_power: float = 1.0) -> Tuple[
    Callable, Callable, Callable
]:
    """(g, g_inverse, g_prime) for a named link; each takes (xp, array).

    g maps mu -> eta; g_prime is dg/dmu (enters both the working response
    and the IRLS weight).
    """
    if link == "identity":
        return (lambda xp, mu: mu,
                lambda xp, eta: eta,
                lambda xp, mu: xp.ones_like(mu))
    if link == "log":
        return (lambda xp, mu: xp.log(mu),
                lambda xp, eta: xp.exp(eta),
                lambda xp, mu: 1.0 / mu)
    if link == "logit":
        return (lambda xp, mu: xp.log(mu) - xp.log1p(-mu),
                lambda xp, eta: 1.0 / (1.0 + xp.exp(-eta)),
                lambda xp, mu: 1.0 / (mu * (1.0 - mu)))
    if link == "inverse":
        return (lambda xp, mu: 1.0 / mu,
                lambda xp, eta: 1.0 / eta,
                lambda xp, mu: -1.0 / (mu * mu))
    if link == "sqrt":
        return (lambda xp, mu: xp.sqrt(mu),
                lambda xp, eta: eta * eta,
                lambda xp, mu: 0.5 / xp.sqrt(mu))
    if link == "probit":
        return (lambda xp, mu: _ndtri(xp, mu),
                lambda xp, eta: _ndtr(xp, eta),
                lambda xp, mu: 1.0 / _norm_pdf(xp, _ndtri(xp, mu)))
    if link == "cloglog":
        return (lambda xp, mu: xp.log(-xp.log1p(-mu)),
                lambda xp, eta: -xp.expm1(-xp.exp(eta)),
                lambda xp, mu: -1.0 / ((1.0 - mu) * xp.log1p(-mu)))
    if link == "power":
        lp = float(link_power)
        if lp == 0.0:
            return link_funcs("log")
        return (lambda xp, mu: mu ** lp,
                lambda xp, eta: eta ** (1.0 / lp),
                lambda xp, mu: lp * mu ** (lp - 1.0))
    raise ValueError(f"unknown link {link!r}")


def _xlogy(xp, a, b):
    """a * log(a/b) with the a==0 limit handled (binomial/poisson dev)."""
    safe = xp.where(a > 0, a, 1.0)
    safe_b = xp.where(b > 0, b, 1.0)
    return xp.where(a > 0, a * (xp.log(safe) - xp.log(safe_b)), 0.0)


def family_funcs(family: str, var_power: float = 0.0) -> Tuple[
    Callable, Callable, Callable, Callable
]:
    """(variance, unit_deviance, clip_mu, init_mu) for a family.

    variance/unit_deviance/clip_mu take (xp, ...); init_mu takes
    (xp, y, w) and produces the IRLS starting mean (the standard GLM
    start used by R and Spark alike).
    """
    if family == "gaussian":
        return (lambda xp, mu: xp.ones_like(mu),
                lambda xp, y, mu: (y - mu) ** 2,
                lambda xp, mu: mu,
                lambda xp, y, w: y)
    if family == "binomial":
        return (lambda xp, mu: mu * (1.0 - mu),
                lambda xp, y, mu: 2.0 * (_xlogy(xp, y, mu)
                                         + _xlogy(xp, 1.0 - y, 1.0 - mu)),
                lambda xp, mu: xp.clip(mu, _EPS, 1.0 - _EPS),
                lambda xp, y, w: (w * y + 0.5) / (w + 1.0))
    if family == "poisson":
        return (lambda xp, mu: mu,
                lambda xp, y, mu: 2.0 * (_xlogy(xp, y, mu) - (y - mu)),
                lambda xp, mu: xp.maximum(mu, _EPS),
                lambda xp, y, w: y + 0.1)
    if family == "gamma":
        return (lambda xp, mu: mu * mu,
                lambda xp, y, mu: -2.0 * (xp.log(y / mu) - (y - mu) / mu),
                lambda xp, mu: xp.maximum(mu, _EPS),
                lambda xp, y, w: y)
    if family == "tweedie":
        p = float(var_power)
        if p == 0.0:
            return family_funcs("gaussian")
        if p == 1.0:
            return family_funcs("poisson")
        if p == 2.0:
            return family_funcs("gamma")

        def dev(xp, y, mu):
            # 2*[ y^(2-p)/((1-p)(2-p)) - y*mu^(1-p)/(1-p) + mu^(2-p)/(2-p) ]
            ymax = xp.maximum(y, 0.0)
            return 2.0 * (ymax ** (2.0 - p) / ((1.0 - p) * (2.0 - p))
                          - y * mu ** (1.0 - p) / (1.0 - p)
                          + mu ** (2.0 - p) / (2.0 - p))

        return (lambda xp, mu: mu ** p,
                dev,
                lambda xp, mu: xp.maximum(mu, _EPS),
                lambda xp, y, w: y + 0.1)
    raise ValueError(f"unknown family {family!r}")


class GlmStepOut(NamedTuple):
    """One IRLS iteration's reduced outputs (all small: d x d and d)."""

    xtx: object   # X' W X            (d, d)
    xtz: object   # X' W z            (d,)
    x_sum: object  # sum(w x)         (d,)
    z_sum: object  # sum(w z)         scalar
    w_sum: object  # sum(w)           scalar
    deviance: object  # sum(w_prior * unit_dev(y, mu))  scalar


def irls_step_math(xp, x, y, w_prior, offset, coef, intercept, *,
                   family: str, link: str, var_power: float,
                   link_power: float, use_init_mu: bool = False) -> GlmStepOut:
    """The ONE definition of a weighted IRLS pass — runs under numpy
    (host fallback) and under jit (device path) unchanged.

    ``use_init_mu`` is the first-iteration start (R glm.fit's mustart):
    mu comes elementwise from the family's standard starting mean of y,
    NOT from the (zero) coefficients — essential for inverse/log links,
    where eta=0 would put mu at a pole and poison the working weights.
    """
    variance, unit_dev, clip_mu, init_mu = family_funcs(family, var_power)
    g, ginv, gprime = link_funcs(link, link_power)
    if use_init_mu:
        mu = clip_mu(xp, init_mu(xp, y, w_prior))
        eta = g(xp, mu) + offset
    else:
        eta = x @ coef + intercept + offset
        mu = clip_mu(xp, ginv(xp, eta))
    gp = gprime(xp, mu)
    z = (eta - offset) + (y - mu) * gp
    wi = w_prior / (variance(xp, mu) * gp * gp)
    xw = x * wi[:, None]
    if xp is np:
        xtx = x.T @ xw
    else:
        from jax import lax

        xtx = lax.dot_general(
            xw, x, (((0,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
        )
    return GlmStepOut(
        xtx=xtx,
        xtz=xw.T @ z,
        x_sum=xp.sum(xw, axis=0),
        z_sum=xp.sum(wi * z),
        w_sum=xp.sum(wi),
        deviance=xp.sum(w_prior * unit_dev(xp, y, mu)),
    )


def _device_step(x, y, w_prior, offset, coef, intercept, *, family, link,
                 var_power, link_power, use_init_mu):
    import jax.numpy as jnp

    return irls_step_math(
        jnp, x, y, w_prior, offset, coef, intercept,
        family=family, link=link, var_power=var_power, link_power=link_power,
        use_init_mu=use_init_mu,
    )


_jitted_device_step = None


def glm_irls_device_step(x, y, w_prior, offset, coef, intercept, *, family,
                         link, var_power, link_power, use_init_mu=False):
    """Jitted device IRLS pass; one compile per (family, link, powers,
    shapes) — stable across fits (module-level cache, like the other
    kernels)."""
    global _jitted_device_step
    if _jitted_device_step is None:
        import jax

        _jitted_device_step = jax.jit(
            _device_step,
            static_argnames=("family", "link", "var_power", "link_power",
                             "use_init_mu"),
        )
    return _jitted_device_step(
        x, y, w_prior, offset, coef, intercept, family=family, link=link,
        var_power=float(var_power), link_power=float(link_power),
        use_init_mu=bool(use_init_mu),
    )


def deviance_math(xp, y, mu, w, *, family: str, var_power: float = 0.0):
    _, unit_dev, _, _ = family_funcs(family, var_power)
    return xp.sum(w * unit_dev(xp, y, mu))


def validate_label_range(y: np.ndarray, *, family: str,
                         var_power: float = 0.0) -> None:
    if family == "binomial":
        if ((y < 0) | (y > 1)).any():
            raise ValueError("binomial labels must lie in [0, 1]")
    elif family == "poisson" or (family == "tweedie" and var_power != 0.0):
        if (y < 0).any():
            raise ValueError(f"{family} labels must be non-negative")
    elif family == "gamma":
        if (y <= 0).any():
            raise ValueError("gamma labels must be positive")
