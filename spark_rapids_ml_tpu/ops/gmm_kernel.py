"""GaussianMixture EM kernels: fused device E-step + statistics pass.

TPU mapping: the driver (host) keeps the tiny mixture state — weights,
means, covariances — and precomputes the PRECISION Cholesky factors
(k x d x d, the sklearn trick), so the per-row device work is pure
matmuls: y_k = (x - mu_k) @ P_k, log-prob from ||y_k||^2, responsibilities
by logsumexp, then the M-step sufficient statistics
(sum r, sum r x, sum r x x^T, loglik) reduced on device in one fused
program. The M-step itself is a k x d x d host-float64 update.

The reference repo (spark-rapids-ml 21.12) is PCA-only; this follows
Spark's ``org.apache.spark.ml.clustering.GaussianMixture`` semantics
(param surface, responsibility outputs, mean-loglik tol) as a
beyond-parity family.

All math is written against the array-module parameter ``xp`` so the
device pass and the host fallback share one definition (the GLM kernel
convention, ``ops/glm_kernel.py``).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

_LOG_2PI = float(np.log(2.0 * np.pi))


class GmmStats(NamedTuple):
    """One EM pass's reduced outputs."""

    resp_sum: object   # sum_n r_nk                (k,)
    mean_sum: object   # sum_n r_nk x_n            (k, d)
    sq_sum: object     # sum_n r_nk x_n x_n^T      (k, d, d)
    loglik: object     # sum_n w_n log p(x_n)      scalar
    w_sum: object      # sum_n w_n                 scalar


def _logsumexp(xp, a, axis):
    m = xp.max(a, axis=axis, keepdims=True)
    return (xp.log(xp.sum(xp.exp(a - m), axis=axis, keepdims=True))
            + m).squeeze(axis)


def log_prob_math(xp, x, means, prec_chol, log_det):
    """(n, k) log N(x | mu_k, Sigma_k) from precision Cholesky factors.

    ``prec_chol[k]`` is upper-triangular with Sigma_k^-1 = P P^T;
    ``log_det[k] = log|P_k|`` (= -0.5 log|Sigma_k|).
    """
    d = x.shape[1]
    # y[k] = (x - mu_k) @ P_k : einsum maps onto k batched (n,d)x(d,d)
    # matmuls — the MXU shape
    y = xp.einsum("nd,kde->kne", x, prec_chol) \
        - xp.einsum("kd,kde->ke", means, prec_chol)[:, None, :]
    sq = xp.sum(y * y, axis=2)                      # (k, n)
    return (-0.5 * (d * _LOG_2PI + sq) + log_det[:, None]).T


def estep_stats_math(xp, x, w_prior, means, prec_chol, log_det,
                     log_weights) -> GmmStats:
    """E-step responsibilities + M-step sufficient statistics, fused."""
    lp = log_prob_math(xp, x, means, prec_chol, log_det) \
        + log_weights[None, :]                       # (n, k)
    norm = _logsumexp(xp, lp, axis=1)                # (n,)
    resp = xp.exp(lp - norm[:, None]) * w_prior[:, None]
    return GmmStats(
        resp_sum=xp.sum(resp, axis=0),
        mean_sum=resp.T @ x,
        sq_sum=xp.einsum("nk,nd,ne->kde", resp, x, x),
        loglik=xp.sum(w_prior * norm),
        w_sum=xp.sum(w_prior),
    )


def responsibilities_math(xp, x, means, prec_chol, log_det, log_weights):
    """(n, k) posterior responsibilities (the transform path)."""
    lp = log_prob_math(xp, x, means, prec_chol, log_det) \
        + log_weights[None, :]
    norm = _logsumexp(xp, lp, axis=1)
    return xp.exp(lp - norm[:, None])


_jitted_estep = None
_jitted_resp = None


def gmm_estep_device(x, w_prior, means, prec_chol, log_det, log_weights):
    global _jitted_estep
    if _jitted_estep is None:
        import jax
        import jax.numpy as jnp

        _jitted_estep = jax.jit(
            lambda *a: estep_stats_math(jnp, *a))
    return _jitted_estep(x, w_prior, means, prec_chol, log_det, log_weights)


def gmm_responsibilities_device(x, means, prec_chol, log_det, log_weights):
    global _jitted_resp
    if _jitted_resp is None:
        import jax
        import jax.numpy as jnp

        _jitted_resp = jax.jit(
            lambda *a: responsibilities_math(jnp, *a))
    return _jitted_resp(x, means, prec_chol, log_det, log_weights)


def precision_cholesky(covs: np.ndarray, reg: float = 0.0):
    """(prec_chol, log_det) from (k, d, d) covariances — host float64.

    Sigma = L L^T  =>  P = (L^-1)^T (upper-triangular), Sigma^-1 = P P^T,
    log|P| = -sum log diag(L).
    """
    from scipy.linalg import solve_triangular

    k, d, _ = covs.shape
    prec = np.empty_like(covs)
    log_det = np.empty(k)
    eye = np.eye(d)
    for i in range(k):
        cov = covs[i] + reg * eye
        try:
            chol = np.linalg.cholesky(cov)
        except np.linalg.LinAlgError as exc:
            raise ValueError(
                "singular component covariance — data may have "
                "(near-)duplicate rows or too-large k; increase "
                "regularization"
            ) from exc
        prec[i] = solve_triangular(chol, eye, lower=True).T
        log_det[i] = -np.sum(np.log(np.diag(chol)))
    return prec, log_det


def m_step(stats: GmmStats, reg: float):
    """Sufficient statistics -> (weights, means, covs), host float64."""
    nk = np.asarray(stats.resp_sum, dtype=np.float64)
    nk = np.maximum(nk, 1e-32)
    w_sum = float(stats.w_sum)
    weights = nk / w_sum
    means = np.asarray(stats.mean_sum, dtype=np.float64) / nk[:, None]
    sq = np.asarray(stats.sq_sum, dtype=np.float64) / nk[:, None, None]
    covs = sq - np.einsum("kd,ke->kde", means, means)
    d = covs.shape[1]
    covs = covs + reg * np.eye(d)[None, :, :]
    return weights, means, covs


def kmeans_pp_rows(x: np.ndarray, k: int, rng) -> np.ndarray:
    """k-means++ D^2-sampled rows of x (host float64) — spread starting
    means. Random-row starts routinely merge adjacent blobs into one
    component (verified on 3-blob data); D^2 sampling fixes that."""
    n = x.shape[0]
    means = np.empty((k, x.shape[1]))
    means[0] = x[rng.integers(0, n)]
    d2 = np.sum((x - means[0]) ** 2, axis=1)
    for i in range(1, k):
        total = float(d2.sum())
        if total <= 0.0:   # all remaining rows coincide with a center
            means[i] = x[rng.integers(0, n)] + 1e-3 * rng.normal(
                size=x.shape[1])
            continue
        j = int(np.searchsorted(np.cumsum(d2 / total), rng.random()))
        means[i] = x[min(j, n - 1)]
        d2 = np.minimum(d2, np.sum((x - means[i]) ** 2, axis=1))
    return means


def init_from_moments(n: float, s1: np.ndarray, s2: np.ndarray,
                      sample: np.ndarray, k: int, rng):
    """The ONE GMM start recipe shared by every fit path (in-memory,
    streamed, Spark plane): k-means++ rows from ``sample`` as means, the
    pooled diagonal variance (from the sufficient statistics n, sum x,
    sum x^2) as every component's covariance, uniform weights."""
    mu = s1 / n
    var = np.maximum(s2 / n - mu * mu, 1e-6)
    means = kmeans_pp_rows(np.asarray(sample, dtype=np.float64), k, rng)
    covs = np.tile(np.diag(var), (k, 1, 1))
    return np.full(k, 1.0 / k), means, covs


def init_params(x: np.ndarray, w: np.ndarray, k: int, seed: int):
    """Seeded start over an in-memory matrix (weighted moments)."""
    rng = np.random.default_rng(seed)
    w_sum = float(np.sum(w))
    s1 = w @ x
    s2 = w @ (x * x)
    return init_from_moments(w_sum, s1, s2, x, k, rng)
