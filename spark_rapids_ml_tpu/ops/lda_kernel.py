"""LDA device kernels: online / batch variational Bayes.

Spark's ``ml.clustering.LDA`` (absent from the PCA-only reference repo)
ships two optimizers: ``online`` (Hoffman's stochastic variational Bayes,
Spark's default) and ``em`` (graph-based collapsed EM). The TPU mapping
keeps Spark's surface but runs Hoffman-style variational inference for
BOTH: the E-step is a fixed-shape ``lax.while_loop`` of dense matmuls
over a (docs, vocab) count panel —

    φ-normalizer:  n_dk = exp(Ψ(γ)−Ψ(Σγ)) · (c / (θ·βᵀ)) · β

which is exactly two MXU matmuls per inner iteration plus elementwise
digammas on the VPU — and the M-step is one ``(k, vocab)`` update. The
``em`` optimizer is full-corpus variational EM (documented deviation:
same estimator/model surface and comparable topic quality, collapsed
Gibbs-style EM does not map to static-shape SPMD programs).

All shapes static: documents ride in padded panels, empty/padded docs
carry zero counts and contribute nothing to the sufficient statistics.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy.special import digamma, gammaln


def dirichlet_expectation(x: jnp.ndarray) -> jnp.ndarray:
    """E[log θ] for θ ~ Dir(x); rows (last axis) are distributions."""
    return digamma(x) - digamma(x.sum(axis=-1, keepdims=True))


class EStepResult(NamedTuple):
    gamma: jnp.ndarray    # (docs, k) variational doc-topic posteriors
    sstats: jnp.ndarray   # (k, vocab) unnormalized topic sufficient stats


@partial(jax.jit, static_argnames=("n_inner",))
def e_step_kernel(
    counts: jnp.ndarray,        # (docs, vocab) term counts (f32)
    exp_elog_beta: jnp.ndarray,  # (k, vocab) exp E[log β]
    alpha: jnp.ndarray,          # (k,) doc concentration
    key: jax.Array,
    n_inner: int = 100,
    tol: float = 1e-3,
) -> EStepResult:
    """Per-document variational update, vectorized over the panel.

    Spark's online optimizer runs the same fixed-point iteration per
    document (up to 100 steps, mean-change 1e-3); here every document in
    the panel iterates in lockstep inside one ``while_loop`` — docs that
    have individually converged keep iterating harmlessly (the update is
    a fixed point) until the panel's max mean-change drops below tol.
    """
    docs, vocab = counts.shape
    k = exp_elog_beta.shape[0]
    # gamma init ~ Gamma(100, 1/100) like Hoffman's reference impl
    gamma0 = jax.random.gamma(key, 100.0, (docs, k),
                              dtype=counts.dtype) / 100.0

    def cond(state):
        _, change, it = state
        return (change > tol) & (it < n_inner)

    def body(state):
        gamma, _, it = state
        elog_theta = dirichlet_expectation(gamma)
        exp_elog_theta = jnp.exp(elog_theta)              # (docs, k)
        # φ normalizer per (doc, word): Σ_k exp_elog_theta·exp_elog_beta
        phinorm = exp_elog_theta @ exp_elog_beta + 1e-100  # (docs, vocab)
        new_gamma = alpha[None, :] + exp_elog_theta * (
            (counts / phinorm) @ exp_elog_beta.T)
        change = jnp.abs(new_gamma - gamma).mean(axis=1).max()
        return new_gamma, change, it + 1

    # the initial mean-change carry is tied to the data (inf + 0·Σc) so
    # its sharding "varying" annotation matches the loop output when the
    # kernel runs inside a shard_map (a bare replicated constant trips
    # the carry-type check there)
    init_change = jnp.asarray(jnp.inf, counts.dtype) + 0.0 * counts.sum()
    gamma, _, _ = lax.while_loop(
        cond, body, (gamma0, init_change, jnp.asarray(0, jnp.int32)))
    elog_theta = dirichlet_expectation(gamma)
    exp_elog_theta = jnp.exp(elog_theta)
    phinorm = exp_elog_theta @ exp_elog_beta + 1e-100
    sstats = exp_elog_theta.T @ (counts / phinorm)         # (k, vocab)
    sstats = sstats * exp_elog_beta
    return EStepResult(gamma, sstats)


@partial(jax.jit, donate_argnums=(0,), static_argnames=("n_inner",))
def online_update_kernel(
    lam: jnp.ndarray,            # (k, vocab) topic-word variational params
    counts: jnp.ndarray,         # (batch, vocab)
    alpha: jnp.ndarray,          # (k,)
    eta: jnp.ndarray,            # scalar topic concentration
    rho: jnp.ndarray,            # scalar learning rate
    corpus_scale: jnp.ndarray,   # scalar D/|batch|
    key: jax.Array,
    n_inner: int = 100,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One stochastic variational step: E-step on the batch, natural-
    gradient blend into λ. Returns (new λ, batch γ)."""
    exp_elog_beta = jnp.exp(dirichlet_expectation(lam))
    gamma, sstats = e_step_kernel(counts, exp_elog_beta, alpha, key,
                                  n_inner=n_inner)
    lam_hat = eta + corpus_scale * sstats
    return (1.0 - rho) * lam + rho * lam_hat, gamma


@partial(jax.jit, static_argnames=("n_inner",))
def perplexity_bound_kernel(
    counts: jnp.ndarray,
    lam: jnp.ndarray,
    alpha: jnp.ndarray,
    eta: jnp.ndarray,
    key: jax.Array,
    n_inner: int = 100,
) -> jnp.ndarray:
    """Variational lower bound on log p(docs) (the quantity Spark's
    ``logLikelihood`` reports; ``logPerplexity`` = −bound/token count).

    Standard decomposition: E_q[log p(w|θ,β)] + E_q[log p(θ|α) − log q(θ|γ)]
    + E_q[log p(β|η) − log q(β|λ)], with the word term bounded via
    log Σ_k exp(Elogθ + Elogβ) computed stably.
    """
    k, vocab = lam.shape
    exp_elog_beta = jnp.exp(dirichlet_expectation(lam))
    gamma, _ = e_step_kernel(counts, exp_elog_beta, alpha, key,
                             n_inner=n_inner)
    elog_theta = dirichlet_expectation(gamma)          # (docs, k)
    elog_beta = dirichlet_expectation(lam)             # (k, vocab)
    # E[log p(w)] ≥ Σ_dw c_dw · log Σ_k exp(Elogθ_dk + Elogβ_kw)
    m = elog_theta.max(axis=1, keepdims=True)
    word_bound = (counts * (jnp.log(
        jnp.exp(elog_theta - m) @ exp_elog_beta + 1e-100) + m)).sum()
    # θ terms
    theta_bound = (
        ((alpha[None, :] - gamma) * elog_theta).sum()
        + gammaln(gamma).sum() - gammaln(gamma.sum(axis=1)).sum()
        + counts.shape[0] * (gammaln(alpha.sum()) - gammaln(alpha).sum())
    )
    # β terms
    beta_bound = (
        ((eta - lam) * elog_beta).sum()
        + gammaln(lam).sum() - gammaln(lam.sum(axis=1)).sum()
        + k * (gammaln(vocab * eta) - vocab * gammaln(eta))
    )
    return word_bound + theta_bound + beta_bound
