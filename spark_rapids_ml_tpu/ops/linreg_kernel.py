"""Linear regression device kernels: normal-equations via sufficient stats.

Same partial-aggregate shape as PCA's covariance (SURVEY.md §7 step 6:
"LinearRegression ... also 'partial-aggregate + small dense solve'"): the
hot op is the Gram XᵀX on the MXU, the solve is a small dense Cholesky on
the (n+1)-sized system, and the distributed form psums (XᵀX, Xᵀy, Σx, Σy,
n) — rows never leave their shard.

Objective (Spark ``LinearRegression`` with ``solver="normal"``):
    min_w  (1/2n)·Σᵢ (yᵢ − xᵢᵀw − b)² + (λ/2)·||w||²
i.e. ridge on mean-centered data; intercept unpenalized.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax


class LinRegStats(NamedTuple):
    xtx: jnp.ndarray     # (n, n)
    xty: jnp.ndarray     # (n,)
    x_sum: jnp.ndarray   # (n,)
    y_sum: jnp.ndarray   # scalar
    y_sq: jnp.ndarray    # scalar Σy²
    count: jnp.ndarray   # scalar


class LinRegResult(NamedTuple):
    coefficients: jnp.ndarray  # (n,)
    intercept: jnp.ndarray     # scalar


@jax.jit
def linreg_partial_stats_kernel(x, y, mask=None):
    """Module-level jitted stats builder (stable jit cache across fits)."""
    return linreg_partial_stats(x, y, mask)


def linreg_partial_stats(
    x: jnp.ndarray, y: jnp.ndarray, mask: Optional[jnp.ndarray] = None
) -> LinRegStats:
    m = (
        jnp.ones(x.shape[0], dtype=x.dtype) if mask is None else mask.astype(x.dtype)
    )
    xm = x * m[:, None]
    ym = y * m
    xtx = lax.dot_general(
        xm, x, (((0,), (0,)), ((), ())), precision=lax.Precision.HIGHEST
    )
    xty = xm.T @ y
    return LinRegStats(
        xtx=xtx,
        xty=xty,
        x_sum=jnp.sum(xm, axis=0),
        y_sum=jnp.sum(ym),
        y_sq=jnp.sum(ym * y),
        count=jnp.sum(m),
    )


def solve_normal_equations(
    stats: LinRegStats, reg_param: float, fit_intercept: bool
) -> LinRegResult:
    n = stats.count
    if fit_intercept:
        mu_x = stats.x_sum / n
        mu_y = stats.y_sum / n
        # centered moments: Xcᵀ·Xc = XᵀX − n·μₓμₓᵀ ; Xcᵀ·yc = Xᵀy − n·μₓμ_y
        a = stats.xtx / n - jnp.outer(mu_x, mu_x)
        b = stats.xty / n - mu_x * mu_y
    else:
        a = stats.xtx / n
        b = stats.xty / n
    a = a + reg_param * jnp.eye(a.shape[0], dtype=a.dtype)
    # SPD system: Cholesky solve; jitter-free because reg/centered Gram is
    # PSD and XLA's cho_factor handles the tiny-n case on device.
    coef = jax.scipy.linalg.cho_solve(jax.scipy.linalg.cho_factor(a), b)
    if fit_intercept:
        intercept = stats.y_sum / n - jnp.dot(stats.x_sum / n, coef)
    else:
        intercept = jnp.zeros((), dtype=coef.dtype)
    return LinRegResult(coef, intercept)


@partial(jax.jit, static_argnames=("fit_intercept",))
def linreg_fit_kernel(
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
) -> LinRegResult:
    stats = linreg_partial_stats(x, y, mask)
    return solve_normal_equations(stats, reg_param, fit_intercept)


@jax.jit
def linreg_predict_kernel(
    x: jnp.ndarray, coefficients: jnp.ndarray, intercept: jnp.ndarray
) -> jnp.ndarray:
    return x @ coefficients.astype(x.dtype) + intercept.astype(x.dtype)
