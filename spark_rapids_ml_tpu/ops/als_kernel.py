"""ALS device kernels: alternating least squares on padded rating blocks.

Spark's ``ml.recommendation.ALS`` (absent from the reference repo, which
is PCA-only — this extends the same estimator surface to the
recommendation family). Spark solves the per-user / per-item normal
equations with an in-block Cholesky over hash-partitioned rating blocks;
the TPU mapping here replaces the block shuffle with **padded gather
batches**: each user's rated items sit in a fixed-width padded row of an
``(n_users, L)`` index table, so the normal-equation assembly is two
batched MXU contractions

    A_u = Yᵀ_u Y_u + λ·n_u·I      (einsum 'ulk,ulm->ukm')
    b_u = Yᵀ_u r_u                (einsum 'ulk,ul->uk')

followed by one batched ``jnp.linalg.solve`` over ``(n, k, k)`` systems —
all static shapes, one compiled program for the whole ``maxIter`` loop
(``lax.fori_loop``), no per-iteration host round trip.

λ·n_u is Spark's ALS-WR scaling (regParam multiplied by each row's
rating count). Implicit feedback uses the Hu–Koren confidence trick: the
global ``YᵀY`` Gram is one (k×k) matmul per half-sweep, and only the
``(c−1)``-weighted correction rides the padded gather. ``nonnegative=True``
replaces the Cholesky solve with a fixed-sweep projected Gauss–Seidel
(coordinate descent clamped at 0), the same NNLS objective Spark's
pivoted NNLS solver optimizes.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax


class ALSResult(NamedTuple):
    user_factors: jnp.ndarray   # (n_users, rank)
    item_factors: jnp.ndarray   # (n_items, rank)
    train_rmse: jnp.ndarray     # scalar f32 (explicit: rating RMSE;
    #                             implicit: preference-residual RMSE)


def _nnls_gauss_seidel(a: jnp.ndarray, b: jnp.ndarray, x0: jnp.ndarray,
                       sweeps: int = 25) -> jnp.ndarray:
    """Batched projected Gauss–Seidel for ``min ½xᵀAx − bᵀx, x ≥ 0``.

    A: (n, k, k) SPD, b/x0: (n, k). Coordinate updates clamped at zero
    converge to the NNLS optimum for SPD A; ``sweeps`` is fixed so the
    whole solve stays one compiled loop (no data-dependent control flow).
    """
    k = b.shape[-1]
    diag = jnp.diagonal(a, axis1=-2, axis2=-1)          # (n, k)
    safe_diag = jnp.where(diag > 0, diag, 1.0)

    def sweep(_, x):
        def coord(j, x):
            aj = lax.dynamic_slice_in_dim(a, j, 1, axis=1)[:, 0, :]  # (n,k)
            bj = lax.dynamic_slice_in_dim(b, j, 1, axis=1)[:, 0]     # (n,)
            dj = lax.dynamic_slice_in_dim(safe_diag, j, 1, axis=1)[:, 0]
            xj = lax.dynamic_slice_in_dim(x, j, 1, axis=1)[:, 0]
            resid = bj - jnp.einsum("nk,nk->n", aj, x) + dj * xj
            new = jnp.maximum(resid / dj, 0.0)
            return lax.dynamic_update_slice_in_dim(
                x, new[:, None], j, axis=1)

        return lax.fori_loop(0, k, coord, x)

    x0 = jnp.maximum(x0, 0.0)
    return lax.fori_loop(0, sweeps, sweep, x0)


def _solve_side(
    other: jnp.ndarray,          # (n_other, rank) — the fixed factor side
    pad_idx: jnp.ndarray,        # (n, L) int32 indices into `other`
    pad_rating: jnp.ndarray,     # (n, L) f32
    pad_mask: jnp.ndarray,       # (n, L) f32 in {0, 1}
    reg: jnp.ndarray,
    implicit: bool,
    alpha: jnp.ndarray,
    nonneg: bool,
    prev: jnp.ndarray,           # (n, rank) — NNLS warm start
) -> jnp.ndarray:
    rank = other.shape[1]
    y = other[pad_idx]                                   # (n, L, k) gather
    ym = y * pad_mask[..., None]
    n_rated = pad_mask.sum(axis=1)                       # (n,)
    eye = jnp.eye(rank, dtype=other.dtype)
    if implicit:
        # Hu–Koren: confidence c = 1 + α|r| weights EVERY observed entry
        # in A, but the preference target is p = 1 only for r > 0 — a
        # negative rating is a confident zero-preference (Spark's
        # NormalEquation adds b-weight 0 for r ≤ 0, and its ridge count
        # `numExplicits` counts only positive ratings). The dense YᵀY
        # term is one global (k,k) Gram — shared by every row.
        gram = lax.dot_general(
            other, other, (((0,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST)
        conf_m1 = alpha * jnp.abs(pad_rating) * pad_mask  # (n, L)
        pos = (pad_rating > 0).astype(other.dtype) * pad_mask
        a = (gram[None, :, :]
             + jnp.einsum("ulk,ul,ulm->ukm", ym, conf_m1, y,
                          precision=lax.Precision.HIGHEST))
        b = jnp.einsum("ulk,ul->uk", ym, (1.0 + conf_m1) * pos,
                       precision=lax.Precision.HIGHEST)
        n_reg = pos.sum(axis=1)
    else:
        a = jnp.einsum("ulk,ulm->ukm", ym, y,
                       precision=lax.Precision.HIGHEST)
        b = jnp.einsum("ulk,ul->uk", ym, pad_rating,
                       precision=lax.Precision.HIGHEST)
        n_reg = n_rated
    # λ·n I (ALS-WR; implicit counts positives only, like Spark's
    # numExplicits); rows with nothing to fit get a pure-identity system
    # (solution 0) instead of a singular one.
    ridge = jnp.where(n_rated > 0, reg * jnp.maximum(n_reg, 1.0), 1.0)
    a = a + ridge[:, None, None] * eye[None, :, :]
    if nonneg:
        return _nnls_gauss_seidel(a, b, prev)
    return jnp.linalg.solve(a, b[..., None])[..., 0]


@partial(jax.jit, static_argnames=("rank", "max_iter", "implicit",
                                   "nonneg"))
def als_fit_kernel(
    u_items: jnp.ndarray, u_ratings: jnp.ndarray, u_mask: jnp.ndarray,
    i_users: jnp.ndarray, i_ratings: jnp.ndarray, i_mask: jnp.ndarray,
    key: jax.Array,
    *,
    rank: int,
    reg: jnp.ndarray,
    alpha: jnp.ndarray,
    max_iter: int,
    implicit: bool = False,
    nonneg: bool = False,
) -> ALSResult:
    """Whole ALS training run in one compiled program.

    Iteration order matches Spark (items first from random init, then
    users — ``ALS.scala`` trains itemFactors from the initial user block
    each sweep starting with users fixed; we fix items' init and update
    users first per half-sweep, equivalent up to the init convention).
    """
    n_users = u_items.shape[0]
    n_items = i_users.shape[0]
    dtype = u_ratings.dtype
    ku, ki = jax.random.split(key)
    # Signed N(0,1)/√rank init: an all-positive start can trap the
    # alternating solves in a poor local minimum on data with signed
    # factor structure (measured: 25 sweeps stuck at train-RMSE 0.26 on
    # noiseless rank-2 data vs 3e-4 from a signed start). NNLS keeps
    # the |·| so its projected iteration starts feasible.
    u0 = jax.random.normal(ku, (n_users, rank), dtype=dtype)
    v0 = jax.random.normal(ki, (n_items, rank), dtype=dtype)
    if nonneg:
        u0 = jnp.abs(u0)
        v0 = jnp.abs(v0)
    u0 = u0 / jnp.sqrt(jnp.asarray(rank, dtype))
    v0 = v0 / jnp.sqrt(jnp.asarray(rank, dtype))

    def body(_, carry):
        u, v = carry
        u = _solve_side(v, u_items, u_ratings, u_mask, reg,
                        implicit, alpha, nonneg, u)
        v = _solve_side(u, i_users, i_ratings, i_mask, reg,
                        implicit, alpha, nonneg, v)
        return (u, v)

    u, v = lax.fori_loop(0, max_iter, body, (u0, v0))

    # training residual over observed entries, through the user-padded
    # table: pred_ul = u_u · v_{item(u,l)}
    pred = jnp.einsum("uk,ulk->ul", u, v[u_items],
                      precision=lax.Precision.HIGHEST)
    target = ((u_ratings > 0).astype(dtype) if implicit
              else u_ratings)
    sq = ((pred - target) ** 2 * u_mask).sum()
    cnt = jnp.maximum(u_mask.sum(), 1.0)
    return ALSResult(u, v, jnp.sqrt(sq / cnt))


@partial(jax.jit, static_argnames=("num", "tile"))
def topk_scores_kernel(
    queries: jnp.ndarray,        # (q, rank) — factor rows to score
    targets: jnp.ndarray,        # (n, rank) — factor rows to rank
    *,
    num: int,
    tile: int = 8192,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-``num`` targets per query by dot-product score.

    Tiled over targets so the (q × n) score matrix never materializes
    past one (q × tile) panel — recommendForAllUsers at catalog scale on
    one chip. Merge is a running top-k: concat the carried best with the
    new tile's scores and re-``top_k``.
    """
    q, rank = queries.shape
    n = targets.shape[0]
    n_pad = ((n + tile - 1) // tile) * tile
    pad = n_pad - n
    tgt = jnp.pad(targets, ((0, pad), (0, 0)))
    neg = jnp.asarray(-jnp.inf, dtype=queries.dtype)

    best_s = jnp.full((q, num), neg, dtype=queries.dtype)
    best_i = jnp.zeros((q, num), dtype=jnp.int32)

    def body(t, carry):
        bs, bi = carry
        chunk = lax.dynamic_slice_in_dim(tgt, t * tile, tile, axis=0)
        scores = lax.dot_general(
            queries, chunk, (((1,), (1,)), ((), ())),
            precision=lax.Precision.HIGHEST)        # (q, tile)
        idx = t * tile + jnp.arange(tile, dtype=jnp.int32)
        valid = idx < n
        scores = jnp.where(valid[None, :], scores, neg)
        cat_s = jnp.concatenate([bs, scores], axis=1)
        cat_i = jnp.concatenate(
            [bi, jnp.broadcast_to(idx[None, :], (q, tile))], axis=1)
        new_s, pos = lax.top_k(cat_s, num)
        new_i = jnp.take_along_axis(cat_i, pos, axis=1)
        return new_s, new_i

    best_s, best_i = lax.fori_loop(0, n_pad // tile, body,
                                   (best_s, best_i))
    return best_s, best_i


def padded_row_width(max_degree: int) -> int:
    """Padded table width for a max row degree: the next power of two —
    the ONE copy of the rule (the streamed ALS ingestion builds the
    same tables incrementally and must stay in lockstep)."""
    return 1 << (max(1, max_degree) - 1).bit_length()


def build_padded_csr(
    rows: "jnp.ndarray", cols: "jnp.ndarray", vals: "jnp.ndarray",
    n_rows: int, pad_to_pow2: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Host-side: COO triples → fixed-width padded row table.

    Returns (idx, val, mask) each (n_rows, L) with L the max row degree
    (rounded up to a power of two so repeated fits of similarly-skewed
    data reuse compiled programs). Padded slots index 0 with mask 0 —
    their gathers contribute nothing.
    """
    import numpy as np

    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    vals = np.asarray(vals, dtype=np.float64)
    order = np.argsort(rows, kind="stable")
    rows, cols, vals = rows[order], cols[order], vals[order]
    counts = np.bincount(rows, minlength=n_rows)
    max_deg = int(counts.max()) if counts.size else 1
    width = padded_row_width(max_deg) if pad_to_pow2 else max(1, max_deg)
    # values stay float64 on host: the device cast happens once at h2d,
    # so dtype='float64' fits see full-fidelity ratings (an f32 staging
    # copy would round >24-bit-mantissa values before the cast up)
    idx = np.zeros((n_rows, width), dtype=np.int32)
    val = np.zeros((n_rows, width), dtype=np.float64)
    mask = np.zeros((n_rows, width), dtype=np.float64)
    starts = np.concatenate([[0], np.cumsum(counts)])
    # vectorized scatter into the padded table: target flat position is
    # row*width + (rank within row)
    within = np.arange(len(rows)) - starts[rows]
    flat = rows * width + within
    idx.ravel()[flat] = cols
    val.ravel()[flat] = vals
    mask.ravel()[flat] = 1.0
    return idx, val, mask
