"""The end-to-end single-device PCA programs.

One jitted XLA program covers the reference's whole fit pipeline —
mean pass (``RapidsRowMatrix.scala:152-162``), centered Gram
(``:168-202``), eigendecomposition + postprocess
(``rapidsml_jni.cu:338-392``) — with zero host round trips between stages.

``pca_transform_kernel`` enables the batched on-device transform the
reference declared but left disabled ("TODO(rongou): make this faster",
``RapidsPCA.scala:172-190``, native ``dgemm_1b`` at
``rapidsml_jni.cu:260-336``): here it is a single MXU matmul over the whole
batch.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_ml_tpu.obs.xprof import tracked_jit
from spark_rapids_ml_tpu.ops.covariance import column_means, covariance
from spark_rapids_ml_tpu.ops.eigh import pca_from_covariance
from spark_rapids_ml_tpu.ops.quantize import quantize_symmetric


class PCAFitResult(NamedTuple):
    components: jnp.ndarray          # (n_features, k), column j = j-th PC
    explained_variance: jnp.ndarray  # (k,) ratios λᵢ/Σλ
    mean: jnp.ndarray                # (n_features,) column means (or zeros)


@partial(
    tracked_jit,
    static_argnames=("k", "mean_centering", "flip_signs", "solver",
                     "precision"),
)
def pca_fit_kernel(
    x: jnp.ndarray,
    k: int,
    mask: Optional[jnp.ndarray] = None,
    mean_centering: bool = True,
    flip_signs: bool = True,
    solver: str = "eigh",
    precision: Optional[str] = None,
) -> PCAFitResult:
    """Full PCA fit on one device: mean → centered Gram → eigh → top-k.

    Two-pass (explicit centering before the Gram) for parity with the
    reference's semantics; the distributed path offers a one-pass variant.
    ``mask`` marks valid rows when the batch is padded to a static shape.
    ``precision`` is STATIC — part of the jit cache key, so switching the
    Gram precision between fits retraces instead of silently reusing the
    old executable.
    """
    if mean_centering:
        mean = column_means(x, mask)
        cov = covariance(x, mean=mean, mask=mask, precision=precision)
    else:
        mean = jnp.zeros((x.shape[1],), dtype=x.dtype)
        cov = covariance(x, mean=None, mask=mask, precision=precision)
    components, evr = pca_from_covariance(
        cov, k, flip_signs=flip_signs, solver=solver
    )
    return PCAFitResult(components, evr, mean)


def _project(x: jnp.ndarray, components: jnp.ndarray) -> jnp.ndarray:
    """The shared projection body: X @ PC — one MXU matmul.

    Spark PCA semantics: NO mean subtraction at transform time
    (``RapidsPCA.scala:187-189`` multiplies ``pc.transpose`` by the raw row
    vector), so we match that exactly for drop-in parity.
    """
    return lax.dot_general(
        x,
        components.astype(x.dtype),
        (((1,), (0,)), ((), ())),
        precision=lax.Precision.HIGHEST,
    )


@tracked_jit
def pca_transform_kernel(
    x: jnp.ndarray, components: jnp.ndarray
) -> jnp.ndarray:
    """Project a whole batch: X @ PC — one MXU matmul (see ``_project``)."""
    return _project(x, components)


# -- serving variants -------------------------------------------------------
# The pipelined micro-batcher's dispatch step calls these through
# ``PCAModel.serving_transform_program`` so batch N+1's transfer overlaps
# batch N's compute. The *_serve variant donates the staged input buffer:
# the pipeline stages a fresh device buffer per batch and never re-reads
# it, so XLA may retire/reuse its memory the moment the program consumes
# it (aliasing engages only where shape+dtype permit; elsewhere donation
# is a no-op — and the batcher's retry path always re-stages from host
# rows, so a donated buffer is never one a retry still holds). The
# reduced-precision variants are separate tracked signatures per bucket,
# env-gated by the engine (SPARK_RAPIDS_ML_TPU_SERVE_PRECISION) and
# guarded by its offline max-error check + the numerics sentinel; they
# skip donation because the cast consumes the input immediately.

pca_transform_serve = tracked_jit(
    _project, label="pca_transform_serve", donate_argnums=(0,)
)


def _project_bf16(x: jnp.ndarray,
                  components_bf16: jnp.ndarray) -> jnp.ndarray:
    """bf16 operands, f32 accumulation (``preferred_element_type``) —
    the documented reduced-precision GEMM posture of the gram sweep.
    The components arrive PRE-CAST (staged once at program build); only
    the per-batch operand casts here."""
    return lax.dot_general(
        x.astype(jnp.bfloat16), components_bf16, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


pca_transform_bf16 = tracked_jit(_project_bf16, label="pca_transform_bf16")


def _project_int8(x: jnp.ndarray, components_q: jnp.ndarray,
                  components_scale: jnp.ndarray) -> jnp.ndarray:
    """Per-tensor symmetric int8 GEMM with int32 accumulation, f32
    dequantized output (``ops.quantize``). The components arrive
    PRE-QUANTIZED (``quantize_symmetric_host`` at program build) — only
    the batch pays the max/round/clip reduction per call."""
    xq, sx = quantize_symmetric(x)
    acc = lax.dot_general(
        xq, components_q, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return acc.astype(jnp.float32) * (sx * components_scale)


pca_transform_int8 = tracked_jit(_project_int8, label="pca_transform_int8")


# Un-jitted stage bodies for the FUSED whole-pipeline serving programs
# (models._serving.build_fused_pipeline_program): the same arithmetic as
# the jitted serve kernels above, composed with the other stages inside
# ONE tracked_jit so a multi-stage PipelineModel predict is a single XLA
# dispatch. Keyed by precision exactly like the kernel tables.
SERVING_STAGE_BODIES = {
    "native": _project,
    "bf16": _project_bf16,
    "int8": _project_int8,
}
