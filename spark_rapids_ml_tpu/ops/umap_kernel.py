"""UMAP on device: fuzzy graph, spectral init, dense-force optimization.

The reference project's current generation ships a cuML-backed UMAP; this
is the TPU-native construction, re-shaped around what the MXU is good at:

* the kNN graph comes from the exact brute-force kernel
  (``ops/knn_kernel.py``) — no RP-forest;
* per-point bandwidths (ρ, σ) use a VECTORIZED bisection: all n rows
  binary-search σ simultaneously for 32 fixed steps (static control flow,
  one compiled program), versus the reference's per-point loop;
* the embedding optimizer replaces UMAP's sequential SGD + negative
  sampling with FULL-BATCH dense forces: per epoch, pairwise embedding
  distances are one MXU rank-expansion and the net force on every point
  is ``rowsum(W)·Y − W·Y`` — one matmul — where W combines attraction
  (membership-weighted) and repulsion (all-pairs, the negative-sampling
  kernel applied densely). Deterministic, O(n²·dim) on the MXU, the
  regime this dense variant targets is n ≲ 30k (same envelope as the
  dense DBSCAN).

Output geometry matches UMAP's objective (same φ(d) = 1/(1+a·d^{2b})
kernel, same ρ/σ calibration to log₂(k)); per-point coordinates are not
bit-comparable to umap-learn (different optimizer schedule), which tests
account for by checking structure (trustworthiness, cluster separation)
rather than coordinates.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_ml_tpu.ops.knn_kernel import pairwise_sqdist


@partial(jax.jit, static_argnames=("n_iter",))
def smooth_knn_calibration(
    knn_dists: jnp.ndarray, n_iter: int = 32
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(rho[n], sigma[n]): UMAP's smooth-kNN distance calibration.

    ρᵢ = distance to the nearest neighbor (local connectivity 1);
    σᵢ solves Σⱼ exp(−max(dᵢⱼ−ρᵢ,0)/σᵢ) = log₂(k) by bisection, all rows
    at once with a fixed iteration count (jit-friendly).
    """
    k = knn_dists.shape[1]
    rho = knn_dists[:, 0]
    target = jnp.log2(jnp.asarray(float(k), knn_dists.dtype))
    shifted = jnp.maximum(knn_dists - rho[:, None], 0.0)

    def psum(sigma):
        return jnp.sum(jnp.exp(-shifted / sigma[:, None]), axis=1)

    lo = jnp.full_like(rho, 1e-8)
    hi = jnp.full_like(rho, 1e3)

    def body(_, state):
        lo, hi = state
        mid = (lo + hi) / 2.0
        too_big = psum(mid) > target  # sum too large ⇒ shrink sigma
        return jnp.where(too_big, lo, mid), jnp.where(too_big, mid, hi)

    lo, hi = lax.fori_loop(0, n_iter, body, (lo, hi))
    sigma = (lo + hi) / 2.0
    # degenerate rows (all-equal distances): fall back to mean distance
    mean_d = jnp.mean(knn_dists, axis=1)
    return rho, jnp.where(sigma <= 2e-8, jnp.maximum(mean_d, 1e-3), sigma)


def fuzzy_graph(
    knn_dists: jnp.ndarray, knn_idx: jnp.ndarray, n: int
) -> jnp.ndarray:
    """Dense symmetrized membership matrix P (n×n) from kNN distances.

    μᵢⱼ = exp(−max(dᵢⱼ−ρᵢ,0)/σᵢ) scattered into rows, then the fuzzy-set
    union P = μ + μᵀ − μ∘μᵀ (probabilistic t-conorm), diagonal zeroed.
    """
    rho, sigma = smooth_knn_calibration(knn_dists)
    mu = jnp.exp(-jnp.maximum(knn_dists - rho[:, None], 0.0) / sigma[:, None])
    rows = jnp.repeat(jnp.arange(n), knn_dists.shape[1])
    p = jnp.zeros((n, n), dtype=knn_dists.dtype)
    p = p.at[rows, knn_idx.reshape(-1)].max(mu.reshape(-1))
    p = p + p.T - p * p.T
    return p * (1.0 - jnp.eye(n, dtype=p.dtype))


def spectral_init(p: jnp.ndarray, dim: int) -> jnp.ndarray:
    """Embedding init from the normalized graph Laplacian's bottom
    non-trivial eigenvectors (the reference uses the same spectral
    layout); scaled to UMAP's conventional ±10 box."""
    deg = jnp.sum(p, axis=1)
    inv_sqrt = 1.0 / jnp.sqrt(jnp.maximum(deg, 1e-12))
    lap = jnp.eye(p.shape[0], dtype=p.dtype) - inv_sqrt[:, None] * p * inv_sqrt[None, :]
    _, vecs = jnp.linalg.eigh(lap)
    emb = vecs[:, 1 : dim + 1]
    scale = 10.0 / jnp.maximum(jnp.max(jnp.abs(emb)), 1e-12)
    return emb * scale


@partial(jax.jit, static_argnames=("n_epochs",))
def optimize_embedding(
    p: jnp.ndarray,
    emb0: jnp.ndarray,
    a: jnp.ndarray,
    b: jnp.ndarray,
    learning_rate: jnp.ndarray,
    repulsion_strength: jnp.ndarray,
    n_epochs: int,
) -> jnp.ndarray:
    """Full-batch dense-force descent of the UMAP cross-entropy.

    Attraction weight on pair (i,j):  P·(−2ab·d^{2(b−1)})/(1+a·d^{2b});
    repulsion weight: (1−P)·(2γb)/((ε+d²)(1+a·d^{2b})). The net force on
    every point is one matmul: F = diag(rowsum W)·Y − W·Y. Learning rate
    decays linearly to zero (UMAP's schedule); updates are clipped to ±4
    like the reference implementation.
    """
    eps = jnp.asarray(1e-3, emb0.dtype)

    def epoch(i, y):
        d2 = pairwise_sqdist(y, y)
        d2b = jnp.power(jnp.maximum(d2, 1e-12), b)
        denom = 1.0 + a * d2b
        # weight clips mirror umap-learn's ±4 gradient-value clip: for
        # b < 1 the attraction kernel diverges as d→0, and coincident
        # points would otherwise produce inf·0 force terms
        w_att = jnp.clip(
            p * (-2.0 * a * b * d2b / jnp.maximum(d2, 1e-12)) / denom,
            -1e4,
            0.0,
        )
        w_rep = jnp.clip(
            (1.0 - p) * (2.0 * repulsion_strength * b)
            / ((eps + d2) * denom),
            0.0,
            1e4,
        )
        w = w_att + w_rep
        w = w * (1.0 - jnp.eye(y.shape[0], dtype=y.dtype))
        # force_i = Σⱼ wᵢⱼ (yᵢ − yⱼ)  —  one MXU matmul. With w_att ≤ 0
        # and w_rep ≥ 0 this IS the descent direction (−∂loss/∂yᵢ):
        # attraction pulls toward neighbors, repulsion pushes apart.
        force = jnp.sum(w, axis=1)[:, None] * y - w @ y
        alpha = learning_rate * (1.0 - i / n_epochs)
        step = jnp.clip(alpha * force, -4.0, 4.0)
        return y + step

    return lax.fori_loop(0, n_epochs, epoch, emb0)


def symmetric_edge_list(mu, knn_idx, n: int):
    """Host-side sparse fuzzy-set union: the (i<j, P_ij) edge list.

    The dense kernel scatters μ into an n×n matrix and unions with its
    transpose (``fuzzy_graph``); at large n that matrix is the memory
    wall, but the UNION only has support on kNN edges — at most 2·n·k of
    them. NumPy assembly: dedupe directed duplicates by max (the
    ``.at[].max`` semantics), then P = μ_ij + μ_ji − μ_ij·μ_ji per
    undirected pair. Returns (edge_i, edge_j, p) int32/int32/f64 arrays.
    """
    import numpy as np

    mu = np.asarray(mu, dtype=np.float64)
    idx = np.asarray(knn_idx, dtype=np.int64)
    k = mu.shape[1]
    rows = np.repeat(np.arange(n, dtype=np.int64), k)
    cols = idx.reshape(-1)
    vals = mu.reshape(-1)
    keep = rows != cols
    rows, cols, vals = rows[keep], cols[keep], vals[keep]
    # directed key → max over duplicates
    key = rows * n + cols
    order = np.argsort(key, kind="stable")
    key, vals = key[order], vals[order]
    uniq, start = np.unique(key, return_index=True)
    dmax = np.maximum.reduceat(vals, start)
    # pair up (i→j) with (j→i): canonical undirected key
    di, dj = uniq // n, uniq % n
    lo, hi = np.minimum(di, dj), np.maximum(di, dj)
    ukey = lo * n + hi
    forward = di < dj
    uorder = np.argsort(ukey, kind="stable")
    ukey_s = ukey[uorder]
    w_s = dmax[uorder]
    fwd_s = forward[uorder]
    uu, ustart = np.unique(ukey_s, return_index=True)
    # each undirected key appears once or twice; accumulate both directions
    w_ij = np.zeros(len(uu))
    w_ji = np.zeros(len(uu))
    pos = np.searchsorted(uu, ukey_s)
    np.maximum.at(w_ij, pos[fwd_s], w_s[fwd_s])
    np.maximum.at(w_ji, pos[~fwd_s], w_s[~fwd_s])
    p = w_ij + w_ji - w_ij * w_ji
    return (
        (uu // n).astype(np.int32),
        (uu % n).astype(np.int32),
        p,
    )


def pca_init(x: jnp.ndarray, dim: int) -> jnp.ndarray:
    """Embedding init from the top principal components, scaled to the
    conventional ±10 box — umap-learn's ``init='pca'``. The blocked
    large-n path uses this instead of the dense spectral init (whose
    n×n Laplacian eigh is the O(n³) wall the path exists to avoid).
    Reuses the shared covariance/eigh chain so precision and ordering
    conventions live in one place."""
    from spark_rapids_ml_tpu.ops.covariance import column_means, covariance
    from spark_rapids_ml_tpu.ops.eigh import pca_from_covariance

    mean = column_means(x)
    comps, _ = pca_from_covariance(
        covariance(x, mean=mean), dim, flip_signs=False, solver="eigh"
    )
    emb = (x - mean[None, :]) @ comps
    scale = 10.0 / jnp.maximum(jnp.max(jnp.abs(emb)), 1e-12)
    return emb * scale


@partial(jax.jit, static_argnames=("n_epochs", "block_rows"))
def optimize_embedding_blocked(
    edge_i: jnp.ndarray,       # (nnz,) int32, i < j
    edge_j: jnp.ndarray,       # (nnz,) int32
    edge_p: jnp.ndarray,       # (nnz,) membership P_ij
    emb0: jnp.ndarray,         # (n_pad, dim), padded to block_rows multiple
    valid: jnp.ndarray,        # (n_pad,) bool, real rows
    a: jnp.ndarray,
    b: jnp.ndarray,
    learning_rate: jnp.ndarray,
    repulsion_strength: jnp.ndarray,
    n_epochs: int,
    block_rows: int,
) -> jnp.ndarray:
    """``optimize_embedding`` semantics with the n×n force matrix TILED.

    Same weights as the dense kernel, split by support: the all-pairs
    repulsion term (weight (2γb)/((ε+d²)(1+a·d^{2b})), support
    everywhere) streams over row blocks under ``lax.map`` — peak memory
    one (block_rows × n) distance block; the attraction term and the
    −P·repulsion correction (support only on graph edges) ride the edge
    list with two segment-sums. Self-pairs need no masking: their force
    contribution w_ii·(yᵢ−yᵢ) is identically zero in the
    rowsum(W)·Y − W·Y form.
    """
    n = emb0.shape[0]
    assert n % block_rows == 0
    nb = n // block_rows
    dim = emb0.shape[1]
    eps = jnp.asarray(1e-3, emb0.dtype)
    valid_f = valid.astype(emb0.dtype)

    def epoch(i, y):
        def rep_block(yi):
            d2 = pairwise_sqdist(yi, y)
            d2b = jnp.power(jnp.maximum(d2, 1e-12), b)
            w = jnp.clip(
                (2.0 * repulsion_strength * b)
                / ((eps + d2) * (1.0 + a * d2b)),
                0.0,
                1e4,
            ) * valid_f[None, :]
            return jnp.sum(w, axis=1)[:, None] * yi - w @ y
        f_rep = lax.map(
            rep_block, y.reshape(nb, block_rows, dim)
        ).reshape(n, dim)

        yi, yj = y[edge_i], y[edge_j]
        d2 = jnp.sum((yi - yj) ** 2, axis=1)
        d2b = jnp.power(jnp.maximum(d2, 1e-12), b)
        denom = 1.0 + a * d2b
        w_att = jnp.clip(
            edge_p * (-2.0 * a * b * d2b / jnp.maximum(d2, 1e-12)) / denom,
            -1e4,
            0.0,
        )
        # the dense kernel's repulsion carries (1−P); the blocked pass
        # above used 1, so subtract the P·repulsion part exactly on edges
        w_rep_corr = -jnp.clip(
            edge_p * (2.0 * repulsion_strength * b) / ((eps + d2) * denom),
            0.0,
            1e4,
        )
        w_edge = (w_att + w_rep_corr)[:, None] * (yi - yj)
        f_att = (
            jax.ops.segment_sum(w_edge, edge_i, num_segments=n)
            - jax.ops.segment_sum(w_edge, edge_j, num_segments=n)
        )

        force = f_rep + f_att
        alpha = learning_rate * (1.0 - i / n_epochs)
        return y + jnp.clip(alpha * force, -4.0, 4.0)

    return lax.fori_loop(0, n_epochs, epoch, emb0)


def fit_ab(min_dist: float, spread: float = 1.0) -> Tuple[float, float]:
    """Fit the (a, b) of φ(d)=1/(1+a·d^{2b}) to UMAP's target curve
    (1 for d<min_dist, exp(−(d−min_dist)/spread) beyond) — plain NumPy
    grid+refine least squares, no scipy dependency."""
    import numpy as np

    xv = np.linspace(0, spread * 3, 300)
    yv = np.where(
        xv < min_dist, 1.0, np.exp(-(xv - min_dist) / spread)
    )

    def loss(av, bv):
        return ((1.0 / (1.0 + av * xv ** (2 * bv)) - yv) ** 2).sum()

    best = (1.0, 1.0, loss(1.0, 1.0))
    grid_a = np.linspace(0.2, 10.0, 60)
    grid_b = np.linspace(0.3, 2.5, 60)
    for av in grid_a:
        for bv in grid_b:
            cur = loss(av, bv)
            if cur < best[2]:
                best = (av, bv, cur)
    av, bv, _ = best
    for _ in range(3):  # local refine
        da = np.linspace(av * 0.8, av * 1.2, 40)
        db = np.linspace(bv * 0.8, bv * 1.2, 40)
        for ai in da:
            for bi in db:
                cur = loss(ai, bi)
                if cur < best[2]:
                    best = (ai, bi, cur)
        av, bv, _ = best
    return float(av), float(bv)
