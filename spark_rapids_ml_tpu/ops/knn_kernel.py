"""Brute-force k-nearest-neighbors kernels: pairwise distances on the MXU.

Coverage beyond the reference snapshot (which ships only PCA): the current
generation of the reference project grew a brute-force NearestNeighbors
estimator on exactly this shape of kernel (pairwise-distance GEMM + top-k),
so the TPU framework carries one too. The TPU formulation: the n_q×n_items
squared-distance matrix is one rank-expansion ``|q|² − 2·q·itemsᵀ + |x|²``
— a single MXU matmul plus broadcasts that XLA fuses — followed by
``lax.top_k``. No spatial index (KD/ball tree): on the MXU, dense batched
arithmetic beats pointer-chasing structures by orders of magnitude, the
same trade the reference's GPU version makes.

Distance matmuls run at HIGHEST precision: the ``−2qxᵀ`` cancellation
against the norm terms measurably degrades under bf16 splits (same policy
as the k-means distance kernel, ops/kmeans_kernel.py).

Padding contract: callers pad query batches to static bucket shapes (XLA
recompiles per shape otherwise) and pad item rows with ``item_mask=0``;
masked items get +inf distance so they are never selected.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def pairwise_sqdist(
    queries: jnp.ndarray,
    items: jnp.ndarray,
    item_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """(n_q, n_items) squared euclidean distances, masked items → +inf."""
    qn = jnp.sum(queries * queries, axis=1, keepdims=True)
    xn = jnp.sum(items * items, axis=1)[None, :]
    cross = lax.dot_general(
        queries,
        items,
        (((1,), (1,)), ((), ())),
        precision=lax.Precision.HIGHEST,
    )
    d2 = jnp.maximum(qn - 2.0 * cross + xn, 0.0)
    if item_mask is not None:
        d2 = jnp.where(
            item_mask[None, :] > 0, d2, jnp.asarray(jnp.inf, d2.dtype)
        )
    return d2


@partial(jax.jit, static_argnames=("k",))
def knn_kernel(
    queries: jnp.ndarray,
    items: jnp.ndarray,
    k: int,
    item_mask: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k nearest items for each query row.

    Returns ``(distances, indices)`` each (n_q, k): euclidean distances
    ascending and the item-row indices. One compiled program per
    (bucket-shape, k).
    """
    d2 = pairwise_sqdist(queries, items, item_mask)
    neg, idx = lax.top_k(-d2, k)
    return jnp.sqrt(jnp.maximum(-neg, 0.0)), idx


@partial(jax.jit, static_argnames=("k",))
def knn_merge(
    dist_parts: jnp.ndarray, idx_parts: jnp.ndarray, k: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge per-shard top-k candidate lists into the global top-k.

    ``dist_parts``/``idx_parts`` are (n_q, n_candidates) with n_candidates
    = n_shards·k and indices already offset to the global item numbering.
    A second ``top_k`` over the candidate axis gives the exact global
    result — the standard two-level reduction for sharded KNN.
    """
    neg, pos = lax.top_k(-dist_parts, k)
    return -neg, jnp.take_along_axis(idx_parts, pos, axis=1)


@partial(jax.jit, static_argnames=("k",))
def exact_rerank(
    queries: jnp.ndarray,     # (n_q, dim)
    items: jnp.ndarray,       # (n_items, dim) raw rows
    cand_ids: jnp.ndarray,    # (n_q, C) ADC candidates, −1 = padding
    k: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact-distance re-rank of approximate candidates (the
    IndexRefineFlat pattern): gather the C candidate rows per query,
    compute true squared distances, keep the top k. Quantization error
    then only affects which rows REACH the candidate set, not their final
    ordering — the standard recall lift for compact PQ codes."""
    rows = items[jnp.maximum(cand_ids, 0)]               # (Q, C, dim)
    diff = queries[:, None, :].astype(items.dtype) - rows
    d2 = jnp.sum(diff * diff, axis=2)
    d2 = jnp.where(cand_ids < 0, jnp.asarray(jnp.inf, d2.dtype), d2)
    neg, pos = lax.top_k(-d2, k)
    return -neg, jnp.take_along_axis(cand_ids, pos, axis=1)


# -- IVF-Flat approximate search (the reference project's NearestNeighbors
# exposes brute vs ivfflat; the TPU variant keeps everything dense/static:
# coarse quantizer = the k-means kernel, buckets padded to one max size) --


@partial(jax.jit, static_argnames=("k", "nprobe"))
def ivf_search(
    queries: jnp.ndarray,       # (n_q, dim)
    centroids: jnp.ndarray,     # (nlist, dim)
    bucket_items: jnp.ndarray,  # (nlist, max_size, dim), zero-padded
    bucket_ids: jnp.ndarray,    # (nlist, max_size) int32 original row ids
    bucket_mask: jnp.ndarray,   # (nlist, max_size) 1 = real item
    k: int,
    nprobe: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Approximate top-k: search only the ``nprobe`` nearest buckets.

    Returns (sq_distances, indices) each (n_q, k); indices address the
    ORIGINAL item numbering via ``bucket_ids``. Exact when
    nprobe == nlist. All shapes static: the bucket gather is
    (n_q, nprobe·max_size, dim) — bound query batches accordingly.
    """
    cd = pairwise_sqdist(queries, centroids)
    _, probes = lax.top_k(-cd, nprobe)             # (n_q, nprobe)
    cand = bucket_items[probes]                    # (n_q, nprobe, m, dim)
    cand_ids = bucket_ids[probes].reshape(queries.shape[0], -1)
    cand_mask = bucket_mask[probes].reshape(queries.shape[0], -1)
    # padding slots surface as id −1 / distance +inf, never as item 0
    cand_ids = jnp.where(cand_mask > 0, cand_ids, -1)
    n_q, _, m, dim = cand.shape
    cand = cand.reshape(n_q, nprobe * m, dim)
    qn = jnp.sum(queries * queries, axis=1)[:, None]
    xn = jnp.sum(cand * cand, axis=2)
    cross = jnp.einsum(
        "qd,qcd->qc", queries, cand, precision=lax.Precision.HIGHEST
    )
    d2 = jnp.maximum(qn - 2.0 * cross + xn, 0.0)
    d2 = jnp.where(cand_mask > 0, d2, jnp.asarray(jnp.inf, d2.dtype))
    neg, pos = lax.top_k(-d2, k)
    return -neg, jnp.take_along_axis(cand_ids, pos, axis=1)


# -- IVF-PQ approximate search: coarse quantizer + product-quantized
# residuals. The asymmetric-distance (ADC) lookup tables are built as ONE
# MXU contraction per query batch (query residuals x subspace codebooks);
# the candidate scan is then a vectorized gather over int32 codes — the
# compressed representation (M codes/item) is what travels through HBM,
# not raw rows. Approximate even at nprobe == nlist (quantization error),
# matching the reference project's ivfpq contract. --------------------------


@partial(jax.jit, static_argnames=("k", "nprobe"))
def ivfpq_search(
    queries: jnp.ndarray,       # (n_q, dim)
    centroids: jnp.ndarray,     # (nlist, dim) coarse quantizer
    codebooks: jnp.ndarray,     # (M, ksub, dsub) per-subspace codewords
    bucket_codes: jnp.ndarray,  # (M, nlist, max_size) int32 PQ codes
    bucket_ids: jnp.ndarray,    # (nlist, max_size) int32 original row ids
    bucket_mask: jnp.ndarray,   # (nlist, max_size) 1 = real item
    k: int,
    nprobe: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Approximate top-k via ADC over the ``nprobe`` nearest buckets.

    d²(q, item) ≈ Σ_m ‖(q − c_bucket)|_m − codebook_m[code_m]‖², the
    standard residual-PQ estimator. Returns (sq_distances, indices),
    indices in the ORIGINAL item numbering (−1 on padding).

    Layout note: codes are stored subspace-major (M, nlist, max_size) and
    the scan unrolls over the M subspaces, so every gather intermediate is
    (n_q, nprobe, max_size) with the large candidate axis minor — a
    (…, max_size, M) layout instead would pad the tiny M axis to the
    128-lane tile and inflate the scan memory ~8x.
    """
    n_q = queries.shape[0]
    m_sub, ksub, dsub = codebooks.shape
    cd = pairwise_sqdist(queries, centroids)
    _, probes = lax.top_k(-cd, nprobe)                     # (Q, P)
    # per-probe query residuals, split into subspaces
    qr = (queries[:, None, :] - centroids[probes]).reshape(
        n_q, nprobe, m_sub, dsub
    )
    # ADC tables (Q, P, M, ksub): one batched MXU contraction over dsub
    cross = jnp.einsum(
        "qpmd,mjd->qpmj", qr, codebooks, precision=lax.Precision.HIGHEST
    )
    qn = jnp.sum(qr * qr, axis=3)[..., None]
    cn = jnp.sum(codebooks * codebooks, axis=2)[None, None, :, :]
    lut = qn - 2.0 * cross + cn
    # candidate scan, unrolled over subspaces: d2[q,p,c] += lut_m[q,p,code]
    d2 = jnp.zeros(
        (n_q, nprobe, bucket_ids.shape[1]), dtype=queries.dtype
    )
    for m in range(m_sub):
        codes_m = bucket_codes[m][probes]                  # (Q, P, m_sz)
        d2 = d2 + jnp.take_along_axis(lut[:, :, m, :], codes_m, axis=2)
    d2 = d2.reshape(n_q, -1)
    cand_ids = bucket_ids[probes].reshape(n_q, -1)
    cand_mask = bucket_mask[probes].reshape(n_q, -1)
    cand_ids = jnp.where(cand_mask > 0, cand_ids, -1)
    d2 = jnp.where(
        cand_mask > 0, jnp.maximum(d2, 0.0), jnp.asarray(jnp.inf, d2.dtype)
    )
    neg, pos = lax.top_k(-d2, k)
    return -neg, jnp.take_along_axis(cand_ids, pos, axis=1)
