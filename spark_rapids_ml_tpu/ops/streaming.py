"""Streaming (chunked) PCA fit: bounded HBM for unbounded rows.

The reference streams per-partition chunks through the GPU (one JNI GEMM
per partition, ``RapidsRowMatrix.scala:168-202``). The TPU-native analogue:
an on-device sufficient-statistics accumulator ``(Σxxᵀ, Σx, n)`` updated by
a jitted, buffer-donating step per batch — HBM usage is one batch + one
n×n Gram regardless of total rows, and batches stream through while the
MXU stays busy. Finalization (covariance → eigh → postprocess) is the same
program the one-shot kernel uses.

This is also the host data-loader contract: feed fixed-shape batches
(pad + mask the tail — no recompilation), call ``update``, then
``finalize(k)``.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.covariance import covariance_from_stats, partial_gram_stats
from spark_rapids_ml_tpu.ops.eigh import pca_from_covariance
from spark_rapids_ml_tpu.ops.pca_kernel import PCAFitResult


class GramStats(NamedTuple):
    """Device-resident accumulator: Gram (n×n), column sum (n,), row count."""

    gram: jnp.ndarray
    col_sum: jnp.ndarray
    count: jnp.ndarray


def init_stats(n_features: int, dtype=jnp.float32, device=None) -> GramStats:
    zeros = partial(jnp.zeros, dtype=dtype)
    stats = GramStats(
        gram=zeros((n_features, n_features)),
        col_sum=zeros((n_features,)),
        count=jnp.zeros((), dtype=dtype),
    )
    if device is not None:
        stats = jax.device_put(stats, device)
    return stats


@partial(jax.jit, donate_argnums=(0,))
def update_stats(
    stats: GramStats, batch: jnp.ndarray, mask: Optional[jnp.ndarray] = None
) -> GramStats:
    """Accumulate one batch. ``stats`` buffers are DONATED — XLA updates the
    Gram in place (no n×n copy per batch)."""
    g, s, cnt = partial_gram_stats(batch.astype(stats.gram.dtype), mask)
    return GramStats(stats.gram + g, stats.col_sum + s, stats.count + cnt)


@partial(jax.jit, static_argnames=("k", "mean_centering", "flip_signs"))
def finalize_stats(
    stats: GramStats,
    k: int,
    mean_centering: bool = True,
    flip_signs: bool = True,
) -> PCAFitResult:
    cov = covariance_from_stats(
        stats.gram, stats.col_sum, stats.count, mean_centering=mean_centering
    )
    if mean_centering:
        mean = stats.col_sum / stats.count
    else:
        mean = jnp.zeros_like(stats.col_sum)
    components, evr = pca_from_covariance(cov, k, flip_signs=flip_signs)
    return PCAFitResult(components, evr, mean)


class StreamingPCA:
    """Convenience wrapper: ``StreamingPCA(n).partial_fit(b)...finalize(k)``."""

    def __init__(self, n_features: int, dtype=jnp.float32, device=None):
        self._stats = init_stats(n_features, dtype=dtype, device=device)

    def partial_fit(self, batch, mask=None) -> "StreamingPCA":
        self._stats = update_stats(self._stats, batch, mask)
        return self

    @property
    def rows_seen(self) -> float:
        return float(self._stats.count)

    def finalize(self, k: int, mean_centering: bool = True) -> PCAFitResult:
        return jax.block_until_ready(
            finalize_stats(self._stats, k, mean_centering=mean_centering)
        )
