"""Streaming (chunked) PCA fit: bounded HBM for unbounded rows.

The reference streams per-partition chunks through the GPU (one JNI GEMM
per partition, ``RapidsRowMatrix.scala:168-202``). The TPU-native analogue:
an on-device sufficient-statistics accumulator ``(Σxxᵀ, Σx, n)`` updated by
a jitted, buffer-donating step per batch — HBM usage is one batch + one
n×n Gram regardless of total rows, and batches stream through while the
MXU stays busy. Finalization (covariance → eigh → postprocess) is the same
program the one-shot kernel uses.

This is also the host data-loader contract: feed fixed-shape batches
(pad + mask the tail — no recompilation), call ``update``, then
``finalize(k)``.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.obs.xprof import tracked_jit
from spark_rapids_ml_tpu.ops.covariance import covariance_from_stats, partial_gram_stats
from spark_rapids_ml_tpu.ops.eigh import pca_from_covariance
from spark_rapids_ml_tpu.ops.pca_kernel import PCAFitResult


class GramStats(NamedTuple):
    """Device-resident accumulator: Gram (n×n), column sum (n,), row count."""

    gram: jnp.ndarray
    col_sum: jnp.ndarray
    count: jnp.ndarray


def init_stats(n_features: int, dtype=jnp.float32, device=None) -> GramStats:
    zeros = partial(jnp.zeros, dtype=dtype)
    stats = GramStats(
        gram=zeros((n_features, n_features)),
        col_sum=zeros((n_features,)),
        # int32, not the compute dtype: f32 counts lose exactness past 2^24
        # rows (see ops.covariance.row_count)
        count=jnp.zeros((), dtype=jnp.int32),
    )
    if device is not None:
        stats = jax.device_put(stats, device)
    return stats


@partial(tracked_jit, donate_argnums=(0,), static_argnames=("precision",))
def update_stats(
    stats: GramStats, batch: jnp.ndarray, mask: Optional[jnp.ndarray] = None,
    precision: Optional[str] = None,
) -> GramStats:
    """Accumulate one batch. ``stats`` buffers are DONATED — XLA updates the
    Gram in place (no n×n copy per batch). ``precision`` is static (part
    of the jit key) so switching Gram precision retraces."""
    g, s, cnt = partial_gram_stats(batch.astype(stats.gram.dtype), mask,
                                   precision=precision)
    return GramStats(stats.gram + g, stats.col_sum + s, stats.count + cnt)


@partial(
    tracked_jit, static_argnames=("k", "mean_centering", "flip_signs", "solver")
)
def finalize_stats(
    stats: GramStats,
    k: int,
    mean_centering: bool = True,
    flip_signs: bool = True,
    solver: str = "eigh",
) -> PCAFitResult:
    cov = covariance_from_stats(
        stats.gram, stats.col_sum, stats.count, mean_centering=mean_centering
    )
    if mean_centering:
        mean = stats.col_sum / stats.count
    else:
        mean = jnp.zeros_like(stats.col_sum)
    # 'auto' resolves statically (this function is jitted, so the residual
    # gate cannot run here — eager callers wanting the gate use
    # ops.eigh.pca_from_covariance_gated directly, as bench.py and the
    # PCA model's _solve_cov_gated do)
    components, evr = pca_from_covariance(
        cov, k, flip_signs=flip_signs, solver=solver
    )
    return PCAFitResult(components, evr, mean)


@partial(tracked_jit, donate_argnums=(0,),
         static_argnames=("bn", "br", "precision"))
def _update_stats_fused_blocked(stats: GramStats, batch: jnp.ndarray,
                                *, bn: int, br: int,
                                precision: Optional[str] = None
                                ) -> GramStats:
    from spark_rapids_ml_tpu.ops.pallas_gram import fused_centered_gram

    b = batch.astype(stats.gram.dtype)
    zero_mean = jnp.zeros((b.shape[1],), dtype=b.dtype)
    ones = jnp.ones((b.shape[0],), dtype=b.dtype)
    g = fused_centered_gram(b, zero_mean, ones, precision=precision,
                            block_n=bn, block_r=br)
    s = jnp.sum(b, axis=0)
    cnt = jnp.asarray(b.shape[0], dtype=jnp.int32)
    return GramStats(stats.gram + g, stats.col_sum + s, stats.count + cnt)


def update_stats_fused(stats: GramStats, batch: jnp.ndarray,
                       precision: Optional[str] = None) -> GramStats:
    """``update_stats`` with the Gram computed by the Pallas symmetric
    folded-grid kernel (``ops.pallas_gram``) instead of ``lax.dot_general``.
    Requires tile-aligned batches (rows % block_r == 0, an even number of
    block_n feature tiles) and no mask.

    The block shape is read EAGERLY (outside jit) and passed as static
    args — a `gram_block_shape()` call inside the traced body would bake
    the first compile's shape into the jit cache and silently ignore
    later env/bench overrides."""
    from spark_rapids_ml_tpu.ops.pallas_gram import gram_block_shape

    bn, br = gram_block_shape()
    return _update_stats_fused_blocked(stats, batch, bn=bn, br=br,
                                       precision=precision)


def _gram_platform(gram_acc) -> str:
    """Platform of the accumulator's device (seam for dispatch tests)."""
    return next(iter(gram_acc.devices())).platform


def fused_update_applicable(gram_acc, batch, mask) -> bool:
    """Whether the Pallas Gram accumulator handles this (acc, batch, mask).

    The policy (flag override, TPU family, f32, measured-cost heuristic)
    is ``ops.pallas_gram.pallas_gram_preferred`` — shared with the one-shot
    estimator gate. On top of it this path requires exact tile alignment
    and no mask (``update_stats_fused`` does not pad). The env kill switch
    (TPUML_PALLAS_GRAM=0) is honored BEFORE any pallas import so it also
    bypasses a pallas module that fails to import.
    """
    import os

    if os.environ.get("TPUML_PALLAS_GRAM") == "0":
        return False
    if mask is not None or gram_acc.dtype != jnp.float32:
        return False
    try:
        from spark_rapids_ml_tpu.ops.pallas_gram import (
            gram_block_shape,
            pallas_gram_preferred,
        )
    except Exception:  # pallas unavailable on this JAX build
        return False
    bn, br = gram_block_shape()
    rows, n = batch.shape
    if rows % br or n % bn or (n // bn) % 2:
        return False
    try:
        platform = _gram_platform(gram_acc)
    except Exception:  # tracers / committed-less arrays: stay conservative
        return False
    return pallas_gram_preferred(platform, gram_acc.dtype, n)


def update_stats_auto(
    stats: GramStats, batch: jnp.ndarray, mask: Optional[jnp.ndarray] = None,
    precision: Optional[str] = None,
) -> GramStats:
    """The production accumulate step: picks the measured-fastest Gram
    kernel for this backend/shape (see ``fused_update_applicable``)."""
    if fused_update_applicable(stats.gram, batch, mask):
        return update_stats_fused(stats, batch, precision=precision)
    return update_stats(stats, batch, mask, precision=precision)


class StreamingPCA:
    """Convenience wrapper: ``StreamingPCA(n).partial_fit(b)...finalize(k)``."""

    def __init__(self, n_features: int, dtype=jnp.float32, device=None):
        self._stats = init_stats(n_features, dtype=dtype, device=device)

    def partial_fit(self, batch, mask=None) -> "StreamingPCA":
        self._stats = update_stats_auto(self._stats, batch, mask)
        return self

    @property
    def rows_seen(self) -> float:
        return float(self._stats.count)

    def finalize(
        self, k: int, mean_centering: bool = True, solver: str = "eigh"
    ) -> PCAFitResult:
        return jax.block_until_ready(
            finalize_stats(
                self._stats, k, mean_centering=mean_centering, solver=solver
            )
        )


# -- two-pass streaming (exact reference semantics, out-of-core) -----------
#
# The one-pass (Σxxᵀ, Σx, n) accumulator above loses accuracy to f32
# cancellation in G − n·μμᵀ when |μ| ≫ σ. A re-iterable source affords the
# reference's own schedule out-of-core: pass 1 streams (Σx, n) → μ, pass 2
# streams the CENTERED Gram — numerically the two-pass fit kernel, with HBM
# bounded at one batch + one n×n accumulator.

class MeanStats(NamedTuple):
    col_sum: jnp.ndarray
    count: jnp.ndarray


@partial(tracked_jit, donate_argnums=(0,))
def update_mean_stats(
    stats: MeanStats, batch: jnp.ndarray, mask: Optional[jnp.ndarray] = None
) -> MeanStats:
    from spark_rapids_ml_tpu.ops.covariance import _masked, row_count

    b = batch.astype(stats.col_sum.dtype)
    return MeanStats(
        stats.col_sum + jnp.sum(_masked(b, mask), axis=0),
        stats.count + row_count(b, mask),
    )


@partial(tracked_jit, donate_argnums=(0,), static_argnames=("precision",))
def update_centered_gram(
    gram_acc: jnp.ndarray,
    batch: jnp.ndarray,
    mean: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    precision: Optional[str] = None,
) -> jnp.ndarray:
    from spark_rapids_ml_tpu.ops.covariance import _masked, gram

    b = batch.astype(gram_acc.dtype) - mean[None, :]
    return gram_acc + gram(_masked(b, mask), precision=precision)


@partial(tracked_jit, donate_argnums=(0,),
         static_argnames=("bn", "br", "precision"))
def _update_centered_gram_fused_blocked(gram_acc, batch, mean, *, bn, br,
                                        precision=None):
    from spark_rapids_ml_tpu.ops.pallas_gram import fused_centered_gram

    b = batch.astype(gram_acc.dtype)
    ones = jnp.ones((b.shape[0],), dtype=b.dtype)
    return gram_acc + fused_centered_gram(b, mean.astype(b.dtype), ones,
                                          precision=precision,
                                          block_n=bn, block_r=br)


def _update_centered_gram_fused(gram_acc, batch, mean, precision=None):
    from spark_rapids_ml_tpu.ops.pallas_gram import gram_block_shape

    bn, br = gram_block_shape()
    return _update_centered_gram_fused_blocked(gram_acc, batch, mean,
                                               bn=bn, br=br,
                                               precision=precision)


def update_centered_gram_auto(gram_acc, batch, mean, mask=None,
                              precision=None):
    """Centered-Gram accumulate via the measured-fastest kernel: the Pallas
    kernel centers in VMEM (no (X−μ) materialization at all), same policy
    gate as ``update_stats_auto``."""
    if fused_update_applicable(gram_acc, batch, mask):
        return _update_centered_gram_fused(gram_acc, batch, mean,
                                           precision=precision)
    return update_centered_gram(gram_acc, batch, mean, mask,
                                precision=precision)


def stream_covariance(
    source,
    mean_centering: bool = True,
    dtype=jnp.float32,
    device=None,
    precision: Optional[str] = None,
):
    """Stream a ``data.batches.BatchSource`` into (covariance, mean, count).

    Two-pass (center → Gram) when the source is re-iterable and centering is
    requested; one-pass sufficient statistics otherwise. Returns device
    arrays; covariance is normalized by n−1 as everywhere in this package.
    """
    n = source.n_features
    if mean_centering and source.reiterable:
        mstats = MeanStats(
            jnp.zeros((n,), dtype=dtype), jnp.zeros((), dtype=jnp.int32)
        )
        if device is not None:
            mstats = jax.device_put(mstats, device)
        for batch, mask in source.batches():
            mstats = update_mean_stats(mstats, jnp.asarray(batch, dtype=dtype),
                                       None if mask is None else jnp.asarray(mask))
        count = mstats.count
        mean = mstats.col_sum / count
        gram_acc = jnp.zeros((n, n), dtype=dtype)
        if device is not None:
            gram_acc = jax.device_put(gram_acc, device)
        pass2_rows = 0
        for batch, mask in source.batches():
            pass2_rows += batch.shape[0] if mask is None else int(mask.sum())
            gram_acc = update_centered_gram_auto(
                gram_acc, jnp.asarray(batch, dtype=dtype), mean,
                None if mask is None else jnp.asarray(mask),
                precision=precision)
        if pass2_rows != int(count):
            # A "re-iterable" factory that hands back a partially-consumed
            # iterator would silently zero the Gram; fail instead.
            raise RuntimeError(
                f"two-pass streaming saw {int(count)} rows on pass 1 but "
                f"{pass2_rows} on pass 2; the source factory must return a "
                f"FRESH iterator on every call"
            )
        denom = jnp.maximum(count - 1, 1)
        return gram_acc / denom, mean, count

    stats = init_stats(n, dtype=dtype, device=device)
    for batch, mask in source.batches():
        stats = update_stats_auto(stats, jnp.asarray(batch, dtype=dtype),
                                  None if mask is None else jnp.asarray(mask),
                                  precision=precision)
    cov = covariance_from_stats(
        stats.gram, stats.col_sum, stats.count, mean_centering=mean_centering
    )
    if mean_centering:
        mean = stats.col_sum / stats.count
    else:
        mean = jnp.zeros_like(stats.col_sum)
    return cov, mean, stats.count


