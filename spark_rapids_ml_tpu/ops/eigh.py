"""Eigendecomposition + PCA postprocessing, fused into one XLA program.

Replaces the reference's driver-GPU ``calSVD`` native kernel
(``/root/reference/native/src/rapidsml_jni.cu:338-392``): RAFT ``eigDC``
(cuSolver syevd) → colReverse/rowReverse → S←√S → Thrust signFlip. Here the
whole chain — ``eigh``, descending reorder, sign-flip, explained-variance —
is one jitted program; XLA fuses the postprocessing into a few vector ops.

Semantic corrections vs the reference (SURVEY.md §3.6):
* explained variance is λ/Σλ (Spark CPU semantics), not √λ/Σ√λ
  (the reference GPU path's known inconsistency,
  ``RapidsRowMatrix.scala:101-102`` + ``rapidsml_jni.cu:377``);
* the sign-flip convention (each component's max-|·| coordinate positive,
  ``rapidsml_jni.cu:37-64``) is kept — it makes results deterministic and
  matches sklearn.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def eigh_descending(cov: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric eigendecomposition with eigenvalues in descending order.

    ``jnp.linalg.eigh`` returns ascending order; the reference reverses with
    ``colReverse``/``rowReverse`` (``rapidsml_jni.cu:374-375``) — here it is a
    negative-stride gather XLA folds away.
    """
    evals, evecs = jnp.linalg.eigh(cov)
    return evals[::-1], evecs[:, ::-1]


def sign_flip(evecs: jnp.ndarray) -> jnp.ndarray:
    """Flip each column's sign so its max-|·| entry is positive.

    Vectorized equivalent of the reference's Thrust ``signFlip`` kernel
    (``rapidsml_jni.cu:37-64``): one argmax + gather + broadcast multiply,
    no per-column loop.
    """
    idx = jnp.argmax(jnp.abs(evecs), axis=0)
    picked = evecs[idx, jnp.arange(evecs.shape[1])]
    signs = jnp.where(picked < 0, -1.0, 1.0).astype(evecs.dtype)
    return evecs * signs[None, :]


def explained_variance_ratio(evals: jnp.ndarray) -> jnp.ndarray:
    """λᵢ/Σλ over all eigenvalues (clamped at 0 for tiny negatives).

    Denominator is the sum over ALL eigenvalues; truncation to k happens
    after, as in ``RapidsRowMatrix.scala:101-109``.
    """
    lam = jnp.maximum(evals, 0.0)
    total = jnp.sum(lam)
    return lam / jnp.where(total > 0, total, 1.0)


def eigh_postprocess_host(evals, evecs):
    """NumPy version of the descending-reorder + sign-flip chain — same
    semantics as the XLA chain above, shared by every host fallback (PCA,
    TruncatedSVD) so the conventions can't drift. Takes LAPACK
    ascending-order output; returns (evals_descending, evecs_flipped)."""
    import numpy as np

    evals = np.asarray(evals)[::-1]
    evecs = np.asarray(evecs)[:, ::-1]
    idx = np.argmax(np.abs(evecs), axis=0)
    signs = np.where(evecs[idx, np.arange(evecs.shape[1])] < 0, -1.0, 1.0)
    return evals, evecs * signs[None, :]


def pca_postprocess_host(evals, evecs, k: int):
    """Host postprocessing for PCA: reorder/flip + λ/Σλ + top-k."""
    import numpy as np

    evals, evecs = eigh_postprocess_host(evals, evecs)
    lam = np.maximum(evals, 0.0)
    total = lam.sum()
    evr = lam / (total if total > 0 else 1.0)
    return evecs[:, :k], evr[:k]


def resolve_auto_solver(n: int, k: int) -> str:
    """Static solver choice for ``solver='auto'``: randomized top-k when
    k ≪ n on a covariance big enough for the O(n³) eigh to matter
    (measured ~1.4s at n=4096 on a v5e vs 0.37s randomized), dense eigh
    otherwise. Shape-only, so it is jit-safe (resolves at trace time)."""
    return "randomized" if (n >= 1024 and k * 8 <= n) else "eigh"


def pca_from_covariance(
    cov: jnp.ndarray, k: int, flip_signs: bool = True, solver: str = "eigh"
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(components[n,k], explained_variance_ratio[k]) from covariance.

    ``k`` is static (compile-time), matching the top-k truncation
    ``Arrays.copyOfRange(u.data, 0, n*k)`` (``RapidsRowMatrix.scala:104-109``).

    ``solver``:
    * ``"eigh"`` (default) — dense full-spectrum factorization, exact
      per-vector parity with the LAPACK/Spark oracle. O(n³), and the fixed
      cost that dominates small-row fits (measured 0.9s at n=4096 on a
      v5e).
    * ``"randomized"`` — Halko-Martinsson-Tropp subspace iteration for the
      top k only (``ops.randomized``): a chain of tall-skinny MXU matmuls,
      O(n²·k), ~100× faster at n=4096 k=256. The λ/Σλ denominator stays
      EXACT via trace(cov). Per-vector accuracy depends on spectral gaps —
      see the accuracy caveat in ``ops/randomized.py``; use on decaying
      spectra (the regime where PCA is meaningful).
    * ``"auto"`` — ``resolve_auto_solver`` picks between them by shape.
      Under jit the choice is static and unverified; eager callers should
      prefer ``pca_from_covariance_gated``, which adds the residual check.
    """
    if solver == "auto":
        solver = resolve_auto_solver(cov.shape[0], k)
    if solver == "randomized":
        from spark_rapids_ml_tpu.ops.randomized import (
            randomized_pca_from_covariance,
        )

        return randomized_pca_from_covariance(
            cov, k, jnp.trace(cov), flip_signs=flip_signs
        )
    if solver != "eigh":
        raise ValueError(
            f"solver={solver!r}: expected 'eigh', 'randomized', or 'auto'"
        )
    evals, evecs = eigh_descending(cov)
    if flip_signs:
        evecs = sign_flip(evecs)
    evr = explained_variance_ratio(evals)
    return evecs[:, :k], evr[:k]


def pca_from_covariance_gated(
    cov: jnp.ndarray,
    k: int,
    flip_signs: bool = True,
    solver: str = "auto",
    residual_rtol: float = 0.05,
) -> Tuple[jnp.ndarray, jnp.ndarray, str]:
    """``pca_from_covariance`` with the eigh-vs-randomized residual gate.

    Host-driven (one scalar D2H read), so only for eager call sites — the
    model fit paths and ``finalize_stats``, not jitted kernels. When the
    shape heuristic picks randomized, the eigenpair residual
    ``‖Cov·V − V·Λ‖_F / (√k · mean(λ))`` is checked on device; if it
    exceeds ``residual_rtol`` (catastrophic non-convergence — a slow-decay
    tail the subspace iteration didn't capture), the dense eigh result is
    computed and returned instead. Sub-threshold wobble on near-degenerate
    spectra is rotation within an eigenvalue cluster — a legitimate PCA
    basis capturing the same variance — and intentionally passes.

    Returns ``(components, evr, solver_used)``.
    """
    import jax

    if solver == "auto":
        solver = resolve_auto_solver(cov.shape[0], k)
    if isinstance(cov, jax.core.Tracer):
        # under jit the gate's D2H read is impossible; take the static
        # choice ungated (same behavior as pca_from_covariance('auto'))
        pc, evr = pca_from_covariance(cov, k, flip_signs, solver)
        return pc, evr, solver
    if solver != "randomized":
        pc, evr = pca_from_covariance(cov, k, flip_signs, solver)
        return pc, evr, solver
    pc, evr = pca_from_covariance(cov, k, flip_signs, "randomized")
    trace = jnp.trace(cov)
    lam = evr * trace
    resid = jnp.linalg.norm(cov @ pc - pc * lam[None, :])
    scale = jnp.sqrt(jnp.asarray(k, cov.dtype)) * jnp.maximum(
        jnp.mean(lam), jnp.finfo(cov.dtype).tiny
    )
    # inverted comparison so NaN/inf residuals (overflowed solve) FAIL the
    # gate rather than slipping through a `NaN > rtol` == False
    if not (float(resid / scale) <= residual_rtol):
        pc, evr = pca_from_covariance(cov, k, flip_signs, "eigh")
        return pc, evr, "eigh(gated)"
    return pc, evr, "randomized"
