"""Randomized top-k eigensolver (subspace iteration) for large covariances.

The reference's eigensolve is a dense full-spectrum ``syevd`` on the driver
GPU (``/root/reference/native/src/rapidsml_jni.cu:338-392``), which caps the
feature dimension at whatever one device can factorize. For PCA only the top
k eigenpairs are needed; randomized subspace iteration (Halko-Martinsson-
Tropp) gets them with a handful of tall-skinny matmuls — MXU-friendly,
O(n²·l) instead of O(n³), and the only primitive it needs from the matrix is
``v ↦ Cov·v``. That matvec abstraction is what lets the same solver run on a
replicated covariance (here) or on a feature-sharded covariance where no
device ever holds the full n×n (``parallel/feature_sharded.py``) — the
"feature-dimension scaling" answer sketched in SURVEY.md §5.

All iteration counts are static, so the whole solve jit-compiles into one
XLA program (QR + matmul chain) with no host round trips.

Accuracy caveat (inherent to randomized methods, same as sklearn's
``svd_solver='randomized'``): individual eigenvectors converge at a rate set
by the gaps between consecutive eigenvalues. On decaying spectra — the
regime where PCA is meaningful — a few power iterations reach oracle
accuracy (see tests/test_feature_sharded.py). On near-degenerate spectra
(e.g. isotropic noise) the top-k SUBSPACE is still captured but individual
vectors within a degenerate cluster are arbitrary rotations of each other;
use the dense ``eigh`` solver when exact per-vector parity on gapless
spectra matters.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from spark_rapids_ml_tpu.ops.eigh import eigh_descending, sign_flip


def _orthonormalize(y: jnp.ndarray) -> jnp.ndarray:
    """Orthonormal basis of range(Y) via eigh-based whitening.

    ``jnp.linalg.qr`` lowers to a blocked Householder loop that compiles
    pathologically slowly on the TPU backend (minutes-scale at 4096×266,
    measured via a hung finalize); the Gram-eigh route is three MXU matmuls
    plus an l×l eigendecomposition (QDWH — the same primitive the dense
    solver already compiles): B = YᵀY, B = VΛVᵀ, Q = Y·V·Λ^(−1/2).
    Like CholeskyQR this squares the condition number, so callers
    re-orthonormalize EVERY iteration (which subspace iteration does
    anyway) and tiny Λ entries are clamped. Clamped directions become
    exactly-zero columns and STAY zero through subsequent matvecs (unlike
    Householder QR, which would fill them with arbitrary orthonormal
    vectors): Rayleigh-Ritz then assigns them eigenvalue 0 and they sort
    last, so they only surface as zero component rows when the requested k
    exceeds rank(Cov) — preferable to NaNs poisoning the whole basis.
    """
    b = y.T @ y
    b = (b + b.T) / 2
    evals, vecs = jnp.linalg.eigh(b)
    eps = jnp.asarray(jnp.finfo(y.dtype).eps, y.dtype)
    floor = jnp.maximum(evals[-1], 0.0) * eps * y.shape[0]
    inv_sqrt = jnp.where(evals > floor, 1.0 / jnp.sqrt(jnp.maximum(evals, floor)), 0.0)
    return y @ (vecs * inv_sqrt[None, :])


def subspace_iteration(
    matvec: Callable[[jnp.ndarray], jnp.ndarray],
    n: int,
    l: int,
    n_iter: int,
    key: jax.Array,
    dtype,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-l eigenpairs of a symmetric PSD operator given only its matvec.

    ``matvec`` maps an (n, l) block to Cov @ block (full rows, whatever the
    caller's covariance layout). Returns (evals[l] descending, evecs[n, l]).
    Re-orthonormalization every step keeps the power iteration stable at
    f32; the Rayleigh-Ritz projection B = QᵀCovQ recovers the eigenvalues.
    """
    # Full f32 matmuls throughout: the iteration's convergence and the
    # Rayleigh-Ritz eigenvalues are sensitive to the single-pass-bf16 TPU
    # default, and these tall-skinny (n×l) products are a rounding error
    # next to the O(n²·rows) Gram that produced the covariance.
    with jax.default_matmul_precision("highest"):
        omega = jax.random.normal(key, (n, l), dtype=dtype)
        y = matvec(omega)
        for _ in range(max(n_iter, 0)):
            q = _orthonormalize(y)
            y = matvec(q)
        q = _orthonormalize(y)
        b = q.T @ matvec(q)
        b = (b + b.T) / 2  # exact symmetry for eigh
        evals, vecs = eigh_descending(b)
        return evals, q @ vecs


def topk_from_subspace(
    evals: jnp.ndarray,
    evecs: jnp.ndarray,
    k: int,
    total_variance: jnp.ndarray,
    flip_signs: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Shared postprocessing for randomized solves: sign-flip, top-k
    truncation, λ/Σλ with clamped Rayleigh-Ritz eigenvalues.

    ``total_variance`` (= trace(Cov)) is passed in rather than derived so the
    λ/Σλ denominator stays EXACT while the λᵢ are estimates — sharded
    callers compute the trace with a cheap collective. One implementation so
    the replicated and sharded paths cannot drift.
    """
    if flip_signs:
        evecs = sign_flip(evecs)
    lam = jnp.maximum(evals[:k], 0.0)
    evr = lam / jnp.where(total_variance > 0, total_variance, 1.0)
    return evecs[:, :k], evr


def randomized_pca_from_covariance(
    cov: jnp.ndarray,
    k: int,
    total_variance: jnp.ndarray,
    oversample: int = 10,
    n_iter: int = 4,
    seed: int = 0,
    flip_signs: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(components[n, k], explained_variance_ratio[k]) from a replicated
    covariance, without factorizing the full spectrum."""
    n = cov.shape[0]
    l = min(k + oversample, n)
    evals, evecs = subspace_iteration(
        lambda v: cov @ v, n, l, n_iter, jax.random.PRNGKey(seed), cov.dtype
    )
    return topk_from_subspace(evals, evecs, k, total_variance, flip_signs)
