"""LogisticRegression device kernels: Newton-IRLS in one compiled program.

Third-algorithm coverage beyond the reference (whose roadmap stops at PCA;
KMeans/LinearRegression are BASELINE.md config 5). Binary logistic
regression with L2, in Spark ML's objective convention:

    min_w  (1/n) Σ logloss(yᵢ, σ(xᵢ·w + b)) + (λ/2)·||w||²   (intercept
    unpenalized, like Spark's ``LogisticRegression`` with
    ``elasticNetParam=0``)

solved by Newton-IRLS — each iteration is two MXU matmuls (the logits
``X·w`` and the weighted Hessian ``Xᵀdiag(σ')X``) plus an (n+1)² Cholesky
solve, the same "big matmul + small dense solve" shape as every other
algorithm here. The iteration is a ``lax.while_loop`` compiled into the
program; masked (padding) rows contribute nothing to loss, gradient, or
Hessian.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax


class LogRegResult(NamedTuple):
    coefficients: jnp.ndarray   # (n_features,)
    intercept: jnp.ndarray      # scalar
    n_iter: jnp.ndarray         # scalar int
    converged: jnp.ndarray      # scalar bool


def _grad_hess(w, x, y, valid, reg_param, fit_intercept, reduce_fn):
    """(gradient, Hessian) of the Spark-convention objective at w.

    ``w`` is (n+1,): coefficients ++ intercept slot (zero-pinned when
    ``fit_intercept`` is False). ``reduce_fn`` combines the per-shard
    (Xᵀr, XᵀWX, Σr, ΣW, n) partials — identity on one device, ``psum``
    over the mesh in the distributed form.
    """
    n_feat = x.shape[1]
    coef, b = w[:n_feat], w[n_feat]
    z = x @ coef + b
    p = jax.nn.sigmoid(z)
    r = (p - y) * valid                 # residual, masked
    s = p * (1.0 - p) * valid           # IRLS weights, masked
    gx = lax.dot_general(x, r, (((0,), (0,)), ((), ())),
                         precision=lax.Precision.HIGHEST)
    # Hessian core: Xᵀ diag(s) X — one MXU matmul of the s-scaled rows
    xs = x * s[:, None]
    hxx = lax.dot_general(x, xs, (((0,), (0,)), ((), ())),
                          precision=lax.Precision.HIGHEST)
    hxb = jnp.sum(xs, axis=0)
    stats = reduce_fn((gx, hxx, hxb, jnp.sum(r), jnp.sum(s),
                       jnp.sum(valid)))
    gx, hxx, hxb, rsum, ssum, cnt = stats
    inv_n = 1.0 / jnp.maximum(cnt, 1.0)

    g = jnp.zeros_like(w)
    g = g.at[:n_feat].set(gx * inv_n + reg_param * coef)
    h = jnp.zeros((n_feat + 1, n_feat + 1), dtype=w.dtype)
    h = h.at[:n_feat, :n_feat].set(
        hxx * inv_n + reg_param * jnp.eye(n_feat, dtype=w.dtype)
    )
    if fit_intercept:
        g = g.at[n_feat].set(rsum * inv_n)
        h = h.at[:n_feat, n_feat].set(hxb * inv_n)
        h = h.at[n_feat, :n_feat].set(hxb * inv_n)
        h = h.at[n_feat, n_feat].set(ssum * inv_n)
    else:
        # pin the intercept slot: unit diagonal, zero gradient
        h = h.at[n_feat, n_feat].set(1.0)
    return g, h


def newton_iterations(
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    reg_param: float,
    fit_intercept: bool,
    max_iter: int,
    tol: float,
    reduce_fn=lambda t: t,
) -> LogRegResult:
    dtype = x.dtype
    valid = (
        jnp.ones(x.shape[0], dtype=dtype) if mask is None
        else mask.astype(dtype)
    )
    n_feat = x.shape[1]
    w0 = jnp.zeros((n_feat + 1,), dtype=dtype)

    def step(state):
        w, _, it, _ = state
        g, h = _grad_hess(w, x, y, valid, reg_param, fit_intercept, reduce_fn)
        # Damped-free Newton with a Cholesky solve; the ridge term (or the
        # pinned intercept slot) keeps H positive definite.
        delta = jax.scipy.linalg.cho_solve(jax.scipy.linalg.cho_factor(h), g)
        w_new = w - delta
        moved = jnp.max(jnp.abs(delta))
        return w_new, moved, it + 1, moved <= tol

    def cond(state):
        _, _, it, done = state
        return jnp.logical_and(it < max_iter, jnp.logical_not(done))

    init = (w0, jnp.asarray(jnp.inf, dtype=dtype),
            jnp.asarray(0, dtype=jnp.int32), jnp.asarray(False))
    w, _, n_iter, converged = lax.while_loop(cond, step, init)
    return LogRegResult(w[:n_feat], w[n_feat], n_iter, converged)


@partial(jax.jit, static_argnames=("fit_intercept", "max_iter"))
def logreg_fit_kernel(
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
    max_iter: int = 100,
    tol: float = 1e-8,
) -> LogRegResult:
    return newton_iterations(
        x, y, mask, reg_param, fit_intercept, max_iter, tol
    )


@partial(jax.jit, donate_argnums=(0,))
def update_logreg_stats(carry, batch_z, w, b, mask=None):
    """Out-of-core Newton building block: fold one ``[X | y]`` batch's
    (Xᵀr, XᵀWX, Xᵀs, Σr, Σs, n) partials at the current (w, b) into a
    donated accumulator. One streamed pass with this per batch = one
    Newton gradient/Hessian evaluation over the full dataset."""
    gx, hxx, hxb, rsum, ssum, cnt = carry
    x = batch_z[:, :-1].astype(gx.dtype)
    y = batch_z[:, -1].astype(gx.dtype)
    valid = (
        jnp.ones(x.shape[0], dtype=x.dtype) if mask is None
        else mask.astype(x.dtype)
    )
    p = jax.nn.sigmoid(x @ w + b)
    r = (p - y) * valid
    s = p * (1.0 - p) * valid
    xs = x * s[:, None]
    return (
        gx + lax.dot_general(x, r, (((0,), (0,)), ((), ())),
                             precision=lax.Precision.HIGHEST),
        hxx + lax.dot_general(x, xs, (((0,), (0,)), ((), ())),
                              precision=lax.Precision.HIGHEST),
        hxb + jnp.sum(xs, axis=0),
        rsum + jnp.sum(r),
        ssum + jnp.sum(s),
        cnt + jnp.sum(valid),
    )


@jax.jit
def logreg_predict_kernel(x, coefficients, intercept):
    """Class probabilities σ(X·w + b) — one batched MXU matmul (the
    enabled-batch-transform posture shared with PCAModel.transform)."""
    return jax.nn.sigmoid(x @ coefficients + intercept)
