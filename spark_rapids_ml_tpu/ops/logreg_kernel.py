"""LogisticRegression device kernels: Newton-IRLS in one compiled program.

Third-algorithm coverage beyond the reference (whose roadmap stops at PCA;
KMeans/LinearRegression are BASELINE.md config 5). Binary logistic
regression with L2, in Spark ML's objective convention:

    min_w  (1/n) Σ logloss(yᵢ, σ(xᵢ·w + b)) + (λ/2)·||w||²   (intercept
    unpenalized, like Spark's ``LogisticRegression`` with
    ``elasticNetParam=0``)

solved by Newton-IRLS — each iteration is two MXU matmuls (the logits
``X·w`` and the weighted Hessian ``Xᵀdiag(σ')X``) plus an (n+1)² Cholesky
solve, the same "big matmul + small dense solve" shape as every other
algorithm here. The iteration is a ``lax.while_loop`` compiled into the
program; masked (padding) rows contribute nothing to loss, gradient, or
Hessian.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_ml_tpu.obs.xprof import tracked_jit


class LogRegResult(NamedTuple):
    coefficients: jnp.ndarray   # (n_features,)
    intercept: jnp.ndarray      # scalar
    n_iter: jnp.ndarray         # scalar int
    converged: jnp.ndarray      # scalar bool


def _grad_hess(w, x, y, valid, reg_param, fit_intercept, reduce_fn):
    """(gradient, Hessian) of the Spark-convention objective at w.

    ``w`` is (n+1,): coefficients ++ intercept slot (zero-pinned when
    ``fit_intercept`` is False). ``reduce_fn`` combines the per-shard
    (Xᵀr, XᵀWX, Σr, ΣW, n) partials — identity on one device, ``psum``
    over the mesh in the distributed form.
    """
    n_feat = x.shape[1]
    coef, b = w[:n_feat], w[n_feat]
    z = x @ coef + b
    p = jax.nn.sigmoid(z)
    r = (p - y) * valid                 # residual, masked
    s = p * (1.0 - p) * valid           # IRLS weights, masked
    gx = lax.dot_general(x, r, (((0,), (0,)), ((), ())),
                         precision=lax.Precision.HIGHEST)
    # Hessian core: Xᵀ diag(s) X — one MXU matmul of the s-scaled rows
    xs = x * s[:, None]
    hxx = lax.dot_general(x, xs, (((0,), (0,)), ((), ())),
                          precision=lax.Precision.HIGHEST)
    hxb = jnp.sum(xs, axis=0)
    stats = reduce_fn((gx, hxx, hxb, jnp.sum(r), jnp.sum(s),
                       jnp.sum(valid)))
    gx, hxx, hxb, rsum, ssum, cnt = stats
    inv_n = 1.0 / jnp.maximum(cnt, 1.0)

    g = jnp.zeros_like(w)
    g = g.at[:n_feat].set(gx * inv_n + reg_param * coef)
    h = jnp.zeros((n_feat + 1, n_feat + 1), dtype=w.dtype)
    h = h.at[:n_feat, :n_feat].set(
        hxx * inv_n + reg_param * jnp.eye(n_feat, dtype=w.dtype)
    )
    if fit_intercept:
        g = g.at[n_feat].set(rsum * inv_n)
        h = h.at[:n_feat, n_feat].set(hxb * inv_n)
        h = h.at[n_feat, :n_feat].set(hxb * inv_n)
        h = h.at[n_feat, n_feat].set(ssum * inv_n)
    else:
        # pin the intercept slot: unit diagonal, zero gradient
        h = h.at[n_feat, n_feat].set(1.0)
    return g, h


def newton_iterations(
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    reg_param: float,
    fit_intercept: bool,
    max_iter: int,
    tol: float,
    reduce_fn=lambda t: t,
) -> LogRegResult:
    dtype = x.dtype
    valid = (
        jnp.ones(x.shape[0], dtype=dtype) if mask is None
        else mask.astype(dtype)
    )
    n_feat = x.shape[1]
    w0 = jnp.zeros((n_feat + 1,), dtype=dtype)

    def step(state):
        w, _, it, _ = state
        g, h = _grad_hess(w, x, y, valid, reg_param, fit_intercept, reduce_fn)
        # Damped-free Newton with a Cholesky solve; the ridge term (or the
        # pinned intercept slot) keeps H positive definite.
        delta = jax.scipy.linalg.cho_solve(jax.scipy.linalg.cho_factor(h), g)
        w_new = w - delta
        moved = jnp.max(jnp.abs(delta))
        return w_new, moved, it + 1, moved <= tol

    def cond(state):
        _, _, it, done = state
        return jnp.logical_and(it < max_iter, jnp.logical_not(done))

    init = (w0, jnp.asarray(jnp.inf, dtype=dtype),
            jnp.asarray(0, dtype=jnp.int32), jnp.asarray(False))
    w, _, n_iter, converged = lax.while_loop(cond, step, init)
    return LogRegResult(w[:n_feat], w[n_feat], n_iter, converged)


@partial(jax.jit, static_argnames=("fit_intercept", "max_iter"))
def logreg_fit_kernel(
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
    max_iter: int = 100,
    tol: float = 1e-8,
) -> LogRegResult:
    return newton_iterations(
        x, y, mask, reg_param, fit_intercept, max_iter, tol
    )


@partial(jax.jit, donate_argnums=(0,))
def update_logreg_stats(carry, batch_z, w, b, mask=None):
    """Out-of-core Newton building block: fold one ``[X | y]`` batch's
    (Xᵀr, XᵀWX, Xᵀs, Σr, Σs, n) partials at the current (w, b) into a
    donated accumulator. One streamed pass with this per batch = one
    Newton gradient/Hessian evaluation over the full dataset."""
    gx, hxx, hxb, rsum, ssum, cnt = carry
    x = batch_z[:, :-1].astype(gx.dtype)
    y = batch_z[:, -1].astype(gx.dtype)
    valid = (
        jnp.ones(x.shape[0], dtype=x.dtype) if mask is None
        else mask.astype(x.dtype)
    )
    p = jax.nn.sigmoid(x @ w + b)
    r = (p - y) * valid
    s = p * (1.0 - p) * valid
    xs = x * s[:, None]
    return (
        gx + lax.dot_general(x, r, (((0,), (0,)), ((), ())),
                             precision=lax.Precision.HIGHEST),
        hxx + lax.dot_general(x, xs, (((0,), (0,)), ((), ())),
                              precision=lax.Precision.HIGHEST),
        hxb + jnp.sum(xs, axis=0),
        rsum + jnp.sum(r),
        ssum + jnp.sum(s),
        cnt + jnp.sum(valid),
    )


@tracked_jit
def logreg_predict_kernel(x, coefficients, intercept):
    """Class probabilities σ(X·w + b) — one batched MXU matmul (the
    enabled-batch-transform posture shared with PCAModel.transform).
    Tracked so serving calls carry compile/recompile attribution like the
    PCA/KMeans transform kernels."""
    return jax.nn.sigmoid(x @ coefficients + intercept)


# Pipelined-serving variants (LogisticRegressionModel
# .serving_transform_program): donated staged input for the *_serve form
# (the pipeline never re-reads a staged buffer; retries re-stage from host
# rows), plus env-gated reduced-precision logit GEMMs — the sigmoid always
# runs in f32, only the X·w contraction drops precision.


def _predict_sigmoid(x, coefficients, intercept):
    return jax.nn.sigmoid(x @ coefficients + intercept)


logreg_predict_serve = tracked_jit(
    _predict_sigmoid, label="logreg_predict_serve", donate_argnums=(0,)
)


def _predict_bf16(x, coefficients_bf16, intercept):
    """Coefficients arrive PRE-CAST (staged once at program build)."""
    z = lax.dot_general(
        x.astype(jnp.bfloat16),
        coefficients_bf16[:, None],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )[:, 0]
    return jax.nn.sigmoid(z + intercept.astype(jnp.float32))


logreg_predict_bf16 = tracked_jit(_predict_bf16, label="logreg_predict_bf16")


def _predict_int8(x, coefficients_q, coefficients_scale, intercept):
    """Coefficients arrive PRE-QUANTIZED (``quantize_symmetric_host``);
    only the batch pays the quantization reduction per call."""
    from spark_rapids_ml_tpu.ops.quantize import quantize_symmetric

    xq, sx = quantize_symmetric(x)
    z = lax.dot_general(
        xq, coefficients_q[:, None], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )[:, 0].astype(jnp.float32) * (sx * coefficients_scale)
    return jax.nn.sigmoid(z + intercept.astype(jnp.float32))


logreg_predict_int8 = tracked_jit(_predict_int8, label="logreg_predict_int8")

# Un-jitted stage bodies for the fused whole-pipeline serving programs
# (models._serving.build_fused_pipeline_program). σ(X·w+b) is
# output-typed (probabilities), so logreg composes only as the TERMINAL
# stage of a fused chain.
SERVING_STAGE_BODIES = {
    "native": _predict_sigmoid,
    "bf16": _predict_bf16,
    "int8": _predict_int8,
}


# -- multinomial (softmax) family ------------------------------------------
# Spark's LogisticRegression auto-selects multinomial when the label has
# more than two classes. Parameterization matches Spark/sklearn: one
# coefficient row per class (over-parameterized "symmetric" softmax, made
# identifiable by the L2 term), objective
#   (1/Σw)·Σᵢ wᵢ·CE(softmax(Wxᵢ+b), yᵢ) + (λ/2)·‖W‖²  (intercepts free).
# Full Newton on the (K·(d+1)) system: the Hessian's (k,l) feature block
# is Xᵀ diag(wᵢ·(pₖδ(k=l) − pₖpₗ)) X — K² small MXU Grams per iteration,
# fine for the K ≲ tens regime this targets.


class MultinomialResult(NamedTuple):
    coefficients: jnp.ndarray  # (K, n_features)
    intercepts: jnp.ndarray    # (K,)
    n_iter: jnp.ndarray
    converged: jnp.ndarray


def multinomial_raw_stats(wb, x, y_oh, valid):
    """Per-batch RAW softmax-Newton partials at the current (K, d+1)
    parameters: (gxa = rᵀ[x,1] (K, d+1), h_raw = the K²·(d+1)² block
    Hessian numerator, cnt = Σvalid). Additive across batches/shards —
    the accumulation unit for the streamed multinomial fit."""
    n_feat = x.shape[1]
    k = y_oh.shape[1]
    w = wb[:, :n_feat]
    b = wb[:, n_feat]
    z = x @ w.T + b[None, :]
    p = jax.nn.softmax(z, axis=1)
    r = (p - y_oh) * valid[:, None]          # (n, K)
    ones = jnp.ones((x.shape[0], 1), dtype=x.dtype)
    xa = jnp.concatenate([x, ones], axis=1)   # (n, d+1)
    gxa = lax.dot_general(
        r, xa, (((0,), (0,)), ((), ())), precision=lax.Precision.HIGHEST
    )

    def block(kl):
        kk, ll = kl // k, kl % k
        sblk = p[:, kk] * ((kk == ll) * 1.0 - p[:, ll]) * valid
        return lax.dot_general(
            xa * sblk[:, None], xa, (((0,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
        )

    blocks = jax.vmap(block)(jnp.arange(k * k))  # (K², d+1, d+1)
    h_raw = jnp.transpose(
        blocks.reshape(k, k, n_feat + 1, n_feat + 1), (0, 2, 1, 3)
    ).reshape(k * (n_feat + 1), k * (n_feat + 1))
    return gxa, h_raw, jnp.sum(valid)


def assemble_multinomial_system(gxa, h_raw, cnt, wb, reg_param,
                                fit_intercept):
    """(g, h) of the softmax Newton system from accumulated raw partials
    — regularization, intercept pinning, and the gauge ridge live HERE,
    once, shared by the in-memory kernel and the streamed assembler
    (jnp ops: traced inside jit, eager on host arrays)."""
    k, dim = wb.shape
    n_feat = dim - 1
    dtype = h_raw.dtype
    cnt = jnp.maximum(cnt, 1.0)
    w = wb[:, :n_feat]
    g = gxa / cnt
    g = g.at[:, :n_feat].add(reg_param * w)
    if not fit_intercept:
        g = g.at[:, n_feat].set(0.0)
    h = h_raw / cnt
    if not fit_intercept:
        # Pin the intercept slots COMPLETELY: zero their rows and columns,
        # identity diagonal. Zeroing only the gradient would still let
        # Newton steps couple features to implicit intercepts through the
        # off-diagonal Hessian blocks and silently train the wrong model.
        keep = jnp.tile(
            jnp.concatenate([
                jnp.ones((n_feat,), dtype=dtype),
                jnp.zeros((1,), dtype=dtype),
            ]),
            k,
        )
        h = h * keep[:, None] * keep[None, :]

    # L2 on coefficients. The softmax parameterization is invariant under
    # a uniform shift of all K (unpenalized) intercepts — an EXACT null
    # direction for any reg_param — and at reg_param=0 the class-shifted
    # coefficient direction joins it. Pin the gauge with a dtype-scaled
    # ridge (sqrt(eps) × the Hessian's diagonal scale): predictions are
    # invariant to the gauge, and the ridge is far above float32 rounding
    # (a fixed 1e-8 underflows into H in f32 and leaves the system
    # exactly singular).
    eps_ridge = jnp.sqrt(jnp.finfo(dtype).eps).astype(dtype) * (
        jnp.maximum(jnp.mean(jnp.diagonal(h)), 1.0)
    )
    reg_diag = jnp.tile(
        jnp.concatenate([
            jnp.full((n_feat,), reg_param, dtype=dtype),
            jnp.asarray([0.0 if fit_intercept else 1.0], dtype=dtype),
        ]),
        k,
    )
    h = h + jnp.diag(reg_diag) + eps_ridge * jnp.eye(k * dim, dtype=dtype)
    return g, h


def _softmax_grad_hess(wb, x, y_oh, valid, reg_param, fit_intercept):
    gxa, h_raw, cnt = multinomial_raw_stats(wb, x, y_oh, valid)
    return assemble_multinomial_system(
        gxa, h_raw, cnt, wb, reg_param, fit_intercept
    )


@partial(jax.jit, donate_argnums=(0,))
def update_multinomial_stats(carry, x, y_oh, wb, mask=None):
    """Out-of-core softmax-Newton building block: fold one batch's raw
    partials at the current parameters into a donated accumulator. One
    streamed pass = one Newton gradient/Hessian evaluation."""
    gxa, h_raw, cnt = carry
    valid = (
        jnp.ones(x.shape[0], dtype=x.dtype) if mask is None
        else mask.astype(x.dtype)
    )
    g, h, c = multinomial_raw_stats(wb, x.astype(gxa.dtype),
                                    y_oh.astype(gxa.dtype), valid)
    return gxa + g, h_raw + h, cnt + c


@partial(
    jax.jit,
    static_argnames=("fit_intercept", "max_iter", "n_classes"),
)
def multinomial_fit_kernel(
    x: jnp.ndarray,
    y_onehot: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
    max_iter: int = 25,
    tol: float = 1e-6,
    n_classes: int = 2,
) -> MultinomialResult:
    dtype = x.dtype
    n_feat = x.shape[1]
    valid = (
        jnp.ones(x.shape[0], dtype=dtype) if mask is None
        else mask.astype(dtype)
    )
    wb0 = jnp.zeros((n_classes, n_feat + 1), dtype=dtype)

    def cond(state):
        wb, i, delta = state
        return jnp.logical_and(i < max_iter, delta > tol)

    def body(state):
        wb, i, _ = state
        g, h = _softmax_grad_hess(
            wb, x, y_onehot, valid, reg_param, fit_intercept
        )
        step = jax.scipy.linalg.cho_solve(
            jax.scipy.linalg.cho_factor(h), g.reshape(-1)
        ).reshape(n_classes, n_feat + 1)
        wb = wb - step
        return wb, i + 1, jnp.max(jnp.abs(step))

    wb, n_iter, delta = lax.while_loop(
        cond, body, (wb0, jnp.asarray(0), jnp.asarray(jnp.inf, dtype))
    )
    return MultinomialResult(
        coefficients=wb[:, :n_feat],
        intercepts=wb[:, n_feat] * (1.0 if fit_intercept else 0.0),
        n_iter=n_iter,
        converged=delta <= tol,
    )
