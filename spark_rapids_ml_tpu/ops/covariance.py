"""Covariance / Gram assembly — the MXU-heavy half of PCA.

Replaces the reference's per-partition ``dgemm`` JNI kernel
(``/root/reference/native/src/rapidsml_jni.cu:172-258``: per-call cudaMalloc,
H2D copy, cuBLAS GEMM, D2H copy) with jit-compiled XLA programs: centering,
scaling and the rank-update all fuse into one MXU matmul with no host round
trips. The dead ``dspr`` packed rank-1 path
(``rapidsml_jni.cu:107-170``) is intentionally dropped — an outer-product
accumulate is just a Gram matmul on TPU (SURVEY.md §2 checklist item 4).

Semantics follow the *corrected* spec (SURVEY.md §3.6): covariance normalizes
by ``numRows - 1`` everywhere (the reference's GEMM path wrongly scales by
``1/√(numCols−1)``, ``RapidsRowMatrix.scala:169``), and ``meanCentering=False``
is supported on every path (the reference's CPU spr path crashes,
``RapidsRowMatrix.scala:219-225``).

All kernels take an optional per-row ``mask`` so callers can pad row counts
to static bucket shapes (XLA requires static shapes; uneven data partitions
are padded and masked rather than recompiled per shape).
"""

from __future__ import annotations

import os
from typing import Optional

import jax.numpy as jnp
from jax import lax

# MXU precision for Gram products ONLY (kmeans distances, linreg normal
# equations and the PCA transform keep ``HIGHEST``: their expanded-form
# cancellations measurably degrade under bf16 splits). ``bfloat16_3x``
# (3-pass bf16 split with f32 accumulation) measures numerically
# indistinguishable from ``highest`` on the covariance/eigenvector oracle —
# max|cov err| 1.34e-5 vs 1.37e-5 on 65536×512 N(0,1) data, and equal error
# on mean-100 data where one-pass cancellation dominates both modes alike —
# while running ~1.3× faster on the MXU. ``highest`` (full f32 passes) and
# ``default`` (single-pass bf16 — fails the 1e-5 bar) remain selectable.
# Resolved lazily at each call site so a bad env value fails where a Gram is
# requested (with a clear message), not at ``import spark_rapids_ml_tpu``.
# Note: inside jit-compiled kernels the value is read at TRACE time and baked
# into the compiled program — changing the env var later affects new traces
# (new shapes) but not already-cached executables.
from spark_rapids_ml_tpu.utils.numeric import (  # noqa: E402
    GRAM_PRECISIONS as _ALLOWED_PRECISIONS,
)


def default_gram_precision() -> str:
    """Gram MXU precision from ``TPUML_GRAM_PRECISION`` (default bfloat16_3x)."""
    value = os.environ.get("TPUML_GRAM_PRECISION", "bfloat16_3x")
    if value not in _ALLOWED_PRECISIONS:
        raise ValueError(
            f"TPUML_GRAM_PRECISION={value!r} is not one of {_ALLOWED_PRECISIONS}"
        )
    return value


def resolve_gram_precision(value) -> str:
    """An estimator's ``gramPrecision`` param → the concrete MXU
    precision: ``None``/'auto' defers to the env-configured default;
    an explicit value is validated and wins over the env var."""
    if value is None or value == "auto":
        return default_gram_precision()
    if value not in _ALLOWED_PRECISIONS:
        raise ValueError(
            f"gramPrecision={value!r} is not one of "
            f"('auto',) + {_ALLOWED_PRECISIONS}"
        )
    return value


def _masked(x: jnp.ndarray, mask: Optional[jnp.ndarray]) -> jnp.ndarray:
    if mask is None:
        return x
    return x * mask[:, None].astype(x.dtype)


def row_count(x: jnp.ndarray, mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Number of valid rows (scalar int32).

    Integer, NOT x's dtype: accumulated f32 counts stop being exact at 2²⁴
    (16.7M) rows — squarely inside the out-of-core/streaming regime — and
    would silently corrupt the mean and the ``n·μμᵀ`` correction. int32 is
    exact to 2.1e9 rows and TPU-native (x64 off would demote int64 anyway).
    Callers divide by it / scale with it, which promotes to float as needed.
    """
    if mask is None:
        return jnp.asarray(x.shape[0], dtype=jnp.int32)
    return jnp.sum(mask).astype(jnp.int32)


def column_means(x: jnp.ndarray, mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Per-column mean over valid rows.

    Equivalent of the reference's driver-side ``Statistics.colStats(rows).mean``
    pass (``RapidsRowMatrix.scala:152-162``), but computed on device.
    """
    n = row_count(x, mask)
    return jnp.sum(_masked(x, mask), axis=0) / n


def gram(x: jnp.ndarray, precision=None) -> jnp.ndarray:
    """xᵀx on the MXU. ``precision=None`` resolves to
    ``default_gram_precision()``; both it and ``highest`` keep f32 accumulation
    exact enough for the 1e-5 oracle bar (see SURVEY.md §7 "float64")."""
    return lax.dot_general(
        x,
        x,
        (((0,), (0,)), ((), ())),
        precision=default_gram_precision() if precision is None else precision,
    )


def covariance(
    x: jnp.ndarray,
    mean: Optional[jnp.ndarray] = None,
    mask: Optional[jnp.ndarray] = None,
    ddof: int = 1,
    precision=None,
) -> jnp.ndarray:
    """Sample covariance ``(X−μ)ᵀ(X−μ) / (n − ddof)``.

    Mirrors the reference's GEMM covariance path
    (``RapidsRowMatrix.scala:168-202``) but folds the ``1/√(n−ddof)`` row
    scaling into XLA's fusion rather than a Scala per-row hot loop, and fixes
    the normalizer to use the row count (§3.6 caveat).

    ``mean=None`` skips centering (the ``meanCentering=false`` mode,
    ``RapidsRowMatrix.scala:163-165``).
    """
    xc = x if mean is None else x - mean[None, :]
    xc = _masked(xc, mask)
    n = row_count(x, mask)
    scale = 1.0 / jnp.sqrt(jnp.maximum(n - ddof, 1).astype(x.dtype))
    return gram(xc * scale, precision=precision)


def partial_gram_stats(
    x: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    precision=None,
):
    """One-pass per-shard sufficient statistics: (xᵀx, Σx, count).

    The building block of the distributed path: each device computes these on
    its row shard, then a single fused ``psum`` combines them across the mesh
    — replacing the reference's executor→driver serialization of n×n partials
    (``RapidsRowMatrix.scala:202``).
    """
    xm = _masked(x, mask)
    g = gram(xm, precision=precision)
    s = jnp.sum(xm, axis=0)
    cnt = row_count(x, mask)
    return g, s, cnt


def covariance_from_stats(
    g: jnp.ndarray, s: jnp.ndarray, cnt: jnp.ndarray, ddof: int = 1,
    mean_centering: bool = True,
) -> jnp.ndarray:
    """Combine global (Σxxᵀ, Σx, n) into covariance: (G − n·μμᵀ)/(n−ddof).

    The one-pass formulation. Its accuracy limit is the f32 cancellation in
    ``G − n·μμᵀ`` when |μ| ≫ σ — measured equally bad under ``highest`` and
    ``bfloat16_3x`` Gram precision (≈0.1 abs err on N(100,1) data either way)
    — so for large-mean/ill-conditioned data use the two-pass variant
    (center first, then Gram), which is the fit kernel's default for parity
    with the reference's semantics; this is the low-communication option.
    """
    denom = jnp.maximum(cnt - ddof, 1).astype(g.dtype)
    if not mean_centering:
        return g / denom
    mu = s / cnt
    return (g - cnt * jnp.outer(mu, mu)) / denom


