"""MultilayerPerceptron kernels: whole-training-loop-on-device.

TPU mapping: the ENTIRE full-batch training run — forward (layer
matmuls on the MXU), softmax cross-entropy, backward, and the L-BFGS /
GD update — compiles into ONE XLA program: a ``lax.while_loop`` over
optimizer steps with the loss-change tolerance evaluated on device, so
there is no per-iteration host round-trip at all (contrast the IRLS
planes, which are host-driven by design because their per-iteration
state must cross a Spark job boundary).

Semantics follow Spark's ``ml.classification.MultilayerPerceptron
Classifier`` (sigmoid hidden layers, softmax output, cross-entropy,
solvers 'l-bfgs' and 'gd'); the reference repo is PCA-only
(``/root/reference/src/main/scala/com/nvidia/spark/ml/feature/PCA.scala``).
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def init_weights(layers: Sequence[int], seed: int) -> List[dict]:
    """Glorot-uniform init per affine layer, host-side, f64.

    Returns a pytree: [{"w": (d_in, d_out), "b": (d_out,)}, ...].
    """
    rng = np.random.default_rng(seed)
    params = []
    for d_in, d_out in zip(layers[:-1], layers[1:]):
        limit = np.sqrt(6.0 / (d_in + d_out))
        params.append({
            "w": rng.uniform(-limit, limit, size=(d_in, d_out)),
            "b": np.zeros(d_out),
        })
    return params


def forward_logits(params, x):
    """Sigmoid hidden layers + final affine (the pre-softmax logits —
    Spark's rawPrediction)."""
    h = x
    for layer in params[:-1]:
        h = 1.0 / (1.0 + jnp.exp(-(h @ layer["w"] + layer["b"])))
    last = params[-1]
    return h @ last["w"] + last["b"]


def validate_and_onehot(x, y, layers):
    """Spark MLP label conventions in ONE place (shared by the local
    fit and ``parallel.distributed_mlp_fit``): layers[0] must match the
    feature width, labels must be class indices 0..layers[-1]-1;
    returns the (n, n_classes) one-hot matrix."""
    import numpy as np

    x = np.asarray(x)
    y = np.asarray(y, dtype=np.float64).reshape(-1)
    if y.shape[0] != x.shape[0]:
        raise ValueError(
            f"labels length {y.shape[0]} != rows {x.shape[0]}")
    if x.shape[1] != layers[0]:
        raise ValueError(
            f"layers[0]={layers[0]} != feature width {x.shape[1]}")
    n_classes = int(layers[-1])
    y_idx = y.astype(np.int64)
    if not np.array_equal(y_idx, y) or y_idx.min() < 0 \
            or y_idx.max() >= n_classes:
        raise ValueError(
            f"labels must be class indices 0..{n_classes - 1} "
            "(Spark MLP convention)")
    y_onehot = np.zeros((y.shape[0], n_classes))
    y_onehot[np.arange(y.shape[0]), y_idx] = 1.0
    return y_onehot


def rowwise_cross_entropy(params, x, y_onehot):
    """Per-row softmax cross-entropy — the ONE objective kernel the
    local and mesh-distributed MLP fits share (the reduction differs:
    plain weighted mean here, psum'd global mean in
    parallel/distributed_optim.py)."""
    logits = forward_logits(params, x)
    logp = jax.nn.log_softmax(logits, axis=1)
    return -(y_onehot * logp).sum(axis=1)


def mean_cross_entropy(params, x, y_onehot, w):
    return (w * rowwise_cross_entropy(params, x, y_onehot)).sum() \
        / w.sum()


def mlp_train_kernel(params, x, y_onehot, w, *, solver: str,
                     max_iter: int, tol, step_size):
    """Full-batch training to convergence in one compiled program —
    a thin wrapper over the shared whole-loop-on-device optimizer
    (``ops/optim.py::minimize_kernel``) with the MLP's softmax
    cross-entropy objective.

    solver='l-bfgs': optax.lbfgs (zoom linesearch) — Spark's default.
    solver='gd': plain gradient descent at ``step_size``.
    Stops when |loss - loss_prev| < tol or at ``max_iter``.
    Returns (params, n_iter, final_loss).
    """
    from spark_rapids_ml_tpu.ops.optim import minimize_kernel

    return minimize_kernel(
        params, (x, y_onehot, w), loss_fn=mean_cross_entropy,
        solver=solver, max_iter=max_iter, tol=tol, step_size=step_size)


def flatten_weights(params: List[dict]) -> np.ndarray:
    """Spark-layout flat weight vector: per layer, W row-major then b."""
    parts = []
    for layer in params:
        parts.append(np.asarray(layer["w"], dtype=np.float64).ravel())
        parts.append(np.asarray(layer["b"], dtype=np.float64).ravel())
    return np.concatenate(parts)


def unflatten_weights(flat: np.ndarray,
                      layers: Sequence[int]) -> List[dict]:
    params = []
    pos = 0
    for d_in, d_out in zip(layers[:-1], layers[1:]):
        w = flat[pos:pos + d_in * d_out].reshape(d_in, d_out)
        pos += d_in * d_out
        b = flat[pos:pos + d_out]
        pos += d_out
        params.append({"w": np.asarray(w), "b": np.asarray(b)})
    if pos != flat.shape[0]:
        raise ValueError(
            f"weight vector length {flat.shape[0]} does not match "
            f"layers {list(layers)} (expected {pos})")
    return params
