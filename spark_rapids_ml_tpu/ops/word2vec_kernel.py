"""Word2Vec device kernel: skip-gram negative-sampling SGD steps.

The TPU-shaped replacement for Spark Word2Vec's hierarchical-softmax
inner loop (see ``models/word2vec.py`` for the documented deviation):
each step is a fixed-shape batch of embedding gathers, two batched
contractions, and three scatter-adds, with negatives drawn on device
from the unigram^{3/4} noise distribution. Embedding tables are donated,
so the whole training run keeps exactly one (vocab, dim) pair resident.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, donate_argnums=(0, 1), static_argnames=("k_neg",))
def sgns_batch_kernel(u, v, c_idx, ctx_idx, key, lr, noise_logits,
                      k_neg: int):
    """One negative-sampling SGD step over a (center, context) batch.

    Returns (u, v, batch loss). Gradients follow Mikolov's SGNS:
    ∂/∂u_c = (σ(u·v⁺)−1)·v⁺ + Σ_k σ(u·v⁻_k)·v⁻_k, symmetrical for v.
    """
    negs = jax.random.categorical(
        key, noise_logits, shape=(c_idx.shape[0], k_neg))
    uc = u[c_idx]                                   # (b, d)
    vpos = v[ctx_idx]                               # (b, d)
    vneg = v[negs]                                  # (b, K, d)
    pos_score = jnp.sum(uc * vpos, axis=-1)
    neg_score = jnp.einsum("bd,bkd->bk", uc, vneg)
    gpos = jax.nn.sigmoid(pos_score) - 1.0          # (b,)
    gneg = jax.nn.sigmoid(neg_score)                # (b, K)
    guc = gpos[:, None] * vpos + jnp.einsum("bk,bkd->bd", gneg, vneg)
    loss = -(jax.nn.log_sigmoid(pos_score).sum()
             + jax.nn.log_sigmoid(-neg_score).sum())
    # Per-word gradient AVERAGING: the reference word2vec applies pair
    # updates sequentially, but a batched scatter-add SUMS every colliding
    # contribution — on a small vocabulary hundreds of pairs hit the same
    # row per batch and the summed step diverges. Dividing each row's
    # accumulated gradient by its batch occurrence count keeps the
    # per-row step at O(lr) for any batch/vocab ratio.
    ones = jnp.ones_like(c_idx, dtype=u.dtype)
    cnt_u = jnp.zeros((u.shape[0],), u.dtype).at[c_idx].add(ones)
    cnt_v = (jnp.zeros((v.shape[0],), v.dtype)
             .at[ctx_idx].add(ones)
             .at[negs.reshape(-1)].add(1.0))
    cnt_u = jnp.maximum(cnt_u, 1.0)
    cnt_v = jnp.maximum(cnt_v, 1.0)
    u = u.at[c_idx].add(-lr * guc / cnt_u[c_idx][:, None])
    v = v.at[ctx_idx].add(
        -lr * gpos[:, None] * uc / cnt_v[ctx_idx][:, None])
    neg_flat = negs.reshape(-1)
    v = v.at[neg_flat].add(
        -lr * (gneg[..., None] * uc[:, None, :]).reshape(-1, uc.shape[1])
        / cnt_v[neg_flat][:, None])
    return u, v, loss
