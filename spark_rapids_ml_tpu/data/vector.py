"""Dense/sparse vector types with Spark ML ``linalg`` semantics.

The reference framework consumes Spark ML ``Vector`` columns (dense or
sparse) and guarantees identical results for both encodings
(``/root/reference/src/test/scala/com/nvidia/spark/ml/feature/PCASuite.scala:155-190``).
These lightweight equivalents preserve that user-facing contract without a
JVM: both encodings densify to the same ``numpy`` row before device transfer.
"""

from __future__ import annotations

from typing import Iterable, Sequence, Tuple, Union

import numpy as np


class DenseVector:
    """A dense 1-D vector of float64 values (Spark ``ml.linalg.DenseVector``)."""

    __slots__ = ("values",)

    def __init__(self, values: Iterable[float]):
        self.values = np.asarray(values, dtype=np.float64).reshape(-1)

    @property
    def size(self) -> int:
        return int(self.values.shape[0])

    def to_array(self) -> np.ndarray:
        return self.values

    toArray = to_array

    def __len__(self) -> int:
        return self.size

    def __getitem__(self, i: int) -> float:
        return float(self.values[i])

    def __eq__(self, other) -> bool:
        if isinstance(other, (DenseVector, SparseVector)):
            return np.array_equal(self.values, other.to_array())
        return NotImplemented

    def __repr__(self) -> str:
        return f"DenseVector({self.values.tolist()})"


class SparseVector:
    """A sparse vector: (size, sorted indices, values) — Spark ``SparseVector``."""

    __slots__ = ("size", "indices", "values")

    def __init__(self, size: int, indices: Iterable[int], values: Iterable[float]):
        self.size = int(size)
        self.indices = np.asarray(indices, dtype=np.int32).reshape(-1)
        self.values = np.asarray(values, dtype=np.float64).reshape(-1)
        if self.indices.shape[0] != self.values.shape[0]:
            raise ValueError("indices and values must have the same length")
        if self.indices.size and (
            np.any(np.diff(self.indices) <= 0)
            or self.indices[0] < 0
            or self.indices[-1] >= self.size
        ):
            raise ValueError("indices must be strictly increasing and in [0, size)")

    def to_array(self) -> np.ndarray:
        out = np.zeros(self.size, dtype=np.float64)
        out[self.indices] = self.values
        return out

    toArray = to_array

    def __len__(self) -> int:
        return self.size

    def __eq__(self, other) -> bool:
        if isinstance(other, (DenseVector, SparseVector)):
            return np.array_equal(self.to_array(), other.to_array())
        return NotImplemented

    def __repr__(self) -> str:
        return (
            f"SparseVector({self.size}, {self.indices.tolist()}, "
            f"{self.values.tolist()})"
        )


Vector = Union[DenseVector, SparseVector]


class Vectors:
    """Factory helpers mirroring Spark's ``ml.linalg.Vectors``."""

    @staticmethod
    def dense(*values) -> DenseVector:
        if len(values) == 1 and isinstance(values[0], (list, tuple, np.ndarray)):
            return DenseVector(values[0])
        return DenseVector(values)

    @staticmethod
    def sparse(size: int, *args) -> SparseVector:
        # Accept (size, indices, values) or (size, [(i, v), ...]).
        if len(args) == 1:
            pairs: Sequence[Tuple[int, float]] = sorted(args[0])
            indices = [int(i) for i, _ in pairs]
            values = [float(v) for _, v in pairs]
            return SparseVector(size, indices, values)
        if len(args) == 2:
            return SparseVector(size, args[0], args[1])
        raise TypeError("Vectors.sparse(size, indices, values) or (size, pairs)")


def rows_to_matrix(rows: Iterable) -> np.ndarray:
    """Densify an iterable of vectors/arrays into an (m, n) float64 matrix.

    All rows must share one size — mirrors the reference's implicit contract
    (numFeatures from the first row,
    ``/root/reference/src/main/scala/org/apache/spark/ml/feature/RapidsPCA.scala:117-119``).
    """
    dense_rows = []
    n = None
    for r in rows:
        if isinstance(r, (DenseVector, SparseVector)):
            arr = r.to_array()
        else:
            arr = np.asarray(r, dtype=np.float64).reshape(-1)
        if n is None:
            n = arr.shape[0]
        elif arr.shape[0] != n:
            raise ValueError(
                f"inconsistent vector sizes: expected {n}, got {arr.shape[0]}"
            )
        dense_rows.append(arr)
    if not dense_rows:
        raise ValueError("empty input: need at least one row")
    return np.stack(dense_rows, axis=0)
