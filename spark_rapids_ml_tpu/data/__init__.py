from spark_rapids_ml_tpu.data.vector import DenseVector, SparseVector, Vectors
from spark_rapids_ml_tpu.data.frame import VectorFrame

__all__ = ["DenseVector", "SparseVector", "Vectors", "VectorFrame"]
