"""Out-of-core batch sources: fixed-shape streams for unbounded datasets.

The reference never materializes the whole dataset in one buffer — it
streams partition chunks through the device, one JNI GEMM per partition
(``/root/reference/src/main/scala/org/apache/spark/ml/linalg/distributed/RapidsRowMatrix.scala:168-202``).
This module is the TPU-native ingestion contract behind that capability:
any fit() input — an in-memory matrix, a generator of arbitrarily-sized
chunks, or a callable producing such a generator — is normalized into a
stream of FIXED-shape ``(batch, mask)`` pairs. Fixed shapes matter because
XLA compiles one program per shape: uneven chunks are re-blocked into
``batch_rows``-row buckets and the tail is padded + masked, so the whole
stream hits one cached executable (SURVEY.md §7 "bucketed static shapes").

Re-iterability drives semantics upstream: a re-iterable source (matrix,
list of chunks, or factory callable) supports the exact two-pass
mean-then-centered-Gram schedule; a one-shot iterator gets the one-pass
(Σxxᵀ, Σx, n) formulation (documented cancellation hazard for |μ| ≫ σ,
see ``ops/covariance.covariance_from_stats``).
"""

from __future__ import annotations

import os
from typing import Iterator, Optional, Tuple

import numpy as np

# In-memory inputs larger than this stream through the device accumulator in
# batch_rows buckets instead of one whole-matrix device_put. Default 1 GiB:
# comfortably under a v5e chip's HBM while keeping small fits single-shot.
STREAM_THRESHOLD_ENV = "TPUML_STREAM_THRESHOLD_BYTES"
DEFAULT_STREAM_THRESHOLD = 1 << 30


def stream_threshold_bytes() -> int:
    value = os.environ.get(STREAM_THRESHOLD_ENV)
    if value is None:
        return DEFAULT_STREAM_THRESHOLD
    try:
        return int(value)
    except ValueError as exc:
        raise ValueError(
            f"{STREAM_THRESHOLD_ENV}={value!r} is not an integer byte count"
        ) from exc


def auto_batch_rows(n_features: int, target_bytes: int = 128 << 20,
                    itemsize: int = 4) -> int:
    """Rows per device batch so one f32 batch is ~``target_bytes``, rounded
    to a multiple of 256 (MXU/lane-friendly), floored at 1024."""
    rows = max(1024, target_bytes // max(1, n_features * itemsize))
    return max(1024, (rows // 256) * 256)


def _as_chunk(chunk) -> np.ndarray:
    arr = np.asarray(chunk)
    if arr.ndim == 1:
        arr = arr[None, :]
    if arr.ndim != 2:
        raise ValueError(
            f"batch chunks must be 1-D or 2-D row arrays, got ndim={arr.ndim}"
        )
    return arr


def streaming_source(dataset, batch_rows: int = 0) -> Optional["BatchSource"]:
    """Return a BatchSource for inherently-streaming fit() inputs (a
    generator / iterator of chunks, or a zero-arg callable producing one),
    else None.

    Materializable inputs (arrays, frames, pandas, lists of vectors) return
    None — estimators decide separately whether to stream those by size.
    """
    import pandas as pd

    from spark_rapids_ml_tpu.data.frame import VectorFrame

    if isinstance(dataset, (VectorFrame, pd.DataFrame, np.ndarray, list, tuple)):
        return None
    if callable(dataset):
        return BatchSource(dataset, batch_rows=batch_rows)
    if hasattr(dataset, "__array__"):
        return None
    if hasattr(dataset, "__next__"):
        return BatchSource(dataset, batch_rows=batch_rows)
    return None


class BatchSource:
    """Normalizes a fit() input into fixed-shape ``(batch, mask)`` streams.

    ``source`` may be:
      * a 2-D array (or anything ``np.asarray`` densifies to one) — re-iterable,
      * a list/tuple of chunks — re-iterable,
      * a zero-arg callable returning an iterable of chunks — re-iterable
        (called once per pass),
      * a one-shot iterator/generator of chunks — single pass only.

    Chunks may have any row count; they are re-blocked into exact
    ``batch_rows`` buckets. Every yielded batch has shape
    ``(batch_rows, n_features)``; the final bucket is zero-padded with
    ``mask`` marking valid rows (``mask is None`` for full buckets — the
    jitted accumulators trace the mask-free fast path for those).
    """

    def __init__(self, source, batch_rows: int = 0,
                 n_features: Optional[int] = None, chunk_transform=None):
        """``chunk_transform`` (chunk → 2-D array) runs on each raw chunk
        BEFORE re-blocking — callers with structured chunks (e.g.
        LinearRegression's (X, y) pairs) pass it here instead of wrapping
        the source in a generator expression, which would defeat the
        non-fresh-factory detection below."""
        self._matrix: Optional[np.ndarray] = None
        self._factory = None
        self._oneshot: Optional[Iterator] = None
        self._transform = chunk_transform

        if callable(source):
            # A factory must produce a FRESH iterator per call. `lambda: gen`
            # over one generator object is an easy mistake that would make
            # pass 2 silently iterate an exhausted stream — detect it by
            # identity (same iterator object on both calls) and demote to a
            # one-shot source. `lambda: some_list` is fine: lists are not
            # their own iterators.
            probe = source()
            if iter(probe) is probe and source() is probe:
                self._oneshot = iter(probe)
            else:
                self._factory = source
        elif isinstance(source, (list, tuple)):
            chunks = [self._prep(c) for c in source]
            self._factory = lambda: iter(chunks)
        elif hasattr(source, "__array__") or isinstance(source, np.ndarray):
            self._matrix = np.asarray(source)
            if self._matrix.ndim != 2:
                raise ValueError("matrix source must be 2-D")
        elif hasattr(source, "__next__") or hasattr(source, "__iter__"):
            self._oneshot = iter(source)
        else:
            raise TypeError(
                f"unsupported batch source {type(source).__name__}"
            )

        self._consumed = False
        self._first_pass_rows: Optional[int] = None
        self.n_features = n_features
        self._peeked: Optional[np.ndarray] = None
        if self._matrix is not None:
            self.n_features = self._matrix.shape[1]
        elif self.n_features is None:
            # Peek one chunk to learn the width (stashed and re-yielded).
            it = self._factory() if self._factory else self._oneshot
            try:
                first = self._prep(next(iter(it)))
            except StopIteration:
                raise ValueError("batch source is empty") from None
            self.n_features = first.shape[1]
            if self._factory is None:
                self._peeked = first
                self._oneshot = it
            # factory sources: the peek iterator is simply dropped; a fresh
            # pass re-produces every chunk.

        self.batch_rows = batch_rows if batch_rows > 0 else auto_batch_rows(
            self.n_features
        )
        if self._matrix is not None:
            self.batch_rows = min(self.batch_rows, max(1, self._matrix.shape[0]))

    @property
    def reiterable(self) -> bool:
        return self._matrix is not None or self._factory is not None

    def _prep(self, chunk) -> np.ndarray:
        if self._transform is not None:
            chunk = self._transform(chunk)
        return _as_chunk(chunk)

    def _chunks(self) -> Iterator[np.ndarray]:
        if self._matrix is not None:
            b = self.batch_rows
            for i in range(0, self._matrix.shape[0], b):
                yield self._matrix[i:i + b]
            return
        if self._factory is not None:
            for c in self._factory():
                yield self._prep(c)
            return
        if self._consumed:
            raise RuntimeError(
                "one-shot batch source already consumed; pass a callable "
                "returning a fresh iterator (or a matrix/list) to allow "
                "multiple passes"
            )
        self._consumed = True
        if self._peeked is not None:
            yield self._peeked
            self._peeked = None
        for c in self._oneshot:
            yield self._prep(c)

    def batches(self) -> Iterator[Tuple[np.ndarray, Optional[np.ndarray]]]:
        """Yield fixed-shape ``(batch, mask)`` pairs; mask None = all valid.

        Every FULLY-consumed pass must see the same number of rows as the
        first one — a "re-iterable" factory that actually hands back a
        shared, partially-exhausted underlying iterator (one the identity
        check in ``__init__`` cannot see, e.g. ``lambda: map(f, shared_gen)``)
        would otherwise silently zero out second-pass accumulations."""
        b, n = self.batch_rows, self.n_features
        carry: list = []
        carry_rows = 0
        pass_rows = 0
        for chunk in self._chunks():
            pass_rows += chunk.shape[0]
            if chunk.shape[1] != n:
                raise ValueError(
                    f"chunk has {chunk.shape[1]} features, expected {n}"
                )
            start = 0
            # Fill the carry buffer first, then emit whole buckets directly
            # from the chunk (no copy for aligned middles of big chunks).
            if carry_rows:
                need = b - carry_rows
                take = min(need, chunk.shape[0])
                carry.append(chunk[:take])
                carry_rows += take
                start = take
                if carry_rows == b:
                    yield np.concatenate(carry, axis=0), None
                    carry, carry_rows = [], 0
            while chunk.shape[0] - start >= b:
                yield chunk[start:start + b], None
                start += b
            if start < chunk.shape[0]:
                carry.append(chunk[start:])
                carry_rows += chunk.shape[0] - start
        if carry_rows:
            # the fill stage flushes exactly at b, so any remainder here is
            # strictly short: pad + mask
            tail = np.concatenate(carry, axis=0) if len(carry) > 1 else carry[0]
            padded = np.zeros((b, n), dtype=tail.dtype)
            padded[:carry_rows] = tail
            mask = np.zeros((b,), dtype=bool)
            mask[:carry_rows] = True
            yield padded, mask
        if self._first_pass_rows is None:
            self._first_pass_rows = pass_rows
        elif pass_rows != self._first_pass_rows:
            raise RuntimeError(
                f"streaming pass saw {pass_rows} rows but the first pass saw "
                f"{self._first_pass_rows}; the source factory must return a "
                f"FRESH iterator over the same data on every call"
            )


def streamed_reduce(source, reducer, initial=None):
    """Fold valid rows of a streamed source through ``reducer(acc, rows)``
    — the one masked-iteration loop the host-streamed scaler fits share.
    ``rows`` arrives as float64 with padding removed; empty batches are
    skipped. Raises when the source held no rows at all."""
    import numpy as np

    acc = initial
    seen = False
    for batch, mask in source.batches():
        rows = np.asarray(
            batch if mask is None else batch[mask], dtype=np.float64
        )
        if rows.shape[0] == 0:
            continue
        acc = reducer(acc, rows)
        seen = True
    if not seen:
        raise ValueError("fit requires at least one row")
    return acc
