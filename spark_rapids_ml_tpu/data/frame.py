"""A minimal columnar frame carrying vector columns.

Stands in for the Spark ``DataFrame`` the reference estimator consumes
(``/root/reference/src/main/scala/org/apache/spark/ml/feature/RapidsPCA.scala:111-125``:
``dataset.select(inputCol) → RDD[Vector]``). Columns are named; a column may
hold Spark-style dense/sparse vectors, a 2-D numpy array (one row per frame
row), or plain scalars. ``pandas.DataFrame`` with a vector column converts
losslessly in both directions.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

import numpy as np

from spark_rapids_ml_tpu.data.vector import DenseVector, SparseVector, rows_to_matrix


class VectorFrame:
    """Named columns of equal length; the unit of data the estimators consume."""

    def __init__(self, columns: Dict[str, object]):
        self._columns: Dict[str, object] = {}
        self._length: Optional[int] = None
        for name, col in columns.items():
            self._set(name, col)

    def _set(self, name: str, col) -> None:
        if isinstance(col, np.ndarray) and col.ndim == 2:
            length = col.shape[0]
        else:
            col = list(col)
            length = len(col)
        if self._length is None:
            self._length = length
        elif length != self._length:
            raise ValueError(
                f"column {name!r} has length {length}, expected {self._length}"
            )
        self._columns[name] = col

    @property
    def columns(self) -> List[str]:
        return list(self._columns)

    def __len__(self) -> int:
        return self._length or 0

    def column(self, name: str):
        if name not in self._columns:
            raise KeyError(
                f"column {name!r} not found; available: {self.columns}"
            )
        return self._columns[name]

    def with_column(self, name: str, col) -> "VectorFrame":
        out = VectorFrame(dict(self._columns))
        out._set(name, col)
        return out

    def select_rows(self, indices) -> "VectorFrame":
        """Row subset by integer indices, across every column (the k-fold /
        train-validation split primitive — Spark's analogue is the
        randomSplit/filter over the DataFrame)."""
        idx = np.asarray(indices, dtype=np.int64)
        cols = {}
        for name, col in self._columns.items():
            if isinstance(col, np.ndarray):
                cols[name] = col[idx]
            else:
                cols[name] = [col[int(i)] for i in idx]
        return VectorFrame(cols)

    def vectors_as_matrix(self, name: str) -> np.ndarray:
        """Densify a vector column to an (m, n) float64 matrix."""
        col = self.column(name)
        if isinstance(col, np.ndarray):
            return np.asarray(col, dtype=np.float64)
        return rows_to_matrix(col)

    def to_pandas(self):
        import pandas as pd

        data = {}
        for name, col in self._columns.items():
            if isinstance(col, np.ndarray) and col.ndim == 2:
                data[name] = list(col)
            else:
                data[name] = col
        return pd.DataFrame(data)

    @staticmethod
    def from_pandas(df) -> "VectorFrame":
        return VectorFrame({name: list(df[name]) for name in df.columns})

    def __repr__(self) -> str:
        return f"VectorFrame(columns={self.columns}, rows={len(self)})"


def as_vector_frame(dataset, input_col: str) -> VectorFrame:
    """Coerce any supported dataset into a VectorFrame containing input_col.

    Accepted: VectorFrame, pandas.DataFrame, 2-D numpy/JAX array, or an
    iterable of vectors/row-arrays (the array forms are wrapped under
    ``input_col``).
    """
    if isinstance(dataset, VectorFrame):
        return dataset
    try:
        import pandas as pd

        if isinstance(dataset, pd.DataFrame):
            return VectorFrame.from_pandas(dataset)
    except ImportError:  # pragma: no cover
        pass
    if hasattr(dataset, "collect") and hasattr(dataset, "columns"):
        # a DataFrame (pyspark or the local engine): collect it whole.
        # This is the driver-materialization path — evaluators scoring a
        # validation fold and direct local-model use ride it; the guarded
        # streaming routes are the spark/ planes and adapters.
        names = list(dataset.columns)
        rows = dataset.collect()
        return VectorFrame({
            name: [
                row[i].toArray() if hasattr(row[i], "toArray") else row[i]
                for row in rows
            ]
            for i, name in enumerate(names)
        })
    if not isinstance(dataset, (list, tuple)):
        try:
            arr = np.asarray(dataset, dtype=np.float64)
        except (TypeError, ValueError):
            arr = None
        if arr is not None and arr.ndim == 2:
            return VectorFrame({input_col: arr})
    if isinstance(dataset, (list, tuple)):
        first = dataset[0] if dataset else None
        if isinstance(first, (DenseVector, SparseVector, np.ndarray, list, tuple)):
            return VectorFrame({input_col: list(dataset)})
    raise TypeError(
        f"unsupported dataset type {type(dataset).__name__}: expected "
        "VectorFrame, pandas.DataFrame, 2-D array, or list of vectors"
    )
