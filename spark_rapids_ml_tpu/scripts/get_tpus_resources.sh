#!/usr/bin/env bash
# TPU resource discovery script for Spark executors — the analogue of the
# getGpusResources.sh the reference's README points executors at
# (/root/reference/README.md:87-88). Configure with:
#   spark.executor.resource.tpu.discoveryScript=/path/to/get_tpus_resources.sh
# Prints one Spark ResourceInformation JSON line, e.g.
#   {"name": "tpu", "addresses": ["0", "1", "2", "3"]}
set -euo pipefail

# Fast paths that need no Python: explicit pinning env, then device nodes.
if [[ -n "${TPU_VISIBLE_CHIPS:-}" || -n "${TPU_VISIBLE_DEVICES:-}" ]]; then
  CHIPS="${TPU_VISIBLE_CHIPS:-${TPU_VISIBLE_DEVICES}}"
  # `|| true`: grep exits 1 on zero matches (e.g. TPU_VISIBLE_CHIPS=","),
  # which would abort the whole script under pipefail instead of printing []
  ADDRS=$(echo "$CHIPS" | tr ',' '\n' | sed 's/^ *//; s/ *$//' \
    | { grep -v '^$' || true; } | sed 's/.*/"&"/' | paste -sd, -)
  echo "{\"name\": \"tpu\", \"addresses\": [${ADDRS}]}"
  exit 0
fi

shopt -s nullglob
NODES=(/dev/accel[0-9]*)
if [[ ${#NODES[@]} -gt 0 ]]; then
  ADDRS=$(printf '%s\n' "${NODES[@]}" | sed 's|/dev/accel||' | sort -n \
    | sed 's/.*/"&"/' | paste -sd, -)
  echo "{\"name\": \"tpu\", \"addresses\": [${ADDRS}]}"
  exit 0
fi

# Last resort: ask the Python runtime (initializes the JAX backend).
exec python3 -c 'from spark_rapids_ml_tpu.utils.resources import discovery_json; print(discovery_json(probe_jax=True))'
