"""Platform-selection helper shared by bench, driver entry points and tests.

A TPU PJRT plugin registered at interpreter startup (sitecustomize) may
override ``jax_platforms`` via ``config.update``, silently ignoring a
``JAX_PLATFORMS=cpu`` environment request — and initializing that plugin
blocks when its device tunnel is down, hanging CPU-only runs. The config
update is the authoritative switch, so re-assert the env request there.
"""

from __future__ import annotations

import os


def force_cpu_if_requested() -> None:
    """Honor an explicit ``JAX_PLATFORMS=cpu`` env request even when a
    plugin's register() overrode the config."""
    import jax

    want = os.environ.get("JAX_PLATFORMS", "")
    tokens = want.split(",") if want else []
    if "cpu" in tokens and "axon" not in tokens:
        jax.config.update("jax_platforms", "cpu")


# Peak per-chip dense MXU FLOP/s by device kind (bf16). Shared by the
# benches so MFU numbers can't drift between them; unknown kinds report
# None rather than a made-up number.
PEAK_FLOPS_BF16 = {
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# Peak per-chip HBM bandwidth (bytes/s) by device kind — the roofline's
# second axis. A step whose arithmetic intensity (FLOPs / bytes accessed)
# sits below the ridge point ``peak_flops / peak_bw`` is memory-bound;
# above it, compute-bound. Same contract as the FLOPs table: unknown
# kinds (CPU included) report None, never a made-up number.
PEAK_HBM_BYTES_PER_SECOND = {
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,
    "TPU v5p": 2765e9,
    "TPU v4": 1228e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
}
