"""Structured per-phase timings — the observability the reference lacks.

The reference extends Spark ``Logging`` but emits no metrics
(SURVEY.md §5 "Metrics / logging"). Estimators here record wall-clock per
phase (mean / covariance / solve / transform) into a dict surfaced on the
fitted model as ``model.fit_timings_``.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict


class PhaseTimer:
    def __init__(self):
        self.timings: Dict[str, float] = {}

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.timings[name] = self.timings.get(name, 0.0) + (
                time.perf_counter() - t0
            )

    def as_dict(self) -> Dict[str, float]:
        return dict(self.timings)
