"""Structured per-phase timings — the observability the reference lacks.

The reference extends Spark ``Logging`` but emits no metrics
(SURVEY.md §5 "Metrics / logging"). Estimators here record wall-clock per
phase (mean / covariance / solve / transform) into a dict surfaced on the
fitted model as ``model.fit_timings_`` (and, through ``obs``, folded into
the uniform ``fit_report_``).

Safe for nested and concurrent use: the context manager is re-entrant
(each exit adds its own elapsed interval — note that nesting the SAME
phase name therefore counts the inner interval twice, once on its own and
once inside the outer interval) and the dict is lock-guarded so fits
running on worker threads can share one timer.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict


class PhaseTimer:
    def __init__(self):
        self.timings: Dict[str, float] = {}
        self._lock = threading.RLock()

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name: str, seconds: float) -> None:
        """Accumulate a pre-measured interval into a phase."""
        with self._lock:
            self.timings[name] = self.timings.get(name, 0.0) + float(seconds)

    def as_dict(self) -> Dict[str, float]:
        with self._lock:
            return dict(self.timings)

    def total(self) -> float:
        """Sum of all phase wall-clock (nested phases count their overlap)."""
        with self._lock:
            return sum(self.timings.values())
