"""Device health checks at task start.

The reference's failure posture is "let a CUDA error kill the task and let
Spark reschedule" (SURVEY.md §5: ``env->ThrowNew`` / executor-killing
asserts, ``rapidsml_jni.cu:115,189,356-358``). The TPU-native posture keeps
kernels side-effect-free (safe to re-execute) and adds what the reference
lacked: an explicit runtime health probe before work is scheduled, so a
wedged device tunnel fails fast with a diagnosis instead of hanging a fit.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class DeviceHealth:
    healthy: bool
    platform: str
    device_count: int
    probe_seconds: float
    error: Optional[str] = None
    devices: List[str] = field(default_factory=list)


def check_devices(probe_all: bool = True) -> DeviceHealth:
    """Run a tiny compiled op on the runtime (optionally every local
    device); returns a structured verdict instead of raising.

    No timeout here: backend init itself can block on a dead device tunnel,
    and an in-process deadline can't preempt it — callers needing a hard
    bound use ``check_devices_subprocess``.
    """
    t0 = time.perf_counter()
    try:
        import jax
        import jax.numpy as jnp

        devices = jax.devices()
        names = []
        targets = devices if probe_all else devices[:1]
        for d in targets:
            out = jax.device_put(jnp.ones((8, 8)), d).sum()
            if float(out) != 64.0:
                raise RuntimeError(f"bad probe result on {d}: {out}")
            names.append(str(d))
        return DeviceHealth(
            healthy=True,
            platform=devices[0].platform,
            device_count=len(devices),
            probe_seconds=time.perf_counter() - t0,
            devices=names,
        )
    except Exception as e:  # noqa: BLE001 - health checks report, not raise
        return DeviceHealth(
            healthy=False,
            platform="unknown",
            device_count=0,
            probe_seconds=time.perf_counter() - t0,
            error=f"{type(e).__name__}: {e}",
        )


def check_devices_subprocess(timeout_seconds: float = 90.0) -> DeviceHealth:
    """Health probe with a hard wall-clock bound: runs in a child process so
    a hanging backend init cannot wedge the caller."""
    import json
    import subprocess
    import sys

    # The child's stdout is a parsed protocol (last line = the verdict
    # JSON), written directly — not print, not a logger (a log line is
    # ALSO JSON and could be mistaken for the verdict).
    code = (
        "import json, sys\n"
        "from spark_rapids_ml_tpu.utils.health import check_devices\n"
        "h = check_devices()\n"
        "sys.stdout.write(json.dumps(h.__dict__) + chr(10))\n"
    )
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_seconds,
        )
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
        if proc.returncode == 0 and line.startswith("{"):
            return DeviceHealth(**json.loads(line))
        return DeviceHealth(
            healthy=False,
            platform="unknown",
            device_count=0,
            probe_seconds=time.perf_counter() - t0,
            error=f"probe exited rc={proc.returncode}: {proc.stderr[-300:]}",
        )
    except subprocess.TimeoutExpired:
        return DeviceHealth(
            healthy=False,
            platform="unknown",
            device_count=0,
            probe_seconds=time.perf_counter() - t0,
            error=f"backend init exceeded {timeout_seconds}s (device tunnel wedged?)",
        )
