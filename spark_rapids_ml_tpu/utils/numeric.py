"""Small shared numeric helpers (host-side)."""

from __future__ import annotations

import numpy as np

# The Gram MXU precision vocabulary — lives here (jax-free) so Param
# validators can share it with ops/covariance.py without importing jax
# at estimator-definition time.
GRAM_PRECISIONS = ("default", "bfloat16", "bfloat16_3x", "float32",
                   "highest")


def sigmoid(z: np.ndarray) -> np.ndarray:
    """Numerically stable logistic function: never evaluates exp on a
    positive argument, so large |z| cannot overflow (the naive
    ``1/(1+exp(-z))`` warns and round-trips through inf for z < -745)."""
    z = np.asarray(z, dtype=np.float64)
    out = np.empty_like(z)
    pos = z >= 0
    out[pos] = 1.0 / (1.0 + np.exp(-z[pos]))
    ez = np.exp(z[~pos])
    out[~pos] = ez / (1.0 + ez)
    return out
