"""Spark-style TPU resource discovery and device assignment.

Parity target: the reference's deployment contract
(``/root/reference/README.md:81-89``) — ``spark.task.resource.gpu.amount``,
``spark.executor.resource.gpu.amount`` and a ``discoveryScript``
(``getGpusResources.sh``) that prints Spark's ResourceInformation JSON, plus
the per-task device resolution ``gpuId == -1 ⇒
TaskContext.resources()("gpu").addresses(0)``
(``RapidsRowMatrix.scala:171-175``). Here the resource name is ``tpu``, the
discovery script ships as package data (``discovery_script_path()``), and
assignment
resolves to a JAX device ordinal. Discovery never initializes the JAX
backend unless explicitly asked (backend init can block on a wedged device
tunnel — see utils/health.py).
"""

from __future__ import annotations

import glob
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional

RESOURCE_NAME = "tpu"

# Spark conf keys, with "gpu" swapped for "tpu" (SURVEY.md §5 config table).
TASK_AMOUNT_KEY = "spark.task.resource.tpu.amount"
EXECUTOR_AMOUNT_KEY = "spark.executor.resource.tpu.amount"
DISCOVERY_SCRIPT_KEY = "spark.executor.resource.tpu.discoveryScript"

_ENV_VISIBLE = ("TPU_VISIBLE_CHIPS", "TPU_VISIBLE_DEVICES")
_ENV_TASK_DEVICE = "SPARK_RAPIDS_ML_TPU_DEVICE"


@dataclass
class ResourceInformation:
    """Mirror of ``org.apache.spark.resource.ResourceInformation`` — the
    JSON shape a discovery script must print."""

    name: str
    addresses: List[str] = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps({"name": self.name, "addresses": self.addresses})

    @staticmethod
    def from_json(text: str) -> "ResourceInformation":
        obj = json.loads(text)
        if not isinstance(obj.get("name"), str) or not isinstance(
            obj.get("addresses"), list
        ):
            raise ValueError(f"not a ResourceInformation payload: {text!r}")
        return ResourceInformation(
            name=obj["name"], addresses=[str(a) for a in obj["addresses"]]
        )


class ResourceConf:
    """Two-level config resolution, mirroring the reference's Spark-conf +
    Params split (§5): a properties mapping (``spark.*`` keys) consulted by
    the runtime, with typed accessors for the tpu resource keys.
    """

    def __init__(self, conf: Optional[Mapping[str, str]] = None):
        self._conf: Dict[str, str] = dict(conf or {})

    @staticmethod
    def from_properties(text: str) -> "ResourceConf":
        """Parse ``key value`` / ``key=value`` lines (spark-defaults.conf
        syntax: comments with #, blank lines ignored)."""
        conf: Dict[str, str] = {}
        for raw in text.splitlines():
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            # split at the FIRST separator so values containing '=' (java
            # options, paths) survive intact
            m = re.match(r"^([^=\s]+)\s*[=\s]\s*(.*)$", line)
            if m:
                conf[m.group(1)] = m.group(2).strip()
        return ResourceConf(conf)

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._conf.get(key, default)

    def task_tpu_amount(self, default: float = 0.0) -> float:
        return float(self.get(TASK_AMOUNT_KEY, str(default)))

    def executor_tpu_amount(self, default: int = 0) -> int:
        return int(float(self.get(EXECUTOR_AMOUNT_KEY, str(default))))

    def discovery_script(self) -> Optional[str]:
        return self.get(DISCOVERY_SCRIPT_KEY)


def discovery_script_path() -> str:
    """Absolute path of the packaged discovery script — what to set
    ``spark.executor.resource.tpu.discoveryScript`` to. Ships as package
    data so installed (non-checkout) deployments have it."""
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
        "get_tpus_resources.sh",
    )


def discover_tpu_addresses(probe_jax: bool = False) -> List[str]:
    """Enumerate local TPU chip addresses, cheapest signal first:

    1. ``TPU_VISIBLE_CHIPS``/``TPU_VISIBLE_DEVICES`` env (explicit pinning);
    2. ``/dev/accel*`` device nodes (how TPU VMs expose chips);
    3. optionally (``probe_jax=True``) ``jax.local_devices()`` — accurate
       but initializes the backend, which can block on a dead tunnel.
    """
    for var in _ENV_VISIBLE:
        val = os.environ.get(var)
        if val:
            return [a.strip() for a in val.split(",") if a.strip()]
    # numeric sort (matching the shell script's `sort -n`): lexicographic
    # order would interleave accel10 between accel1 and accel2
    nodes = sorted(
        glob.glob("/dev/accel[0-9]*"),
        key=lambda n: int(re.sub(r"^/dev/accel", "", n)),
    )
    if nodes:
        return [re.sub(r"^/dev/accel", "", n) for n in nodes]
    if probe_jax:
        import jax

        # filter by platform: on a TPU-less host local_devices() falls back
        # to CPU devices, which must not be advertised as tpu addresses
        return [str(d.id) for d in jax.local_devices() if d.platform == "tpu"]
    return []


def discovery_json(probe_jax: bool = False) -> str:
    """What the discovery script prints — the exact contract
    ``spark.executor.resource.tpu.discoveryScript`` expects."""
    return ResourceInformation(
        RESOURCE_NAME, discover_tpu_addresses(probe_jax=probe_jax)
    ).to_json()


def resolve_device_ordinal(
    device_id: int = -1,
    task_resources: Optional[Mapping[str, ResourceInformation]] = None,
    env: Optional[Mapping[str, str]] = None,
) -> int:
    """Which local device a task should use.

    Precedence mirrors ``RapidsRowMatrix.scala:171-175``: an explicit
    ``deviceId != -1`` wins; otherwise the task's assigned resource
    addresses (the TaskContext analogue); otherwise the
    ``SPARK_RAPIDS_ML_TPU_DEVICE`` env var; otherwise ordinal 0.
    """
    if device_id != -1:
        return device_id
    if task_resources and RESOURCE_NAME in task_resources:
        addresses = task_resources[RESOURCE_NAME].addresses
        if addresses:
            return int(addresses[0])
    env = os.environ if env is None else env
    if env.get(_ENV_TASK_DEVICE):
        return int(env[_ENV_TASK_DEVICE])
    return 0


def tree_group_budget_bytes(local_est=None) -> int:
    """Tree-group memory budget shared by the LOCAL vmapped forest fit
    and the statistics-plane tree groups: the estimator's
    ``maxMemoryInMB`` (Spark's aggregation-memory knob, default 256 on
    the estimators; 64MB bare default), overridable by
    SPARK_RAPIDS_ML_TPU_TREE_GROUP_BYTES. Parsed lazily at fit time so
    a malformed env value fails the FIT with a clear message."""
    import os

    raw = os.environ.get("SPARK_RAPIDS_ML_TPU_TREE_GROUP_BYTES")
    if raw is not None:
        try:
            value = int(raw)
            if value < 1:
                raise ValueError
            return value
        except ValueError:
            raise ValueError(
                f"SPARK_RAPIDS_ML_TPU_TREE_GROUP_BYTES={raw!r}: expected "
                "a positive integer byte count"
            ) from None
    if local_est is not None and local_est.has_param("maxMemoryInMB"):
        return int(local_est.get_or_default("maxMemoryInMB")) * 1024 * 1024
    return 64 * 1024 * 1024
