"""Profiler range annotations — the NVTX-equivalent, decoupled from native code.

The reference's ``NvtxRange`` is an AutoCloseable that JNI-pushes an NVTX
range (``/root/reference/src/main/java/com/nvidia/spark/ml/linalg/NvtxRange.java:37-59``,
``rapidsml_jni.cu:82-105``) — and because its static block force-loads the
native library, even pure-CPU paths require the ``.so``
(SURVEY.md §3.4). Here ranges are context managers over
``jax.profiler.TraceAnnotation`` (visible in xprof/TensorBoard traces) that
degrade to no-ops when profiling is unavailable — profiling is optional by
construction. When the native runtime (``libtpuml.so``) is loaded, ranges are
additionally forwarded to its trace ring-buffer so host-side phases show up
in the same timeline.

The 9-color palette mirrors ``NvtxColor.java:20-29`` for familiarity; colors
are advisory metadata on TPU (xprof has no color channel) but are recorded in
the native trace buffer.
"""

from __future__ import annotations

import enum
import time
from typing import Optional


class TraceColor(enum.Enum):
    """ARGB color bits, same palette as the reference's NvtxColor."""

    GREEN = 0xFF76B900
    BLUE = 0xFF0071C5
    PURPLE = 0xFF7F00FF
    YELLOW = 0xFFFFFF00
    RED = 0xFFFF0000
    WHITE = 0xFFFFFFFF
    DARK_GREEN = 0xFF004D00
    ORANGE = 0xFFFFA500
    CYAN = 0xFF00FFFF


class TraceRange:
    """Context manager: ``with TraceRange("compute cov", TraceColor.RED): ...``

    Mirrors the reference's try-with-resources usage at its six
    instrumentation sites (SURVEY.md §3.5). Safe to use with no profiler
    session and no native library.
    """

    def __init__(
        self,
        name: str,
        color: TraceColor = TraceColor.WHITE,
        record: bool = True,
    ):
        self.name = name
        self.color = color
        self._annotation = None
        self._native = None
        self._t0: Optional[float] = None
        self._elapsed: Optional[float] = None
        # record=False lets obs.spans.span() own the ring-buffer event for
        # ranges it creates itself (it carries extra args/trace context).
        self._record = record

    def __enter__(self) -> "TraceRange":
        self._t0 = time.perf_counter()
        self._elapsed = None  # a reused range must not report a stale freeze
        try:
            import jax.profiler

            self._annotation = jax.profiler.TraceAnnotation(self.name)
            self._annotation.__enter__()
        except Exception:
            self._annotation = None
        try:
            from spark_rapids_ml_tpu import native

            if native.is_loaded():
                native.trace_push(self.name, self.color.value)
                self._native = native
        except Exception:
            self._native = None
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._t0 is not None:
            # freeze the duration — ``elapsed`` must stop growing after exit
            self._elapsed = time.perf_counter() - self._t0
        if self._native is not None:
            try:
                self._native.trace_pop()
            except Exception:
                pass
        if self._annotation is not None:
            try:
                self._annotation.__exit__(exc_type, exc, tb)
            except Exception:
                pass
        if self._record and self._t0 is not None:
            # file the completed range into the exportable span ring buffer
            # (lazy import: obs.spans imports this module at load time)
            try:
                from spark_rapids_ml_tpu.obs.spans import record_trace_range

                record_trace_range(
                    self.name, self.color, self._t0,
                    self._t0 + self._elapsed,
                )
            except Exception:
                pass

    @property
    def elapsed(self) -> float:
        """Seconds inside the range: live while entered, frozen after exit."""
        if self._elapsed is not None:
            return self._elapsed
        return time.perf_counter() - self._t0 if self._t0 is not None else 0.0
