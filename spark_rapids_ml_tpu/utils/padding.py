"""Shape-bucket padding: funnel ragged batch sizes into few compiled shapes.

XLA compiles one executable per distinct input shape, so serving traffic
whose batch size varies per request re-pays lowering+compile on every new
row count — the exact "recompile storm" ``obs/xprof.py`` warns about, and
the latency cliff the Flare thesis attributes to interpreting arbitrary
shapes instead of compiling a fixed kernel set (PAPERS.md, arXiv:1703.08219).
The fix is the fixed-shape panel trick from the TPU linear-algebra work
(arXiv:2112.09017): round every batch up to the nearest configured **row
bucket** (powers of two by default), mask/slice the padding back off, and
steady-state traffic hits a handful of compiled signatures.

``pad_to_bucket`` is the one shared helper: the serving engine's
micro-batcher pads coalesced request batches with it, and the PCA / KMeans /
LogisticRegression transform bodies route direct (non-engine) callers
through it too, so a caller looping over ragged pandas chunks stops
triggering per-shape recompiles without ever seeing a padded row.

Padding is semantically free for these kernels: every serving kernel in
``ops/`` is row-independent (X @ PC, distance argmin, sigmoid(Xw+b)), so a
real row's output is bit-identical whether or not zero rows ride below it;
the pad rows are sliced off before any caller sees them.

``SPARK_RAPIDS_ML_TPU_TRANSFORM_PAD=0`` disables transform-body padding
(exact-shape execution, one compile per distinct batch size — the
pre-bucketing behavior).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import numpy as np

TRANSFORM_PAD_ENV = "SPARK_RAPIDS_ML_TPU_TRANSFORM_PAD"

# Below this row count every batch shares ONE bucket: tiny interactive
# requests (1..8 rows) should hit a single compiled signature, not four.
MIN_BUCKET_ROWS = 8


def transform_padding_enabled() -> bool:
    """Whether transform bodies pad direct callers to row buckets
    (default on; ``SPARK_RAPIDS_ML_TPU_TRANSFORM_PAD=0`` restores
    exact-shape execution)."""
    return os.environ.get(TRANSFORM_PAD_ENV, "1") != "0"


def default_buckets(max_rows: int) -> Tuple[int, ...]:
    """The power-of-two bucket ladder up to (at least) ``max_rows``:
    ``(8, 16, 32, ..., next_pow2(max_rows))``."""
    out = []
    b = MIN_BUCKET_ROWS
    while True:
        out.append(b)
        if b >= max_rows:
            return tuple(out)
        b *= 2


def bucket_for(n_rows: int, buckets: Optional[Sequence[int]] = None) -> int:
    """The row bucket a batch of ``n_rows`` pads up to.

    With an explicit ``buckets`` ladder: the smallest bucket >= n_rows,
    or the largest bucket when the batch exceeds them all (the caller —
    the engine's ``max_batch_rows`` — is expected to cap batches at the
    top bucket; an oversize direct batch falls back to the next power of
    two so it still compiles a reusable shape). Without one: the next
    power of two, floored at ``MIN_BUCKET_ROWS``.
    """
    if n_rows < 1:
        raise ValueError(f"n_rows must be >= 1, got {n_rows}")
    if buckets:
        for b in sorted(int(v) for v in buckets):
            if b >= n_rows:
                return b
    # next power of two, floored
    b = MIN_BUCKET_ROWS
    while b < n_rows:
        b *= 2
    return b


def pad_to_bucket(
    rows: np.ndarray, buckets: Optional[Sequence[int]] = None
) -> Tuple[np.ndarray, int]:
    """Pad a (n, d) row matrix up to its shape bucket with zero rows.

    Returns ``(padded, n)`` where ``padded.shape[0] == bucket_for(n)`` and
    ``n`` is the original row count — the caller slices its result back to
    ``[:n]`` so padding never leaks into any response. A batch already
    sitting exactly on a bucket boundary is returned as-is (no copy), and
    so is an EMPTY batch — a 0-row transform must keep returning 0 rows,
    not raise.
    """
    rows = np.asarray(rows)
    if rows.ndim != 2:
        raise ValueError(f"expected a (n, d) matrix, got shape {rows.shape}")
    n = int(rows.shape[0])
    if n == 0:
        return rows, 0
    bucket = bucket_for(n, buckets)
    if bucket == n:
        return rows, n
    return np.pad(rows, ((0, bucket - n), (0, 0))), n


def shard_bucket(n_rows: int, n_shards: int) -> int:
    """The row bucket a SHARDED batch pads up to: the next power of two
    (floored at ``MIN_BUCKET_ROWS``) rounded up to a multiple of
    ``n_shards`` — XLA shardings need equal per-device extents, and the
    serving tier's sharded big-transform path (``serve/placement.py``'s
    mesh over ``("batch",)``) still wants the few-compiled-signatures
    funnel, so sharded requests reuse the same pow-2 ladder (already
    divisible by any pow-2 device count) with a lcm bump for odd mesh
    sizes."""
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    bucket = bucket_for(n_rows)
    rem = bucket % n_shards
    if rem:
        bucket += n_shards - rem
    return bucket


def pad_to_shard_bucket(rows: np.ndarray,
                        n_shards: int) -> Tuple[np.ndarray, int]:
    """Pad a (n, d) matrix to its ``shard_bucket`` with zero rows;
    returns ``(padded, n)`` like ``pad_to_bucket`` (exact fits and
    empty batches are returned as-is)."""
    rows = np.asarray(rows)
    if rows.ndim != 2:
        raise ValueError(f"expected a (n, d) matrix, got shape {rows.shape}")
    n = int(rows.shape[0])
    if n == 0:
        return rows, 0
    bucket = shard_bucket(n, n_shards)
    if bucket == n:
        return rows, n
    return np.pad(rows, ((0, bucket - n), (0, 0))), n


def padding_waste(n_rows: int, bucket: int) -> float:
    """Fraction of the padded batch that is filler (0.0 on exact fit)."""
    if bucket <= 0:
        return 0.0
    return max(bucket - n_rows, 0) / bucket


class StagingPool:
    """Per-bucket reusable host staging arrays for the pipelined batcher.

    The pre-pipeline hot path allocated a fresh concat + pad copy per
    batch; the pipeline instead writes each request's rows straight into a
    preallocated (bucket, d) staging array (zeroing only the padding
    tail), then hands that array to ``jax.device_put``. Buffers ROTATE —
    ``slots`` must cover the in-flight window plus the transfer possibly
    still reading the previous buffer (the batcher sizes it at
    ``pipeline_depth + 2``), so a staging array is never rewritten while
    an earlier batch's host→device copy may still be consuming it.

    Single-writer by design: only one worker thread fills a pool (each
    worker generation builds its own), so there is no lock on the fill
    path. A single request already sitting exactly on its bucket boundary
    short-circuits to the caller's own array — zero copy, matching
    ``pad_to_bucket``'s exact-fit behavior.
    """

    def __init__(self, dtype=np.float64, slots: int = 3):
        self.dtype = np.dtype(dtype)
        self.slots = max(int(slots), 2)
        # (bucket, d) -> {"arrays": [...], "next": int}; arrays allocate
        # lazily so an unused bucket costs nothing.
        self._pools: dict = {}

    def fill(self, parts: Sequence[np.ndarray],
             buckets: Optional[Sequence[int]] = None,
             ) -> Tuple[np.ndarray, int]:
        """Stage one coalesced batch: ``(staged, n)`` where ``staged`` is
        the (bucket, d) array holding the ``parts`` row blocks in order
        with a zeroed padding tail, and ``n`` is the real row count."""
        if not parts:
            raise ValueError("cannot stage an empty batch")
        n = sum(int(p.shape[0]) for p in parts)
        d = int(parts[0].shape[1])
        for p in parts[1:]:
            # explicit width check: the slice assignment below would
            # silently BROADCAST a width-1 block across all d features
            # (np.concatenate raised here) — wrong results, not an error
            if int(p.shape[1]) != d:
                raise ValueError(
                    f"cannot coalesce a {p.shape[1]}-feature request "
                    f"into a {d}-feature batch"
                )
        bucket = bucket_for(n, buckets)
        if (len(parts) == 1 and bucket == n
                and parts[0].dtype == self.dtype):
            return parts[0], n  # exact fit: no copy, like pad_to_bucket
        key = (bucket, d)
        pool = self._pools.get(key)
        if pool is None:
            pool = {"arrays": [], "next": 0}
            self._pools[key] = pool
        arrays = pool["arrays"]
        idx = pool["next"]
        if idx >= len(arrays):
            arrays.append(np.zeros((bucket, d), dtype=self.dtype))
        staged = arrays[idx]
        pool["next"] = (idx + 1) % self.slots
        offset = 0
        for p in parts:
            rows = int(p.shape[0])
            staged[offset:offset + rows] = p  # coerces dtype if needed
            offset += rows
        if offset < bucket:
            staged[offset:] = 0.0  # the reused buffer's stale tail
        return staged, n
