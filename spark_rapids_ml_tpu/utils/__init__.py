from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange
from spark_rapids_ml_tpu.utils.timing import PhaseTimer
from spark_rapids_ml_tpu.utils.health import (
    DeviceHealth,
    check_devices,
    check_devices_subprocess,
)

# The canonical import surface for telemetry is now spark_rapids_ml_tpu.obs
# (which re-exports all of the above); these names stay for back-compat.
__all__ = [
    "DeviceHealth",
    "PhaseTimer",
    "TraceColor",
    "TraceRange",
    "check_devices",
    "check_devices_subprocess",
]
