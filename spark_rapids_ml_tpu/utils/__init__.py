from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange
from spark_rapids_ml_tpu.utils.timing import PhaseTimer

__all__ = ["TraceColor", "TraceRange", "PhaseTimer"]
