"""Distributed PowerIterationClustering over the mesh.

The local PIC's envelope is the dense n×n affinity resident on ONE
device (``maxDenseNodes``); the mesh form shards the row-stochastic
affinity by ROW PANELS over ``data``, so per-chip memory is n²/P and
the envelope scales with the mesh. Each power iteration is one panel
matvec per shard + one ``all_gather`` of the (n,) vector — the whole
``maxIter`` loop compiles into a single sharded program. The affinity
build and validation reuse ``models.pic.build_affinity`` (the single
shared copy), and the trailing 1-D k-means on the converged vector
runs replicated (it is O(n·k), noise next to the O(n²) matvecs).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.obs import (
    current_fit,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    collective_nbytes,
    row_sharding,
)


@partial(tracked_jit, static_argnames=("mesh", "max_iter"))
def distributed_power_iterate_kernel(
    w_panels: jnp.ndarray,
    v0: jnp.ndarray,
    *,
    mesh: Mesh,
    max_iter: int,
):
    """``max_iter`` steps of v ← normalize(W v) with W row-sharded.

    Padding rows are all-zero W rows, so their v entries go to 0 after
    the first step and never affect the L1 normalization."""

    def shard_fn(wp, v):
        def body(_, vec):
            local = wp @ vec                       # (n/P,)
            full = lax.all_gather(local, DATA_AXIS, tiled=True)  # (n,)
            return full / jnp.maximum(jnp.abs(full).sum(), 1e-30)

        return lax.fori_loop(0, max_iter, body, v)

    # check_vma=False: the output IS replicated (every shard holds the
    # identical all-gathered vector), but the static varying-axes
    # checker cannot infer replication through the fori_loop carry
    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P()),
        out_specs=P(),
        check_vma=False,
    )
    return fn(w_panels, v0)


@fit_instrumentation("distributed_pic")
def distributed_pic_assign(
    src,
    dst,
    weights=None,
    *,
    k: int,
    mesh: Mesh,
    max_iter: int = 20,
    seed: int = 0,
    init_mode: str = "random",
    max_dense_nodes: int = None,
    dtype=jnp.float32,
):
    """Edge list → (ids, cluster labels) at mesh scale.

    ``max_dense_nodes`` defaults to ``32768·⌊√P⌋`` so the PER-CHIP
    panel stays within the single-chip envelope (n²/P bytes) as the
    mesh grows; the HOST still materializes the full n² build, which
    is the remaining bound for very large graphs.
    """
    from spark_rapids_ml_tpu.models.pic import build_affinity
    from spark_rapids_ml_tpu.ops.kmeans_kernel import (
        assign_clusters as km_assign,
        kmeans_fit_kernel,
        kmeans_plus_plus_init,
    )

    src = np.asarray(src, dtype=np.float64)
    wts = (np.ones(src.shape[0]) if weights is None
           else np.asarray(weights, dtype=np.float64))
    n_dev = mesh.devices.size
    if max_dense_nodes is None:
        max_dense_nodes = 32_768 * max(1, int(np.sqrt(n_dev)))
    # the pad target depends only on n = |unique ids|; resolve it first
    # so build_affinity can allocate the padded buffer up front
    n = len(np.unique(np.concatenate([
        np.asarray(src, dtype=np.float64),
        np.asarray(dst, dtype=np.float64)])))
    pad = (-n) % n_dev
    ids, w, deg = build_affinity(src, dst, wts, max_dense_nodes,
                                 np.dtype(dtype), pad_rows=pad)
    w_dev = jax.device_put(w, row_sharding(mesh))

    rng = np.random.default_rng(seed)
    if init_mode == "degree":
        v0 = np.zeros(n + pad)
        v0[:n] = deg / deg.sum()
    elif init_mode == "random":
        v0 = np.zeros(n + pad)
        v0[:n] = rng.random(n)
        v0[:n] /= np.abs(v0[:n]).sum()
    else:
        raise ValueError("initMode must be 'random' or 'degree'")
    v0_dev = jax.device_put(np.asarray(v0, dtype=np.dtype(dtype)),
                            NamedSharding(mesh, P()))

    ctx = current_fit()
    ctx.set_iterations(max_iter)
    # one all_gather of the (n,) iterate per power iteration
    ctx.record_collective(
        "all_gather", nbytes=collective_nbytes((n + pad,), dtype),
        count=max_iter,
    )
    with ctx.phase("execute"):
        v = jax.block_until_ready(distributed_power_iterate_kernel(
            w_dev, v0_dev, mesh=mesh, max_iter=max_iter))
    # O(1) spread for k-means; the trailing 1-D cluster runs at the
    # SAME dtype as the iteration (the local path's behavior)
    emb = jnp.asarray(np.asarray(v)[:n, None] * n, dtype=dtype)
    init = kmeans_plus_plus_init(emb, k, jax.random.PRNGKey(seed))
    res = kmeans_fit_kernel(emb, init, max_iter=20, tol=1e-6)
    labels = np.asarray(km_assign(emb, res.centers))
    return ids.astype(np.int64), labels.astype(np.int64)
