"""Multi-host runtime: process initialization and global-mesh construction.

The reference's "multi-node" story is Spark's: executors each own one GPU,
all cross-node communication is Spark RPC (driver-side ``reduce`` of n×n
partials, ``RapidsRowMatrix.scala:202``), and device assignment comes from
``spark.executor.resource.gpu`` with a discovery script (``README.md:81-89``).
The TPU-native replacement: every host runs one process, processes join a
PJRT coordination service (``jax.distributed.initialize``), and XLA compiles
collectives over ICI within a slice / DCN across slices. The data plane
(Spark, Ray, a queue) only feeds each host its row shard and triggers the
same compiled program everywhere — it never moves tensors.

Configuration resolution order mirrors the reference's two-level config
(Spark conf → task resources): explicit arguments, then
``SPARK_RAPIDS_ML_TPU_COORDINATOR``/``_NUM_PROCESSES``/``_PROCESS_ID`` env
vars, then the TPU pod metadata JAX discovers natively (on Cloud TPU,
``initialize()`` needs no arguments at all).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

_ENV_COORD = "SPARK_RAPIDS_ML_TPU_COORDINATOR"
_ENV_NPROC = "SPARK_RAPIDS_ML_TPU_NUM_PROCESSES"
_ENV_PID = "SPARK_RAPIDS_ML_TPU_PROCESS_ID"

_initialized = False
_initialized_coordinator: Optional[str] = None


def _distributed_is_initialized(jax) -> bool:
    """``jax.distributed.is_initialized()`` with an old-jax (< 0.5)
    fallback that reads the same client state the real API wraps."""
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    try:  # pragma: no cover - version-dependent internal layout
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join (or skip joining) the multi-host runtime. Idempotent.

    Returns True when running multi-host after the call, False for
    single-host (no coordinator configured anywhere — the common local
    case, where calling ``jax.distributed.initialize`` would fail).
    """
    global _initialized, _initialized_coordinator
    import jax

    # Idempotency check must NOT touch backend-initializing APIs
    # (jax.process_count() would create the backend and make a later
    # initialize() impossible); is_initialized() only reads client state.
    if _initialized or _distributed_is_initialized(jax):
        # Reuse is only safe when it is the SAME job: a second collective
        # fit in a long-lived executor process arrives with a freshly
        # picked driver coordinator, and silently reusing the first job's
        # runtime would desynchronize the barrier (advisor r3). Surface
        # the mismatch instead of hanging.
        requested = coordinator_address or os.environ.get(_ENV_COORD)
        if requested is not None:
            if _initialized_coordinator is None:
                # runtime was initialized outside this module (or from
                # ambient pod metadata): adopt the first requested
                # coordinator as the session's, so a LATER different
                # request is caught as a true conflict
                _initialized_coordinator = requested
            elif requested != _initialized_coordinator:
                raise RuntimeError(
                    "jax.distributed is already initialized in this "
                    "process with coordinator "
                    f"{_initialized_coordinator!r}, but this fit requests "
                    f"{requested!r}. The distributed runtime joins once "
                    "per process lifetime — either pre-set "
                    f"{_ENV_COORD} to one stable coordinator for the "
                    "whole session, or use fresh executor processes per "
                    "collective fit (spark.python.worker.reuse=false)."
                )
        _initialized = True
        return jax.process_count() > 1

    coordinator_address = coordinator_address or os.environ.get(_ENV_COORD)
    if num_processes is None and os.environ.get(_ENV_NPROC):
        num_processes = int(os.environ[_ENV_NPROC])
    if process_id is None and os.environ.get(_ENV_PID):
        process_id = int(os.environ[_ENV_PID])

    # Pod metadata indicates a real multi-worker job only when more than
    # one worker hostname is listed (single-chip PJRT plugins also set
    # TPU_WORKER_HOSTNAMES, to "localhost").
    workers = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    on_multiworker_pod = (
        len([w for w in workers.split(",") if w.strip()]) > 1
        or "MEGASCALE_COORDINATOR_ADDRESS" in os.environ
    )
    if coordinator_address is None and not on_multiworker_pod:
        return False

    try:
        from spark_rapids_ml_tpu.obs import get_registry, span

        with span("multihost:initialize"):
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        get_registry().counter(
            "sparkml_multihost_init_total",
            "successful jax.distributed.initialize joins",
        ).inc()
    except RuntimeError:
        # Backend already initialized (a JAX call ran first). With an
        # explicit coordinator this is a real misuse — surface it; from
        # ambient pod metadata it just means single-process mode.
        if coordinator_address is not None:
            raise
        return False
    _initialized = True
    _initialized_coordinator = coordinator_address
    return jax.process_count() > 1


def global_data_mesh():
    """1-D ``data`` mesh over ALL devices across hosts.

    Device order follows ``jax.devices()`` (grouped by process), so each
    host's addressable shard of a mesh-sharded array corresponds to its
    local chips — the property ``host_local_shard`` relies on.
    """
    import jax

    from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), (DATA_AXIS,))


def process_info() -> dict:
    """Who am I in the job? (for logging / data-plane partition routing)."""
    import jax

    return {
        "process_id": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def host_local_shard(
    n_rows: int,
    process_id: Optional[int] = None,
    process_count: Optional[int] = None,
) -> slice:
    """The half-open row range this host should load, splitting ``n_rows``
    as evenly as possible across processes (earlier processes take the
    remainder — same convention as ``np.array_split``). ``process_id``/
    ``process_count`` default to the runtime's values.

    This is the data-plane contract: each host loads ONLY its slice, then
    the sharded fit runs one compiled program over the global mesh with
    ``jax.make_array_from_process_local_data``-style placement.
    """
    if process_id is None or process_count is None:
        import jax

        process_id = jax.process_index() if process_id is None else process_id
        process_count = (
            jax.process_count() if process_count is None else process_count
        )
    pid, pcount = process_id, process_count
    base, rem = divmod(n_rows, pcount)
    start = pid * base + min(pid, rem)
    stop = start + base + (1 if pid < rem else 0)
    return slice(start, stop)


def make_global_array(local_rows: np.ndarray, mesh, n_global_rows: int):
    """Assemble a globally-sharded array from per-process local rows.

    Single-process: a plain ``device_put`` with the mesh sharding.
    Multi-process: ``jax.make_array_from_process_local_data``, which places
    each host's rows on its local chips without any cross-host copy.
    """
    import time

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS

    sharding = NamedSharding(mesh, P(DATA_AXIS, None))
    nbytes = int(getattr(local_rows, "nbytes", 0))
    try:
        from spark_rapids_ml_tpu.obs import current_fit, get_registry

        get_registry().counter(
            "sparkml_bytes_placed_total",
            "host→device bytes placed onto the global mesh",
        ).inc(nbytes)
        current_fit().note(multihost_local_rows=int(local_rows.shape[0]))
    except Exception:
        pass
    t0 = time.perf_counter()
    if jax.process_count() == 1:
        out = jax.device_put(local_rows, sharding)
    else:
        out = jax.make_array_from_process_local_data(
            sharding, local_rows, (n_global_rows,) + local_rows.shape[1:]
        )
    t1 = time.perf_counter()
    try:
        # this host's placement seconds are the skew/straggler input:
        # each process reports its own seam time into the live FitRun,
        # and the driver's skew() compares them against the fleet median
        from spark_rapids_ml_tpu.obs import fitmon, spans

        spans.record_event(
            "multihost:placement", t0, t1,
            rows=int(local_rows.shape[0]), nbytes=nbytes,
        )
        run = fitmon.current_run()
        run.note_host_step(f"host{jax.process_index()}", t1 - t0)
        run.record_collective(
            "placement", nbytes=nbytes, count=1, seconds=t1 - t0
        )
    except Exception:
        pass
    return out
