"""Distributed brute-force KNN: items sharded over the mesh.

The item set is what grows (the fitted corpus); queries are small batches.
So items shard over the ``data`` axis, queries replicate, and the exact
global top-k comes from the standard two-level reduction: per-shard
``top_k`` of the local distance block, ``all_gather`` of the k candidates
per shard (k·n_shards rows per query — tiny), then a replicated merge
``top_k``. Communication per query batch is O(n_q·k·n_shards), never the
O(n_q·n_items) distance matrix; the heavy matmul stays shard-local on each
chip's MXU.

Local indices are offset to global item numbering inside the shard_map
(axis_index · items_per_shard), so the merged indices directly address the
original (pre-padding) item matrix.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.obs import (
    current_fit,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.ops.knn_kernel import knn_merge, pairwise_sqdist
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    collective_nbytes,
    pad_rows_to_multiple,
    row_sharding,
)


@partial(tracked_jit, static_argnames=("k", "mesh"))
def _sharded_knn(queries, items_padded, item_mask, k: int, mesh: Mesh):
    def per_shard(q, x_shard, mask_shard):
        d2 = pairwise_sqdist(q, x_shard, mask_shard)
        # A shard can contribute at most its own row count; min(k, rows)
        # keeps top_k legal for tiny shards and stays exact (when rows < k
        # the shard's ENTIRE item set becomes candidates). Global
        # candidate count n_shards·k_local ≥ k because k ≤ n_items.
        k_local = min(k, x_shard.shape[0])
        neg, idx = lax.top_k(-d2, k_local)
        offset = lax.axis_index(DATA_AXIS) * x_shard.shape[0]
        gidx = idx + offset
        # gather candidates from every shard, then merge on each replica
        # (knn_merge = the shared two-level reduction; one implementation
        # so sign/tie semantics can't drift between call sites)
        all_d = lax.all_gather(-neg, DATA_AXIS, axis=1, tiled=True)
        all_i = lax.all_gather(gidx, DATA_AXIS, axis=1, tiled=True)
        return knn_merge(all_d, all_i, k)

    # check_vma=False: after the all_gather every shard holds the SAME
    # candidate set and runs the same deterministic merge, so the outputs
    # are replicated by construction — but the static varying-mesh-axes
    # analysis can't prove it through axis_index/top_k/take_along_axis.
    return jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS, None), P(DATA_AXIS)),
        out_specs=(P(), P()),
        check_vma=False,
    )(queries, items_padded, item_mask)


@fit_instrumentation("distributed_knn")
def distributed_kneighbors(
    queries: np.ndarray,
    items: np.ndarray,
    k: int,
    mesh: Mesh,
    dtype=jnp.float32,
) -> Tuple[np.ndarray, np.ndarray]:
    """Exact global (distances, indices) with items sharded over ``mesh``.

    Pads items to the shard multiple with masked (+inf-distance) rows, so
    uneven corpora never recompile or bias results.
    """
    n_items = items.shape[0]
    if not (1 <= k <= n_items):
        raise ValueError(f"k = {k} must be in [1, {n_items}]")
    n_shards = int(np.prod(mesh.devices.shape))
    items_p, mask = pad_rows_to_multiple(
        np.asarray(items, dtype=np.dtype(dtype)), n_shards
    )
    sharding = row_sharding(mesh)
    items_dev = jax.device_put(jnp.asarray(items_p), sharding)
    mask_dev = jax.device_put(
        jnp.asarray(mask, dtype=items_dev.dtype), NamedSharding(mesh, P(DATA_AXIS))
    )
    q_dev = jax.device_put(
        jnp.asarray(np.asarray(queries, dtype=np.dtype(dtype))),
        NamedSharding(mesh, P()),
    )
    ctx = current_fit()
    n_q = np.asarray(queries).shape[0]
    # two all_gathers of the per-shard top-k candidates: (q, k·D) distances
    # + (q, k·D) global indices
    ctx.record_collective(
        "all_gather",
        nbytes=collective_nbytes((n_q, k * n_shards), items_dev.dtype))
    ctx.record_collective(
        "all_gather",
        nbytes=collective_nbytes((n_q, k * n_shards), np.int32))
    with ctx.phase("execute"):
        d, i = _sharded_knn(q_dev, items_dev, mask_dev, k, mesh)
    return (
        np.sqrt(np.maximum(np.asarray(d), 0.0)),
        np.asarray(i, dtype=np.int64),
    )
