"""Distributed LinearSVC over the mesh.

Same shape as the other distributed fits: rows sharded over ``data``,
per-shard squared-hinge partials, one fused ``psum`` per generalized-
Newton iteration INSIDE the compiled while_loop, replicated (n+1)²
solve — filling the ``reduce_fn`` slot ``ops/svm_kernel.py`` declares.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.obs import (
    current_fit,
    current_run,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.ops.svm_kernel import SvcResult, svc_newton_iterations
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    collective_nbytes,
    pad_rows_to_multiple,
    row_sharding,
)


@partial(
    tracked_jit,
    static_argnames=("mesh", "fit_intercept", "max_iter"),
)
def distributed_svc_fit_kernel(
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    *,
    mesh: Mesh,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
    max_iter: int = 100,
    tol: float = 1e-8,
) -> SvcResult:
    def shard_fn(x_shard, y_shard, mask_shard):
        return tuple(
            svc_newton_iterations(
                x_shard, y_shard, mask_shard,
                reg_param, fit_intercept, max_iter, tol,
                reduce_fn=lambda t: jax.lax.psum(t, DATA_AXIS),
            )
        )

    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P(), P(), P()),
    )
    coef, intercept, n_iter, converged = fn(x, y, mask)
    return SvcResult(coef, intercept, n_iter, converged)


@fit_instrumentation("distributed_svc")
def distributed_svc_fit(
    x_host: np.ndarray,
    y_host: np.ndarray,
    mesh: Mesh,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
    max_iter: int = 100,
    tol: float = 1e-8,
    dtype=None,
) -> SvcResult:
    ctx = current_fit()
    x_host = np.asarray(x_host)
    y_host = np.asarray(y_host).reshape(-1)
    n_dev = mesh.devices.size
    with ctx.phase("prepare"):
        x_padded, mask = pad_rows_to_multiple(x_host, n_dev)
        y_padded = np.zeros(x_padded.shape[0], dtype=y_host.dtype)
        y_padded[: y_host.shape[0]] = y_host
        if dtype is not None:
            x_padded = x_padded.astype(dtype)
            y_padded = y_padded.astype(dtype)
            mask = mask.astype(dtype)
    with ctx.phase("placement"):
        x_dev = jax.device_put(x_padded, row_sharding(mesh))
        shard1 = NamedSharding(mesh, P(DATA_AXIS))
        y_dev = jax.device_put(y_padded, shard1)
        mask_dev = jax.device_put(mask, shard1)
    with ctx.phase("execute"), current_run().step(
        "newton", rows=x_host.shape[0]
    ) as step:
        result = jax.block_until_ready(
            distributed_svc_fit_kernel(
                x_dev, y_dev, mask_dev,
                mesh=mesh, reg_param=reg_param, fit_intercept=fit_intercept,
                max_iter=max_iter, tol=tol,
            )
        )
        step.note(n_iter=int(result[2]), converged=int(result[3]))
    # one fused psum of (gradient, Hessian) per generalized-Newton iteration
    d = x_host.shape[1] + (1 if fit_intercept else 0)
    n_iter = int(result[2])
    ctx.set_iterations(n_iter)
    ctx.record_collective(
        "all_reduce", nbytes=collective_nbytes((d * d + d,), x_padded.dtype),
        count=max(n_iter, 1),
    )
    return result
