"""Distributed gradient-boosted trees: rows sharded, histograms psum'd.

Boosting composes with the sharded histogram grower the same way the
RandomForest does (``distributed_forest``): each boosting iteration grows
ONE regression tree on the current residuals with rows sharded over the
mesh — per-shard (count, Σr, Σr²) level histograms, one ``psum`` per
level, replicated split selection — and each shard keeps its own rows'
leaf assignments, so the margin update f += lr·leaf[leaf_ids] never moves
a data row. The driver-side work per iteration is the elementwise
residual/hessian update and (for classification) the Newton leaf refit
from per-leaf weight sums — O(n) and O(2^depth).

Fills the VERDICT r2 gap "GBT has no distributed fit"; semantics match
``models/gbt.py`` exactly (same residuals, same Newton leaf refit, same
Spark subsamplingRate convention).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.obs import (
    current_fit,
    current_run,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.ops.forest_kernel import (
    TreeEnsemble,
    grow_tree_regression,
    quantile_bins,
)
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    collective_nbytes,
    pad_rows_to_multiple,
)


@partial(
    tracked_jit,
    static_argnames=("max_depth", "n_bins", "min_leaf", "mesh"),
)
def _sharded_grow_with_leaf_ids(
    binned, r, w, feat_mask, max_depth, n_bins, min_leaf, mesh,
):
    def per_shard(b, rr, ww, fm):
        return grow_tree_regression(
            b, rr, ww, fm, max_depth, n_bins, min_leaf,
            axis_name=DATA_AXIS, return_leaf_ids=True,
        )

    return jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS), P()),
        # tree structure replicated; leaf ids stay with their shard's rows
        out_specs=(P(), P(), P(), P(), P(DATA_AXIS)),
        check_vma=False,
    )(binned, r, w, feat_mask)


@fit_instrumentation("distributed_gbt")
def distributed_gbt_fit(
    x: np.ndarray,
    y: np.ndarray,
    mesh: Mesh,
    max_iter: int = 20,
    max_depth: int = 5,
    n_bins: int = 32,
    min_leaf: int = 1,
    step_size: float = 0.1,
    classification: bool = False,
    subsampling_rate: float = 1.0,
    seed: int = 0,
    dtype=jnp.float32,
) -> Tuple[TreeEnsemble, np.ndarray, float, np.ndarray]:
    """(ensemble, bin_edges, init_margin, split_gains) — the triple the
    local GBT model consumes plus the per-node gains for
    ``ops.forest_kernel.feature_importances``, fitted with rows sharded
    over ``mesh``."""
    from spark_rapids_ml_tpu.models.gbt import boosting_loop, gbt_init_margin

    n_dev = int(np.prod(mesh.devices.shape))
    x = np.asarray(x)
    y = np.asarray(y, dtype=np.float64).reshape(-1)
    n, d = x.shape
    if y.shape[0] != n:
        raise ValueError(f"labels length {y.shape[0]} != rows {n}")
    binned_np, edges = quantile_bins(x, n_bins)
    binned_p, mask = pad_rows_to_multiple(binned_np, n_dev)
    y_p = np.zeros(binned_p.shape[0])
    y_p[:n] = y
    rng = np.random.default_rng(seed)

    row_shard = NamedSharding(mesh, P(DATA_AXIS, None))
    vec_shard = NamedSharding(mesh, P(DATA_AXIS))
    binned_dev = jax.device_put(
        jnp.asarray(binned_p, dtype=jnp.int32), row_shard
    )
    full_mask = jnp.asarray(np.ones((max_depth, d)), dtype=dtype)

    init = gbt_init_margin(y, classification)

    ctx = current_fit()
    # per boosted tree, one (count, Σr, Σr²) histogram psum per depth level
    hist_nbytes = collective_nbytes(
        (3, 2 ** max_depth, d, n_bins), np.dtype(dtype))

    def grow_fn(r, w):
        ctx.record_collective(
            "all_reduce", nbytes=hist_nbytes, count=max_depth)
        # the np.asarray conversions block on the grown tree, so the
        # step's wall time covers the full boosted-tree growth
        with current_run().step("boost_tree", rows=n):
            ft, tt, leaf, g_tree, leaf_ids_dev = \
                _sharded_grow_with_leaf_ids(
                    binned_dev,
                    jax.device_put(jnp.asarray(r, dtype=dtype),
                                   vec_shard),
                    jax.device_put(jnp.asarray(w, dtype=dtype),
                                   vec_shard),
                    full_mask, max_depth, n_bins, min_leaf, mesh,
                )
            return (np.asarray(ft), np.asarray(tt), np.asarray(leaf),
                    np.asarray(g_tree), np.asarray(leaf_ids_dev))

    ensemble, gains = boosting_loop(
        y_padded=y_p, mask=mask, n_real=n, init=init, max_iter=max_iter,
        step_size=step_size, classification=classification,
        subsampling_rate=subsampling_rate, rng=rng, max_depth=max_depth,
        grow_fn=grow_fn,
    )
    return ensemble, edges, init, gains
