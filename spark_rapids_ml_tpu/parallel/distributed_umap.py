"""Distributed UMAP optimizer: force panels + edge slices over the mesh.

The blocked single-device optimizer (``ops.umap_kernel.
optimize_embedding_blocked``) splits forces by support — sparse-edge
attraction, row-panel streamed repulsion. Here the same decomposition
runs SPMD: the embedding is replicated (n×dim — tiny), each device owns
one row panel of the all-pairs repulsion and one slice of the symmetric
edge list, and each epoch exchanges one ``all_gather`` of repulsion
panels plus one ``psum`` of edge-force partials — O(n·dim) traffic per
epoch, never a distance matrix. The math is identical to the blocked
kernel, so single- and multi-device runs agree to reduction-order
rounding.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.obs import (
    current_fit,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.ops.knn_kernel import pairwise_sqdist
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    collective_nbytes,
    pad_rows_to_multiple,
)


@partial(tracked_jit, static_argnames=("n_epochs", "mesh"))
def _sharded_umap_optimize(
    edge_i, edge_j, edge_p, edge_mask,   # (n_dev·e_per,) padded edge slices
    emb0, valid,                          # replicated (n_pad, dim), (n_pad,)
    a, b, learning_rate, repulsion_strength,
    n_epochs: int,
    mesh: Mesh,
):
    n = emb0.shape[0]
    dt = emb0.dtype
    n_dev = int(np.prod(mesh.devices.shape))
    rows_per = n // n_dev
    eps = jnp.asarray(1e-3, dt)
    valid_f = valid.astype(dt)

    def per_shard(ei, ej, ep, em):
        idx0 = lax.axis_index(DATA_AXIS) * rows_per

        def epoch(i, y):
            yp = lax.dynamic_slice_in_dim(y, idx0, rows_per)
            d2 = pairwise_sqdist(yp, y)
            d2b = jnp.power(jnp.maximum(d2, 1e-12), b)
            w = jnp.clip(
                (2.0 * repulsion_strength * b)
                / ((eps + d2) * (1.0 + a * d2b)),
                0.0,
                1e4,
            ) * valid_f[None, :]
            f_rep_local = jnp.sum(w, axis=1)[:, None] * yp - w @ y
            f_rep = lax.all_gather(
                f_rep_local, DATA_AXIS, axis=0, tiled=True
            )

            yi, yj = y[ei], y[ej]
            ed2 = jnp.sum((yi - yj) ** 2, axis=1)
            ed2b = jnp.power(jnp.maximum(ed2, 1e-12), b)
            denom = 1.0 + a * ed2b
            w_att = jnp.clip(
                ep * (-2.0 * a * b * ed2b / jnp.maximum(ed2, 1e-12))
                / denom,
                -1e4,
                0.0,
            )
            w_rep_corr = -jnp.clip(
                ep * (2.0 * repulsion_strength * b) / ((eps + ed2) * denom),
                0.0,
                1e4,
            )
            w_edge = ((w_att + w_rep_corr) * em)[:, None] * (yi - yj)
            f_att_partial = (
                jax.ops.segment_sum(w_edge, ei, num_segments=n)
                - jax.ops.segment_sum(w_edge, ej, num_segments=n)
            )
            f_att = lax.psum(f_att_partial, DATA_AXIS)

            force = f_rep + f_att
            alpha = learning_rate * (1.0 - i / n_epochs)
            return y + jnp.clip(alpha * force, -4.0, 4.0)

        return lax.fori_loop(0, n_epochs, epoch, emb0)

    return jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(),
        check_vma=False,
    )(edge_i, edge_j, edge_p, edge_mask)


@fit_instrumentation("distributed_umap")
def distributed_umap_optimize(
    edge_i: np.ndarray,
    edge_j: np.ndarray,
    edge_p: np.ndarray,
    emb0: np.ndarray,
    mesh: Mesh,
    a: float,
    b: float,
    learning_rate: float = 1.0,
    repulsion_strength: float = 1.0,
    n_epochs: int = 200,
    dtype=jnp.float32,
) -> np.ndarray:
    """Optimize a UMAP embedding over ``mesh`` from a symmetric edge list
    (``ops.umap_kernel.symmetric_edge_list``) and an init (e.g.
    ``pca_init``). Returns the optimized (n, dim) embedding."""
    n_dev = int(np.prod(mesh.devices.shape))
    emb_pad, row_mask = pad_rows_to_multiple(
        np.asarray(emb0, dtype=np.dtype(dtype)), n_dev
    )
    valid = row_mask > 0
    ei, e_mask = pad_rows_to_multiple(
        np.asarray(edge_i, dtype=np.int32), n_dev
    )
    ej, _ = pad_rows_to_multiple(np.asarray(edge_j, dtype=np.int32), n_dev)
    ep, _ = pad_rows_to_multiple(
        np.asarray(edge_p, dtype=np.dtype(dtype)), n_dev
    )
    ctx = current_fit()
    ctx.set_data(rows=np.asarray(emb0).shape[0],
                 features=np.asarray(emb0).shape[1])
    ctx.set_iterations(n_epochs)
    # per epoch: one all_gather of repulsion panels + one psum of the
    # edge-force partials, each O(n·dim)
    emb_nbytes = collective_nbytes(emb_pad.shape, dtype)
    ctx.record_collective("all_gather", nbytes=emb_nbytes, count=n_epochs)
    ctx.record_collective("all_reduce", nbytes=emb_nbytes, count=n_epochs)
    shard1 = NamedSharding(mesh, P(DATA_AXIS))
    repl = NamedSharding(mesh, P())
    out = _sharded_umap_optimize(
        jax.device_put(jnp.asarray(ei), shard1),
        jax.device_put(jnp.asarray(ej), shard1),
        jax.device_put(jnp.asarray(ep), shard1),
        jax.device_put(jnp.asarray(e_mask, dtype=np.dtype(dtype)), shard1),
        jax.device_put(jnp.asarray(emb_pad), repl),
        jax.device_put(jnp.asarray(valid), repl),
        jnp.asarray(a, dtype=np.dtype(dtype)),
        jnp.asarray(b, dtype=np.dtype(dtype)),
        jnp.asarray(learning_rate, dtype=np.dtype(dtype)),
        jnp.asarray(repulsion_strength, dtype=np.dtype(dtype)),
        n_epochs,
        mesh,
    )
    return np.asarray(out, dtype=np.float64)[: np.asarray(emb0).shape[0]]
