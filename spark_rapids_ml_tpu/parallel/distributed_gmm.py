"""Distributed GaussianMixture over the mesh.

EM with the data plane inverted the TPU way: rows sharded over
``data``, each iteration's E-step runs as batched per-component MXU
matmuls on every shard simultaneously with ONE fused ``psum`` of the
GmmStats tuple (Σr, Σr·x, Σr·xxᵀ, loglik, w_sum) — the small host
M-step (k Cholesky factorizations of d×d covariances) and the
mean-loglik convergence rule reuse the ONE EM driver loop every other
GMM path shares (``models/gaussian_mixture.py::_fit_from_stepper``),
so the mesh fit, the local fit, the streamed fit, and the Spark-plane
fit all walk identical driver code over different statistics planes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.obs import (
    current_fit,
    current_run,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.ops.gmm_kernel import (
    GmmStats,
    estep_stats_math,
    init_params,
)
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    collective_nbytes,
    pad_rows_to_multiple,
    row_sharding,
)


@partial(tracked_jit, static_argnames=("mesh",))
def distributed_gmm_stats_kernel(
    x: jnp.ndarray,
    w: jnp.ndarray,
    means: jnp.ndarray,
    prec_chol: jnp.ndarray,
    log_det: jnp.ndarray,
    log_weights: jnp.ndarray,
    *,
    mesh: Mesh,
) -> GmmStats:
    """One EM pass's sufficient statistics over the whole mesh.

    Padding rows ride in with weight 0 (the E-step scales every
    statistic by ``w_prior``), so no masking logic beyond the weight
    vector is needed."""

    def shard_fn(xs, ws, m, p, ld, lw):
        stats = estep_stats_math(jnp, xs, ws, m, p, ld, lw)
        return tuple(lax.psum(t, DATA_AXIS) for t in stats)

    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(), P(), P(), P()),
        out_specs=(P(), P(), P(), P(), P()),
    )
    return GmmStats(*fn(x, w, means, prec_chol, log_det, log_weights))


@fit_instrumentation("distributed_gmm")
def distributed_gmm_fit(
    x_host: np.ndarray,
    k: int,
    mesh: Mesh,
    max_iter: int = 100,
    tol: float = 1e-3,
    seed: int = 0,
    reg: float = 1e-6,
    weights: np.ndarray = None,
    dtype=None,
):
    """Host-side driver: pad + shard once, run EM with the sharded
    statistics kernel. Returns the standard ``GaussianMixtureModel``
    (same class every other fit path produces)."""
    from spark_rapids_ml_tpu.models.gaussian_mixture import (
        GaussianMixture,
    )
    from spark_rapids_ml_tpu.utils.timing import PhaseTimer

    x_host = np.asarray(x_host, dtype=np.float64)
    n_rows = x_host.shape[0]
    if n_rows < k:
        raise ValueError(f"k={k} components need at least k rows")
    w_host = (np.ones(n_rows) if weights is None
              else np.asarray(weights, dtype=np.float64).reshape(-1))
    n_dev = mesh.devices.size
    x_padded, mask = pad_rows_to_multiple(x_host, n_dev)
    w_padded = np.zeros(x_padded.shape[0])
    w_padded[:n_rows] = w_host          # padding rows carry weight 0
    dt = jnp.float32 if dtype is None else dtype
    x_dev = jax.device_put(
        np.asarray(x_padded, dtype=np.dtype(dt)), row_sharding(mesh))
    w_dev = jax.device_put(
        np.asarray(w_padded, dtype=np.dtype(dt)),
        NamedSharding(mesh, P(DATA_AXIS)),
    )

    ctx = current_fit()
    d = x_host.shape[1]
    # one fused psum of GmmStats (Σr, Σr·x, Σr·xxᵀ, loglik, w_sum) per
    # EM pass — recorded per actual stepper invocation
    step_nbytes = collective_nbytes(
        (k + k * d + k * d * d + 2,), np.dtype(dt))

    def stepper(means, prec, log_det, log_w):
        ctx.record_collective("all_reduce", nbytes=step_nbytes)
        # host→float64 conversion blocks on the result, so the step's
        # wall time covers the full E-step pass, not just the dispatch
        with current_run().step("em_pass", rows=n_rows):
            out = distributed_gmm_stats_kernel(
                x_dev, w_dev,
                jnp.asarray(means, dtype=dt),
                jnp.asarray(prec, dtype=dt),
                jnp.asarray(log_det, dtype=dt),
                jnp.asarray(log_w, dtype=dt),
                mesh=mesh,
            )
            return GmmStats(
                *(np.asarray(v, dtype=np.float64) for v in out))

    est = GaussianMixture()
    est.set("k", int(k))
    est.set("maxIter", int(max_iter))
    est.set("tol", float(tol))
    est.set("seed", int(seed))
    est.set("regParam", float(reg))
    init = init_params(x_host, w_host, k, int(seed))
    return est._fit_from_stepper(stepper, init, PhaseTimer())
