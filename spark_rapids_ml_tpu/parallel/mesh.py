"""Device mesh construction and sharding helpers.

The reference's "cluster" is Spark executors each owning one GPU, with
device assignment via ``spark.executor.resource.gpu`` task resources
(``RapidsRowMatrix.scala:171-175``) and ALL cross-device communication done
by shipping JVM-serialized matrices to the driver
(``RapidsRowMatrix.scala:202``). The TPU-native replacement is a
``jax.sharding.Mesh``: devices are first-class, data is laid out with named
shardings, and XLA compiles the collectives onto ICI/DCN.

Axis convention: ``data`` — rows (samples) are sharded across it; model
state (covariance, components) is replicated. A second ``feature`` axis is
reserved for sharding the n×n Gram when n is too large for one device
(SURVEY.md §5 "feature-dimension scaling" stretch goal).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
FEATURE_AXIS = "feature"


def _install_shard_map_compat() -> None:
    """Expose ``jax.shard_map`` and ``jax.lax.axis_size`` on older jax
    (< 0.5), where shard_map lives at ``jax.experimental.shard_map`` and
    the replication-check kwarg is ``check_rep`` rather than ``check_vma``.
    Every driver in this package imports this module, so the aliases are
    installed before any call site runs."""
    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            # old jax: the axis frame IS the (static) size
            import jax.core as core

            return core.axis_frame(axis_name)

        jax.lax.axis_size = axis_size
    if hasattr(jax, "shard_map"):
        return
    try:
        from jax.experimental.shard_map import shard_map as _shard_map
    except ImportError:  # pragma: no cover - very old jax; let call sites fail
        return

    def shard_map(f, *args, **kwargs):
        kwargs.pop("check_vma", None)
        # the old static replication checker lacks rules for while/argmax
        # the kernels here rely on (newer jax proves them); disable it —
        # out_specs still declare the contract
        kwargs["check_rep"] = False
        return _shard_map(f, *args, **kwargs)

    jax.shard_map = shard_map


_install_shard_map_compat()


def device_count() -> int:
    return len(jax.devices())


def data_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """1-D mesh over the ``data`` axis (data-parallel partial aggregation —
    the only parallelism the workload needs for parity, SURVEY.md §2)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if n_devices > len(devices):
                raise ValueError(
                    f"requested {n_devices} devices, {len(devices)} visible"
                )
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def grid_mesh(n_data: int, n_feature: int) -> Mesh:
    """2-D (data × feature) mesh for the sharded-Gram stretch path."""
    devices = jax.devices()
    need = n_data * n_feature
    if need > len(devices):
        raise ValueError(f"requested {need} devices, {len(devices)} visible")
    grid = np.asarray(devices[:need]).reshape(n_data, n_feature)
    return Mesh(grid, (DATA_AXIS, FEATURE_AXIS))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded over ``data``; feature dim replicated."""
    return NamedSharding(mesh, P(DATA_AXIS, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_shape(mesh: Mesh) -> dict:
    """Axes/shape/device summary for fit reports and logs."""
    first = mesh.devices.flat[0]
    return {
        "axes": tuple(str(a) for a in mesh.axis_names),
        "shape": tuple(int(s) for s in mesh.devices.shape),
        "devices": int(mesh.devices.size),
        "platform": getattr(first, "platform", "unknown"),
    }


def collective_nbytes(shape, dtype) -> int:
    """Payload bytes of one collective operand of ``shape``/``dtype`` —
    the unit every driver's program-level collective accounting
    (``FitContext.record_collective``) is declared in."""
    return int(np.prod([int(s) for s in shape], dtype=np.int64)) * np.dtype(
        dtype
    ).itemsize


def pad_rows_to_multiple(x: np.ndarray, multiple: int):
    """Pad rows so the leading dim divides the mesh; returns (padded, mask).

    XLA shardings need equal per-device extents; uneven partitions are
    padded and masked rather than recompiled (the Spark analogue is
    variable-size partitions, which the reference handles by per-partition
    dynamic shapes — a non-option under jit).
    """
    n = x.shape[0]
    rem = (-n) % multiple
    mask = np.ones(n + rem, dtype=x.dtype if np.issubdtype(x.dtype, np.floating) else np.float64)
    if rem:
        x = np.concatenate([x, np.zeros((rem,) + x.shape[1:], dtype=x.dtype)])
        mask[n:] = 0.0
    return x, mask
