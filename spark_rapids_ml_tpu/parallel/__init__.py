from spark_rapids_ml_tpu.parallel.mesh import data_mesh, device_count, grid_mesh
from spark_rapids_ml_tpu.parallel.distributed_pca import (
    distributed_pca_fit,
    distributed_pca_fit_kernel,
)
from spark_rapids_ml_tpu.parallel.distributed_knn import (
    distributed_kneighbors,
)
from spark_rapids_ml_tpu.parallel.distributed_ivf import (
    distributed_ivf_search,
)
from spark_rapids_ml_tpu.parallel.distributed_dbscan import (
    distributed_dbscan_labels,
)
from spark_rapids_ml_tpu.parallel.distributed_umap import (
    distributed_umap_optimize,
)
from spark_rapids_ml_tpu.parallel.distributed_forest import (
    distributed_forest_fit,
)
from spark_rapids_ml_tpu.parallel.distributed_gbt import (
    distributed_gbt_fit,
)
from spark_rapids_ml_tpu.parallel.distributed_bisecting import (
    BisectingKMeansResult,
    distributed_bisecting_kmeans_fit,
)
from spark_rapids_ml_tpu.parallel.distributed_gmm import (
    distributed_gmm_fit,
    distributed_gmm_stats_kernel,
)
from spark_rapids_ml_tpu.parallel.distributed_nb import (
    distributed_nb_fit,
)
from spark_rapids_ml_tpu.parallel.distributed_pic import (
    distributed_pic_assign,
)
from spark_rapids_ml_tpu.parallel.distributed_glm import (
    distributed_glm_fit,
)
from spark_rapids_ml_tpu.parallel.distributed_word2vec import (
    distributed_word2vec_fit,
)
from spark_rapids_ml_tpu.parallel.distributed_optim import (
    distributed_aft_fit,
    distributed_fm_fit,
    distributed_minimize_kernel,
    distributed_mlp_fit,
)
from spark_rapids_ml_tpu.parallel.distributed_kmeans import (
    distributed_kmeans_fit,
    distributed_kmeans_fit_kernel,
)
from spark_rapids_ml_tpu.parallel.distributed_als import (
    distributed_als_fit,
)
from spark_rapids_ml_tpu.parallel.distributed_lda import (
    distributed_lda_fit,
)
from spark_rapids_ml_tpu.parallel.distributed_linreg import (
    distributed_linreg_fit,
    distributed_linreg_fit_kernel,
)
from spark_rapids_ml_tpu.parallel.distributed_logreg import (
    distributed_logreg_fit,
    distributed_logreg_fit_kernel,
)
from spark_rapids_ml_tpu.parallel.distributed_svc import (
    distributed_svc_fit,
    distributed_svc_fit_kernel,
)
from spark_rapids_ml_tpu.parallel.feature_sharded import (
    feature_sharded_covariance_kernel,
    feature_sharded_pca_fit,
)

__all__ = [
    "data_mesh",
    "device_count",
    "grid_mesh",
    "distributed_pca_fit",
    "distributed_pca_fit_kernel",
    "distributed_kneighbors",
    "distributed_ivf_search",
    "distributed_bisecting_kmeans_fit",
    "distributed_dbscan_labels",
    "distributed_aft_fit",
    "distributed_fm_fit",
    "distributed_glm_fit",
    "distributed_gmm_fit",
    "distributed_mlp_fit",
    "distributed_nb_fit",
    "distributed_pic_assign",
    "distributed_word2vec_fit",
    "distributed_gmm_stats_kernel",
    "BisectingKMeansResult",
    "distributed_minimize_kernel",
    "distributed_umap_optimize",
    "distributed_forest_fit",
    "distributed_gbt_fit",
    "distributed_kmeans_fit",
    "distributed_kmeans_fit_kernel",
    "distributed_als_fit",
    "distributed_lda_fit",
    "distributed_linreg_fit",
    "distributed_linreg_fit_kernel",
    "distributed_logreg_fit",
    "distributed_logreg_fit_kernel",
    "distributed_svc_fit",
    "distributed_svc_fit_kernel",
    "feature_sharded_covariance_kernel",
    "feature_sharded_pca_fit",
]
