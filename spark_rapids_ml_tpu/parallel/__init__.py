from spark_rapids_ml_tpu.parallel.mesh import data_mesh, device_count
from spark_rapids_ml_tpu.parallel.distributed_pca import (
    distributed_pca_fit,
    distributed_pca_fit_kernel,
)

__all__ = [
    "data_mesh",
    "device_count",
    "distributed_pca_fit",
    "distributed_pca_fit_kernel",
]
