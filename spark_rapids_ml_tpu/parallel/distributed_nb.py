"""Distributed NaiveBayes over the mesh.

The purest fit in the family for data parallelism: per-class
sufficient statistics (Σ one-hot, one-hotᵀ·X, one-hotᵀ·X²) are three
MXU contractions per shard plus ONE fused ``psum`` — then the
per-family closed forms reuse ``aggregate.finalize_nb_from_stats``
(the single copy the local fit and the Spark statistics plane already
share, so all three paths cannot drift).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.obs import (
    current_fit,
    current_run,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    collective_nbytes,
    pad_rows_to_multiple,
    row_sharding,
)


@partial(tracked_jit, static_argnames=("mesh", "need_sq"))
def distributed_nb_stats_kernel(
    x: jnp.ndarray,
    y_oh: jnp.ndarray,
    *,
    mesh: Mesh,
    need_sq: bool,
):
    """Global (counts, Σx per class, Σx² per class): one program.
    Padding rows carry an all-zero one-hot row and contribute nothing."""

    def shard_fn(xs, oh):
        def dot_t(a, b):
            return lax.dot_general(
                a, b, (((0,), (0,)), ((), ())),
                precision=lax.Precision.HIGHEST,
            )

        counts = lax.psum(oh.sum(axis=0), DATA_AXIS)
        sums = lax.psum(dot_t(oh, xs), DATA_AXIS)
        sq = (lax.psum(dot_t(oh, xs * xs), DATA_AXIS)
              if need_sq else jnp.zeros_like(sums))
        return counts, sums, sq

    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None)),
        out_specs=(P(), P(), P()),
    )
    return fn(x, y_oh)


@fit_instrumentation("distributed_nb")
def distributed_nb_fit(
    x_host: np.ndarray,
    y_host: np.ndarray,
    mesh: Mesh,
    model_type: str = "multinomial",
    smoothing: float = 1.0,
    weights: np.ndarray = None,
    dtype=jnp.float32,
):
    """Host-side driver. Returns the standard ``NaiveBayesModel`` (same
    class the local fit and the Spark plane produce)."""
    from spark_rapids_ml_tpu.models.naive_bayes import (
        NaiveBayesModel,
        _prepare_nb_inputs,
    )
    from spark_rapids_ml_tpu.spark.aggregate import (
        finalize_nb_from_stats,
    )

    x_host = np.asarray(x_host)
    classes, y_oh = _prepare_nb_inputs(x_host, y_host, weights,
                                       model_type)

    n_dev = mesh.devices.size
    x_padded, _mask = pad_rows_to_multiple(x_host, n_dev)
    oh_padded = np.zeros((x_padded.shape[0], classes.size))
    oh_padded[: y_oh.shape[0]] = y_oh
    x_dev = jax.device_put(
        np.asarray(x_padded, dtype=np.dtype(dtype)), row_sharding(mesh))
    oh_dev = jax.device_put(
        np.asarray(oh_padded, dtype=np.dtype(dtype)),
        NamedSharding(mesh, P(DATA_AXIS, None)),
    )
    ctx = current_fit()
    n_classes, n_feat = classes.size, x_host.shape[1]
    need_sq = model_type == "gaussian"
    # fused psum of (counts, Σx per class[, Σx² per class])
    ctx.record_collective(
        "all_reduce",
        nbytes=collective_nbytes(
            (n_classes * (1 + n_feat * (2 if need_sq else 1)),), dtype
        ),
    )
    with ctx.phase("execute"), current_run().step(
        "class_stats", rows=x_host.shape[0]
    ):
        counts, sums, sq = jax.block_until_ready(
            distributed_nb_stats_kernel(
                x_dev, oh_dev, mesh=mesh, need_sq=need_sq)
        )
    pi, theta, sigma = finalize_nb_from_stats(
        classes,
        np.asarray(counts, dtype=np.float64),
        np.asarray(sums, dtype=np.float64),
        np.asarray(sq, dtype=np.float64),
        model_type, float(smoothing),
    )
    model = NaiveBayesModel(pi=pi, theta=theta, sigma=sigma,
                            classes=classes)
    model.set("modelType", model_type)
    model.set("smoothing", float(smoothing))
    return model
