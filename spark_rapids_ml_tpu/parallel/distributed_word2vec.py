"""Distributed Word2Vec over the mesh.

Skip-gram negative sampling with the PAIR axis sharded: embedding
tables stay replicated, each step shards its (center, context) batch
over ``data``, every shard draws its own negatives (per-shard folded
key) and accumulates dense gradient + occurrence-count tables, and ONE
fused ``psum`` merges them before the replicated table update — the
exact global equivalent of the single-device kernel's
per-row-count-normalized step (``ops/word2vec_kernel.py``), so the
distributed update rule is the local one computed over the union of
shards. Corpus prep (vocabulary, dynamic-window pairs) reuses
``models.word2vec.prepare_corpus`` — the single shared copy.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.obs import (
    current_fit,
    current_run,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, collective_nbytes


@partial(tracked_jit, donate_argnums=(0, 1),
         static_argnames=("mesh", "k_neg"))
def distributed_sgns_step_kernel(
    u: jnp.ndarray,
    v: jnp.ndarray,
    c_idx: jnp.ndarray,
    ctx_idx: jnp.ndarray,
    key: jax.Array,
    lr: jnp.ndarray,
    noise_logits: jnp.ndarray,
    *,
    mesh: Mesh,
    k_neg: int,
):
    """One SGNS step over a mesh-sharded pair batch. Tables are donated
    (one replicated (vocab, dim) pair resident per table for the whole
    run) and updated identically on every shard from the psum'd global
    gradient/count tables."""

    def shard_fn(u_r, v_r, ci, xi, key_r, lr_r, nl_r):
        j = lax.axis_index(DATA_AXIS)
        sub = jax.random.fold_in(key_r, j)
        negs = jax.random.categorical(
            sub, nl_r, shape=(ci.shape[0], k_neg))
        uc = u_r[ci]                                  # (b/P, d)
        vpos = v_r[xi]
        vneg = v_r[negs]                              # (b/P, K, d)
        pos_score = jnp.sum(uc * vpos, axis=-1)
        neg_score = jnp.einsum("bd,bkd->bk", uc, vneg)
        gpos = jax.nn.sigmoid(pos_score) - 1.0
        gneg = jax.nn.sigmoid(neg_score)
        guc = gpos[:, None] * vpos \
            + jnp.einsum("bk,bkd->bd", gneg, vneg)
        loss_local = -(jax.nn.log_sigmoid(pos_score).sum()
                       + jax.nn.log_sigmoid(-neg_score).sum())

        ones = jnp.ones_like(ci, dtype=u_r.dtype)
        vocab = u_r.shape[0]
        gu = jnp.zeros_like(u_r).at[ci].add(guc)
        cu = jnp.zeros((vocab,), u_r.dtype).at[ci].add(ones)
        neg_flat = negs.reshape(-1)
        gv = (jnp.zeros_like(v_r)
              .at[xi].add(gpos[:, None] * uc)
              .at[neg_flat].add(
                  (gneg[..., None] * uc[:, None, :])
                  .reshape(-1, uc.shape[1])))
        cv = (jnp.zeros((vocab,), v_r.dtype)
              .at[xi].add(ones)
              .at[neg_flat].add(1.0))

        gu = lax.psum(gu, DATA_AXIS)
        cu = jnp.maximum(lax.psum(cu, DATA_AXIS), 1.0)
        gv = lax.psum(gv, DATA_AXIS)
        cv = jnp.maximum(lax.psum(cv, DATA_AXIS), 1.0)
        loss = lax.psum(loss_local, DATA_AXIS)
        u_new = u_r - lr_r * gu / cu[:, None]
        v_new = v_r - lr_r * gv / cv[:, None]
        return u_new, v_new, loss

    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(), P(), P(DATA_AXIS), P(DATA_AXIS), P(), P(), P()),
        out_specs=(P(), P(), P()),
    )
    return fn(u, v, c_idx, ctx_idx, key, lr, noise_logits)


@fit_instrumentation("distributed_word2vec")
def distributed_word2vec_fit(
    token_sentences,
    mesh: Mesh,
    vector_size: int = 100,
    window: int = 5,
    min_count: int = 5,
    max_iter: int = 1,
    step_size: float = 0.025,
    k_neg: int = 5,
    batch_size: int = 16_384,
    max_sentence_length: int = 1000,
    seed: int = 0,
    dtype=jnp.float32,
):
    """Host-side driver over raw token sentences. Returns the standard
    ``Word2VecModel`` (same class the local fit produces)."""
    from spark_rapids_ml_tpu.models.word2vec import (
        Word2VecModel,
        prepare_corpus,
    )

    rng = np.random.default_rng(seed)
    vocab, counts, pairs = prepare_corpus(
        [list(s) for s in token_sentences], max_sentence_length,
        min_count, window, rng)
    n_pairs = pairs.shape[1]
    n_dev = mesh.devices.size
    batch = min(batch_size, n_pairs)
    batch = max(n_dev, (batch // n_dev) * n_dev)  # shardable batch

    noise = counts ** 0.75
    noise_logits = jnp.asarray(np.log(noise / noise.sum()), dtype=dtype)
    repl = NamedSharding(mesh, P())
    shard1 = NamedSharding(mesh, P(DATA_AXIS))
    u = jax.device_put(jnp.asarray(
        (rng.random((len(vocab), vector_size)) - 0.5) / vector_size,
        dtype=dtype), repl)
    v = jax.device_put(
        jnp.zeros((len(vocab), vector_size), dtype=dtype), repl)
    key = jax.random.PRNGKey(seed)
    lr0 = float(step_size)
    n_batches = max(1, n_pairs // batch)
    total_steps = max_iter * n_batches

    obs_ctx = current_fit()
    obs_ctx.set_data(rows=n_pairs, features=vector_size)
    # per SGNS step: fused psums of the two (vocab, dim) gradient tables,
    # their (vocab,) touch counts, and the scalar loss
    step_nbytes = collective_nbytes(
        (2 * len(vocab) * (vector_size + 1) + 1,), dtype)
    step = 0
    last_loss = float("nan")
    for epoch in range(max_iter):
        perm = rng.permutation(n_pairs)
        # the epoch-end float(loss) blocks on the last dispatched step,
        # so the monitored step's wall time covers the whole epoch
        with current_run().step("sgns_epoch", rows=n_pairs) as mon:
            for b in range(n_batches):
                sel = perm[b * batch:(b + 1) * batch]
                if sel.size < batch:
                    # keep shapes static even when the whole corpus is
                    # smaller than one shardable batch: cycle the
                    # permuted pairs until the batch is full
                    sel = np.resize(perm, batch)
                lr = jnp.asarray(
                    max(lr0 * (1 - step / total_steps), lr0 * 1e-4),
                    dtype=dtype)
                key, sub = jax.random.split(key)
                obs_ctx.record_collective("all_reduce",
                                          nbytes=step_nbytes)
                u, v, loss = distributed_sgns_step_kernel(
                    u, v,
                    jax.device_put(jnp.asarray(pairs[0, sel]), shard1),
                    jax.device_put(jnp.asarray(pairs[1, sel]), shard1),
                    sub, lr, noise_logits, mesh=mesh, k_neg=k_neg)
                step += 1
            last_loss = float(loss)
            mon.note(loss=last_loss, epoch=float(epoch))
    u = jax.block_until_ready(u)

    model = Word2VecModel(
        vectors=np.asarray(u, dtype=np.float64), vocabulary=vocab)
    model.set("vectorSize", int(vector_size))
    model.final_loss_ = last_loss
    model.num_pairs_ = int(n_pairs)
    return model
