"""Distributed LinearRegression over the mesh.

Same shape as ``distributed_pca``: rows sharded over ``data``, per-shard
sufficient statistics (XᵀX, Xᵀy, Σx, Σy, n), ONE fused ``psum`` over ICI,
then a replicated (tiny) normal-equations solve on every device.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.obs import (
    current_fit,
    current_run,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.ops.linreg_kernel import (
    LinRegResult,
    linreg_partial_stats,
    solve_normal_equations,
)
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    collective_nbytes,
    pad_rows_to_multiple,
    row_sharding,
)


@partial(tracked_jit, static_argnames=("mesh", "fit_intercept"))
def distributed_linreg_fit_kernel(
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    *,
    mesh: Mesh,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
) -> LinRegResult:
    def shard_fn(x_shard, y_shard, mask_shard):
        stats = linreg_partial_stats(x_shard, y_shard, mask_shard)
        stats = type(stats)(*jax.lax.psum(tuple(stats), DATA_AXIS))
        return tuple(solve_normal_equations(stats, reg_param, fit_intercept))

    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P()),
    )
    coef, intercept = fn(x, y, mask)
    return LinRegResult(coef, intercept)


@fit_instrumentation("distributed_linreg")
def distributed_linreg_fit(
    x_host: np.ndarray,
    y_host: np.ndarray,
    mesh: Mesh,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
    dtype=None,
) -> LinRegResult:
    ctx = current_fit()
    x_host = np.asarray(x_host)
    y_host = np.asarray(y_host).reshape(-1)
    n_dev = mesh.devices.size
    with ctx.phase("prepare"):
        x_padded, mask = pad_rows_to_multiple(x_host, n_dev)
        y_padded = np.zeros(x_padded.shape[0], dtype=y_host.dtype)
        y_padded[: y_host.shape[0]] = y_host
        if dtype is not None:
            x_padded = x_padded.astype(dtype)
            y_padded = y_padded.astype(dtype)
            mask = mask.astype(dtype)
    with ctx.phase("placement"):
        x_dev = jax.device_put(x_padded, row_sharding(mesh))
        y_dev = jax.device_put(y_padded, NamedSharding(mesh, P(DATA_AXIS)))
        mask_dev = jax.device_put(mask, NamedSharding(mesh, P(DATA_AXIS)))
    # ONE fused psum of (XᵀX, Xᵀy, Σx, Σy, n)
    n = x_host.shape[1]
    ctx.record_collective(
        "all_reduce",
        nbytes=collective_nbytes((n * n + 2 * n + 2,), x_padded.dtype),
    )
    with ctx.phase("execute"), current_run().step(
        "normal_equations", rows=x_host.shape[0]
    ):
        return jax.block_until_ready(
            distributed_linreg_fit_kernel(
                x_dev, y_dev, mask_dev,
                mesh=mesh, reg_param=reg_param, fit_intercept=fit_intercept,
            )
        )
