"""Distributed LDA over the mesh.

Document-parallel variational EM: rows (documents) shard over the
``data`` axis, the topic-word λ stays replicated, and each EM iteration
is ONE compiled SPMD program — the per-shard E-step (the same
``e_step_kernel`` while_loop of MXU matmuls the single-chip path runs)
followed by a fused ``psum`` of the (k, vocab) sufficient statistics
over ICI. No driver-side reduce, no per-document traffic: the only
collective payload is the k×vocab statistics tensor, exactly the
PCA/KMeans pattern (``distributed_pca.py``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


from spark_rapids_ml_tpu.obs import (
    current_fit,
    current_run,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.ops.lda_kernel import (
    dirichlet_expectation,
    e_step_kernel,
)
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    collective_nbytes,
    pad_rows_to_multiple,
)


@fit_instrumentation("distributed_lda")
def distributed_lda_fit(
    counts: np.ndarray,
    k: int,
    mesh: Mesh,
    *,
    max_iter: int = 20,
    alpha: float | None = None,
    eta: float | None = None,
    seed: int = 0,
    dtype=jnp.float32,
):
    """Full-corpus variational EM, document-sharded. Returns (λ, α) as
    host arrays. Padded documents carry zero counts and contribute
    nothing to the statistics (their γ fixes at α)."""
    n_docs, vocab = counts.shape
    n_dev = mesh.devices.size
    alpha_val = 1.0 / k if alpha is None else float(alpha)
    eta_val = 1.0 / k if eta is None else float(eta)

    x, _ = pad_rows_to_multiple(np.asarray(counts, dtype=np.float64),
                                n_dev)
    x = jax.device_put(
        jnp.asarray(x, dtype=dtype),
        jax.sharding.NamedSharding(mesh, P(DATA_AXIS, None)))
    alpha_vec = jnp.full((k,), alpha_val, dtype=dtype)
    rng = np.random.default_rng(seed)
    lam = jnp.asarray(rng.gamma(100.0, 1.0 / 100.0, (k, vocab)),
                      dtype=dtype)

    @tracked_jit  # compile the SPMD program once; bare shard_map re-traces
    @partial(jax.shard_map, mesh=mesh,
             in_specs=(P(DATA_AXIS, None), P(), P(), P()),
             out_specs=P())
    def em_sstats(counts_shard, lam, alpha_vec, key):
        exp_elog_beta = jnp.exp(dirichlet_expectation(lam))
        shard_key = jax.random.fold_in(key, lax.axis_index(DATA_AXIS))
        _, sstats = e_step_kernel(counts_shard, exp_elog_beta,
                                  alpha_vec, shard_key)
        return lax.psum(sstats, DATA_AXIS)

    ctx = current_fit()
    ctx.set_data(rows=n_docs, features=vocab)
    # each EM pass psums the (k, vocab) sufficient-statistics tensor
    sstats_nbytes = collective_nbytes((k, vocab), dtype)
    key = jax.random.PRNGKey(seed)
    with ctx.phase("execute"), current_run().step(
        "variational_em", rows=n_docs
    ) as mon:
        for _ in range(max_iter):
            key, sub = jax.random.split(key)
            ctx.record_collective("all_reduce", nbytes=sstats_nbytes)
            lam = eta_val + em_sstats(x, lam, alpha_vec, sub)
        # EM passes pipeline on device; block inside the step so its
        # wall time covers the whole chain, not just the dispatches
        lam = jax.block_until_ready(lam)
        mon.note(n_iter=float(max_iter))
    ctx.set_iterations(max_iter)
    return (np.asarray(lam, dtype=np.float64),
            np.asarray(alpha_vec, dtype=np.float64))
