"""Distributed PCA fit: sharded partial Gram + on-device all-reduce.

The reference's distributed covariance ships one n×n double matrix per
partition to the driver and sums there — O(P·n²) driver work over Spark RPC
(``/root/reference/src/main/scala/org/apache/spark/ml/linalg/distributed/RapidsRowMatrix.scala:168-202``).
Here the whole thing is ONE compiled XLA program over a ``Mesh``: each
device computes its shard's sufficient statistics (Gram, column sum, row
count) in HBM, a fused ``psum`` all-reduces them over ICI, and the (small)
eigensolve runs replicated — partials never touch the host.

Two communication schedules:

* ``two_pass`` (default): psum the column sums first, center each shard by
  the global mean, then psum the centered Gram. Matches the reference's
  mean-then-Gram semantics bit-for-bit; 2 collectives.
* ``one_pass``: single fused psum of (Σxxᵀ, Σx, n), covariance via
  ``G − n·μμᵀ``; 1 collective, preferable cross-slice (DCN) where latency
  dominates. Requires HIGHEST-precision accumulation at f32.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.ops.covariance import (
    covariance_from_stats,
    gram,
    partial_gram_stats,
)
from spark_rapids_ml_tpu.obs import (
    current_fit,
    current_run,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.ops.eigh import pca_from_covariance
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    collective_nbytes,
    pad_rows_to_multiple,
    row_sharding,
)


class DistributedPCAResult(NamedTuple):
    components: jnp.ndarray
    explained_variance: jnp.ndarray
    mean: jnp.ndarray


def _shard_fit(x_shard, mask_shard, *, k, mean_centering, one_pass, flip_signs):
    """Per-device program (runs under shard_map over the ``data`` axis)."""
    dtype = x_shard.dtype
    if one_pass:
        g, s, cnt = partial_gram_stats(x_shard, mask_shard)
        # ONE fused all-reduce over ICI for all three statistics.
        g, s, cnt = jax.lax.psum((g, s, cnt), DATA_AXIS)
        cov = covariance_from_stats(g, s, cnt, mean_centering=mean_centering)
        mean = s / cnt if mean_centering else jnp.zeros_like(s)
    else:
        m = mask_shard[:, None].astype(dtype)
        local_sum = jnp.sum(x_shard * m, axis=0)
        local_cnt = jnp.sum(mask_shard).astype(dtype)
        # collective 1: global mean
        total_sum, cnt = jax.lax.psum((local_sum, local_cnt), DATA_AXIS)
        mean = total_sum / cnt if mean_centering else jnp.zeros_like(total_sum)
        # center + fold 1/√(n−1) into the rows BEFORE the Gram, the
        # reference's trick (RapidsRowMatrix.scala:169,179-181) — partial
        # Grams then sum directly to the covariance.
        scale = 1.0 / jnp.sqrt(jnp.maximum(cnt - 1.0, 1.0))
        xc = (x_shard - mean[None, :]) * m * scale
        # collective 2: all-reduce of partial covariance
        cov = jax.lax.psum(gram(xc), DATA_AXIS)
    components, evr = pca_from_covariance(cov, k, flip_signs=flip_signs)
    return components, evr, mean


@partial(
    tracked_jit,
    static_argnames=("mesh", "k", "mean_centering", "one_pass", "flip_signs"),
)
def distributed_pca_fit_kernel(
    x: jnp.ndarray,
    mask: jnp.ndarray,
    *,
    mesh: Mesh,
    k: int,
    mean_centering: bool = True,
    one_pass: bool = False,
    flip_signs: bool = True,
) -> DistributedPCAResult:
    """The full sharded fit as one jitted program.

    ``x``/``mask`` may live on host or be pre-sharded; the in_specs place
    rows over the ``data`` axis, outputs are replicated.
    """
    fn = jax.shard_map(
        partial(
            _shard_fit,
            k=k,
            mean_centering=mean_centering,
            one_pass=one_pass,
            flip_signs=flip_signs,
        ),
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS)),
        out_specs=(P(), P(), P()),
    )
    components, evr, mean = fn(x, mask)
    return DistributedPCAResult(components, evr, mean)


@fit_instrumentation("distributed_pca")
def distributed_pca_fit(
    x_host: np.ndarray,
    k: int,
    mesh: Mesh,
    mean_centering: bool = True,
    one_pass: bool = False,
    flip_signs: bool = True,
    dtype=None,
) -> DistributedPCAResult:
    """Host-side driver: pad rows to the mesh, place shards, run the kernel.

    This is what replaces the reference's mapPartitions + driver reduce: the
    host only pads and hands XLA a sharded array; all math and communication
    is on-device.
    """
    ctx = current_fit()
    n_dev = mesh.devices.size
    x_host = np.asarray(x_host)
    if k > x_host.shape[1]:
        raise ValueError(
            f"k = {k} must be at most the number of features {x_host.shape[1]}"
        )
    with ctx.phase("prepare"):
        x_padded, mask = pad_rows_to_multiple(x_host, n_dev)
        if dtype is not None:
            x_padded = x_padded.astype(dtype)
            mask = mask.astype(dtype)
    with ctx.phase("placement"):
        sharding = row_sharding(mesh)
        x_dev = jax.device_put(x_padded, sharding)
        mask_dev = jax.device_put(mask, NamedSharding(mesh, P(DATA_AXIS)))
    n = x_host.shape[1]
    dt = x_padded.dtype
    if one_pass:
        # ONE fused psum of (Gram, column sum, count)
        ctx.record_collective(
            "all_reduce", nbytes=collective_nbytes((n * n + n + 1,), dt)
        )
    else:
        # psum of (column sum, count), then psum of the centered Gram
        ctx.record_collective(
            "all_reduce", nbytes=collective_nbytes((n + 1,), dt)
        )
        ctx.record_collective(
            "all_reduce", nbytes=collective_nbytes((n, n), dt)
        )
    with ctx.phase("execute"), current_run().step(
        "covariance_eigh", rows=x_host.shape[0]
    ) as step:
        result = jax.block_until_ready(
            distributed_pca_fit_kernel(
                x_dev,
                mask_dev,
                mesh=mesh,
                k=k,
                mean_centering=mean_centering,
                one_pass=one_pass,
                flip_signs=flip_signs,
            )
        )
        step.note(k=k, one_pass=int(one_pass))
        return result
