"""Distributed smooth-objective training over the mesh (FM, AFT).

The whole optimizer loop of ``ops/optim.py::minimize_kernel`` runs
INSIDE one ``shard_map``-compiled program: rows sharded over ``data``,
parameters replicated, and the objective defined as the exact global
weighted mean — ``psum(Σ w·loss) / psum(Σ w) + penalty`` — so L-BFGS /
adamW see the same scalar on every shard and autodiff inserts the
matching gradient ``psum`` automatically (the transpose of ``psum`` is
replication). One compiled program per fit; zero host round-trips
inside the loop — the mesh counterpart of the driver-device fits the
adapter documents as non-decomposable per-PARTITION-JOB (their
linesearch state doesn't split into cheap Spark jobs, but it shards
perfectly across chips inside one program).

Padding rows carry weight 0 and zero features, contributing nothing to
either the loss numerator or the weight denominator.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.models.fm import (
    _l2,
    fm_logistic_rowloss,
    fm_squared_rowloss,
)
from spark_rapids_ml_tpu.models.survival_regression import (
    aft_rowwise_loglik,
)
from spark_rapids_ml_tpu.obs import (
    current_fit,
    current_run,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.ops.optim import minimize_kernel
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    pad_rows_to_multiple,
    row_sharding,
)


def _note_grad_psums(ctx, params0, n_iter, dtype) -> None:
    """Account the per-iteration gradient ``psum``: autodiff inserts one
    all-reduce of the full parameter pytree (plus the 2-scalar loss mean)
    per optimizer step."""
    p_count = sum(
        int(np.prod(np.shape(leaf)))
        for leaf in jax.tree_util.tree_leaves(params0)
    )
    ctx.set_iterations(n_iter)
    ctx.record_collective(
        "all_reduce",
        nbytes=(p_count + 2) * np.dtype(dtype).itemsize,
        count=max(int(n_iter), 1),
    )


# -- module-level psum'd objectives (static jit args need stable ids) ------

def _global_mean(num_local, den_local):
    return (lax.psum(num_local, DATA_AXIS)
            / lax.psum(den_local, DATA_AXIS))


def fm_squared_loss_dp(params, x, y, w, lam):
    rl = fm_squared_rowloss(params, x, y)
    return _global_mean((w * rl).sum(), w.sum()) + _l2(params, lam)


def fm_logistic_loss_dp(params, x, y, w, lam):
    rl = fm_logistic_rowloss(params, x, y)
    return _global_mean((w * rl).sum(), w.sum()) + _l2(params, lam)


def aft_neg_loglik_dp(params, x, log_t, censor, w):
    ll = aft_rowwise_loglik(params, x, log_t, censor)
    return -_global_mean((w * ll).sum(), w.sum())


def mlp_cross_entropy_dp(params, x, y_onehot, w):
    from spark_rapids_ml_tpu.ops.mlp_kernel import rowwise_cross_entropy

    rl = rowwise_cross_entropy(params, x, y_onehot)
    return _global_mean((w * rl).sum(), w.sum())


@partial(tracked_jit, static_argnames=("loss_fn", "solver", "max_iter",
                                   "mesh", "row_args"))
def distributed_minimize_kernel(
    params, data, *, loss_fn, solver: str, max_iter: int, tol,
    step_size=0.01, mesh: Mesh, row_args: int,
):
    """``minimize_kernel`` with the data plane sharded: the first data
    operand is the (rows, d) matrix, the next ``row_args - 1`` are
    per-row vectors, the rest are replicated scalars."""
    data_specs = (
        (P(DATA_AXIS, None),)
        + (P(DATA_AXIS),) * (row_args - 1)
        + (P(),) * (len(data) - row_args)
    )

    def shard_fn(p, *shard_data):
        return minimize_kernel(
            p, shard_data, loss_fn=loss_fn, solver=solver,
            max_iter=max_iter, tol=tol, step_size=step_size)

    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(),) + data_specs,
        out_specs=(P(), P(), P()),
    )
    return fn(params, *data)


def _pad_rows(mesh, x, *row_vectors, dtype=jnp.float32):
    """Pad + shard (x, per-row vectors) over the mesh. Vectors pad with
    ZEROS — the weight vector always travels last, so its padding rows
    carry weight 0 and drop out of both the loss numerator and the
    weight denominator (no separate mask needed)."""
    n_dev = mesh.devices.size
    x_padded, _mask = pad_rows_to_multiple(np.asarray(x), n_dev)
    out = [jax.device_put(np.asarray(x_padded, dtype=np.dtype(dtype)),
                          row_sharding(mesh))]
    vec_sharding = NamedSharding(mesh, P(DATA_AXIS))
    n_rows = np.asarray(x).shape[0]
    for v in row_vectors:
        v = np.asarray(v, dtype=np.float64)
        if v.ndim != 2:
            # scalars become (1,) so the length check below diagnoses
            # them; (n,1) columns flatten — only a genuinely 2-D row
            # matrix (the one-hot case) keeps its second axis
            v = v.reshape(-1)
        elif v.shape[1] == 1:
            v = v.reshape(-1)
        if v.shape[0] != n_rows:
            raise ValueError(
                f"per-row vector length {v.shape[0]} != rows {n_rows}")
        v_padded = np.zeros((x_padded.shape[0],) + v.shape[1:])
        v_padded[: v.shape[0]] = v
        sharding = (vec_sharding if v.ndim == 1
                    else NamedSharding(mesh, P(DATA_AXIS, None)))
        out.append(jax.device_put(
            np.asarray(v_padded, dtype=np.dtype(dtype)), sharding))
    return out


@fit_instrumentation("distributed_fm")
def distributed_fm_fit(
    x_host: np.ndarray,
    y_host: np.ndarray,
    mesh: Mesh,
    classification: bool = False,
    factor_size: int = 8,
    reg_param: float = 0.0,
    max_iter: int = 100,
    tol: float = 1e-6,
    step_size: float = 0.01,
    solver: str = "adamW",
    seed: int = 0,
    init_std: float = 0.01,
    weights: np.ndarray = None,
    dtype=jnp.float32,
):
    """Factorization machine trained over the mesh in one compiled
    program. Returns (params dict on host, n_iter, final loss)."""
    x_host = np.asarray(x_host)
    rng = np.random.default_rng(seed)
    params0 = {
        "factors": jnp.asarray(
            rng.normal(scale=init_std,
                       size=(x_host.shape[1], factor_size)),
            dtype=dtype),
        "intercept": jnp.asarray(0.0, dtype=dtype),
        "linear": jnp.zeros(x_host.shape[1], dtype=dtype),
    }
    w = np.ones(x_host.shape[0]) if weights is None else weights
    x_dev, y_dev, w_dev = _pad_rows(mesh, x_host, y_host, w, dtype=dtype)
    loss_fn = fm_logistic_loss_dp if classification else \
        fm_squared_loss_dp
    with current_run().step(
        "minimize", rows=x_host.shape[0]
    ) as mon:
        params, n_iter, loss = jax.block_until_ready(
            distributed_minimize_kernel(
                params0,
                (x_dev, y_dev, w_dev,
                 jnp.asarray(reg_param, dtype=dtype)),
                loss_fn=loss_fn, solver=solver, max_iter=max_iter,
                tol=tol, step_size=step_size, mesh=mesh, row_args=3,
            )
        )
        mon.note(n_iter=int(n_iter), loss=float(loss))
    _note_grad_psums(current_fit(), params0, n_iter, dtype)
    host = {k: np.asarray(v, dtype=np.float64)
            for k, v in params.items()}
    return host, int(n_iter), float(loss)


@fit_instrumentation("distributed_aft")
def distributed_aft_fit(
    x_host: np.ndarray,
    t_host: np.ndarray,
    censor_host: np.ndarray,
    mesh: Mesh,
    max_iter: int = 100,
    tol: float = 1e-6,
    solver: str = "l-bfgs",
    weights: np.ndarray = None,
    dtype=jnp.float32,
):
    """Weibull AFT survival regression over the mesh in one compiled
    program. Returns (params dict on host, n_iter, final loss)."""
    x_host = np.asarray(x_host)
    t = np.asarray(t_host, dtype=np.float64).reshape(-1)
    if (t <= 0).any():
        raise ValueError("survival times must be > 0")
    cens = np.asarray(censor_host, dtype=np.float64).reshape(-1)
    if not np.isin(cens, (0.0, 1.0)).all():
        raise ValueError(
            "censor values must be 0.0 or 1.0 (1.0 = event observed)")
    params0 = {
        "beta": jnp.zeros(x_host.shape[1], dtype=dtype),
        "intercept": jnp.asarray(0.0, dtype=dtype),
        "log_sigma": jnp.asarray(0.0, dtype=dtype),
    }
    w = np.ones(x_host.shape[0]) if weights is None else weights
    x_dev, logt_dev, cens_dev, w_dev = _pad_rows(
        mesh, x_host, np.log(t), cens, w, dtype=dtype)
    with current_run().step(
        "minimize", rows=x_host.shape[0]
    ) as mon:
        params, n_iter, loss = jax.block_until_ready(
            distributed_minimize_kernel(
                params0, (x_dev, logt_dev, cens_dev, w_dev),
                loss_fn=aft_neg_loglik_dp, solver=solver,
                max_iter=max_iter, tol=tol, mesh=mesh, row_args=4,
            )
        )
        mon.note(n_iter=int(n_iter), loss=float(loss))
    _note_grad_psums(current_fit(), params0, n_iter, dtype)
    host = {k: np.asarray(v, dtype=np.float64)
            for k, v in params.items()}
    return host, int(n_iter), float(loss)


@fit_instrumentation("distributed_mlp")
def distributed_mlp_fit(
    x_host: np.ndarray,
    y_host: np.ndarray,
    layers,
    mesh: Mesh,
    max_iter: int = 100,
    tol: float = 1e-6,
    step_size: float = 0.03,
    solver: str = "l-bfgs",
    seed: int = 0,
    weights: np.ndarray = None,
    dtype=jnp.float32,
):
    """MultilayerPerceptron classifier trained over the mesh in one
    compiled program (Spark MLP conventions: ``layers`` = [in, hidden...,
    n_classes], labels are class indices). Returns (params pytree on
    host, n_iter, final loss)."""
    from spark_rapids_ml_tpu.ops.mlp_kernel import init_weights

    from spark_rapids_ml_tpu.ops.mlp_kernel import validate_and_onehot

    x_host = np.asarray(x_host)
    layers = [int(v) for v in layers]
    y_onehot = validate_and_onehot(x_host, y_host, layers)
    w = np.ones(x_host.shape[0]) if weights is None else weights

    params0 = jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, dtype=dtype),
        init_weights(layers, seed))
    x_dev, oh_dev, w_dev = _pad_rows(mesh, x_host, y_onehot, w,
                                     dtype=dtype)
    with current_run().step(
        "minimize", rows=x_host.shape[0]
    ) as mon:
        params, n_iter, loss = jax.block_until_ready(
            distributed_minimize_kernel(
                params0, (x_dev, oh_dev, w_dev),
                loss_fn=mlp_cross_entropy_dp, solver=solver,
                max_iter=max_iter, tol=tol, step_size=step_size,
                mesh=mesh, row_args=3,
            )
        )
        mon.note(n_iter=int(n_iter), loss=float(loss))
    _note_grad_psums(current_fit(), params0, n_iter, dtype)
    host = jax.tree_util.tree_map(
        lambda a: np.asarray(a, dtype=np.float64), params)
    return host, int(n_iter), float(loss)
