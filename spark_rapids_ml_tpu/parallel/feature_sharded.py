"""Feature-sharded PCA over a 2-D (data × feature) mesh.

The reference caps the feature dimension twice: the spr path's packed
triangle overflows past 65,535 columns (``RapidsRowMatrix.scala:147,204-206``)
and every path materializes the full n×n covariance on ONE device for the
eigensolve (driver GPU, ``RapidsRowMatrix.scala:94-95``). SURVEY.md §5 names
the TPU-native answer: shard the n×n Gram across the mesh. This module is
that path — the workload's analogue of sequence/context parallelism, with
the same communication shape as ring attention: block-resident operands
rotate around a ring while each device accumulates its output block.

Layout. Rows shard over the ``data`` axis, columns over ``feature``; device
(d, f) holds an (m/D, n/F) tile of X. The covariance comes out sharded as
block rows, P(feature, None) — no device ever holds all of it.

Schedules for the n_loc×n block-row Gram:

* ``ring``: F−1 ``ppermute`` hops around the feature axis; each step one
  (n_loc × m_loc)·(m_loc × n_loc) MXU matmul against the tile currently in
  flight. Peak extra memory = ONE remote tile; XLA overlaps the permute with
  the matmul. This is the long-feature scaling path.
* ``allgather``: one ``all_gather`` of the row-shard's full width, then a
  single big matmul. Fewer, larger ops; peak memory F× the tile. Better when
  the tiles are small and ICI latency dominates.

Solvers on the sharded covariance:

* ``eigh``: all-gather the (small enough) covariance and factorize
  replicated — the parity-exact dense path.
* ``randomized``: subspace iteration where the matvec keeps the covariance
  sharded (local block-row matmul + all-gather of the thin (n, l) iterate)
  — full n×n never exists on any device; this is the n ≫ device-memory
  regime (``ops/randomized.py``).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.obs.xprof import tracked_jit
from spark_rapids_ml_tpu.ops.eigh import pca_from_covariance
from spark_rapids_ml_tpu.ops.randomized import (
    subspace_iteration,
    topk_from_subspace,
)
from spark_rapids_ml_tpu.ops.covariance import default_gram_precision
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    FEATURE_AXIS,
    pad_rows_to_multiple,
)


class FeatureShardedPCAResult(NamedTuple):
    components: jnp.ndarray
    explained_variance: jnp.ndarray
    mean: jnp.ndarray


def _block_row_gram(xc: jnp.ndarray, schedule: str) -> jnp.ndarray:
    """This device's (n_loc × n_total) block row of XᵀX over the feature ring.

    ``xc`` is the local (m_loc × n_loc) tile, already centered and scaled.
    Runs inside shard_map; communication is over the ``feature`` axis only.
    """
    F = lax.axis_size(FEATURE_AXIS)
    j = lax.axis_index(FEATURE_AXIS)
    n_loc = xc.shape[1]
    if schedule == "allgather":
        x_full = lax.all_gather(xc, FEATURE_AXIS, axis=1, tiled=True)
        return lax.dot_general(
            xc, x_full, (((0,), (0,)), ((), ())),
            precision=default_gram_precision(),
        )
    # ring: at step t this device holds tile (j+t) mod F and fills that
    # column block of its output row; then the tile moves one hop.
    g_row = jnp.zeros((n_loc, F * n_loc), dtype=xc.dtype)
    held = xc
    for t in range(F):
        blk = lax.dot_general(
            xc, held, (((0,), (0,)), ((), ())),
            precision=default_gram_precision(),
        )
        col = ((j + t) % F) * n_loc
        g_row = lax.dynamic_update_slice(
            g_row, blk, (jnp.zeros((), dtype=col.dtype), col)
        )
        if t + 1 < F:
            held = lax.ppermute(
                held, FEATURE_AXIS, [(i, (i - 1) % F) for i in range(F)]
            )
    return g_row


def _sharded_cov_and_mean(x_tile, mask_shard, *, mean_centering, schedule):
    """Per-device: (block row of Cov, local slice of mean). Collectives:
    one psum over data for the column stats, the feature-axis schedule for
    the Gram, one psum over data for the block row."""
    dtype = x_tile.dtype
    m = mask_shard[:, None].astype(dtype)
    local_sum = jnp.sum(x_tile * m, axis=0)
    local_cnt = jnp.sum(mask_shard).astype(dtype)
    total_sum, cnt = lax.psum((local_sum, local_cnt), DATA_AXIS)
    mean_loc = total_sum / cnt if mean_centering else jnp.zeros_like(total_sum)
    scale = 1.0 / jnp.sqrt(jnp.maximum(cnt - 1.0, 1.0))
    xc = (x_tile - mean_loc[None, :]) * m * scale
    g_row = lax.psum(_block_row_gram(xc, schedule), DATA_AXIS)
    return g_row, mean_loc


def _local_trace(g_row: jnp.ndarray) -> jnp.ndarray:
    """Sum of the global-diagonal entries that land in this block row."""
    n_loc = g_row.shape[0]
    j = lax.axis_index(FEATURE_AXIS)
    start = j * n_loc
    diag_block = lax.dynamic_slice(
        g_row, (jnp.zeros((), dtype=start.dtype), start), (n_loc, n_loc)
    )
    return jnp.trace(diag_block)


@partial(
    tracked_jit,
    static_argnames=("mesh", "mean_centering", "schedule"),
)
def feature_sharded_covariance_kernel(
    x: jnp.ndarray,
    mask: jnp.ndarray,
    *,
    mesh: Mesh,
    mean_centering: bool = True,
    schedule: str = "ring",
):
    """Covariance sharded as block rows over ``feature``; mean sharded over
    ``feature``. One compiled program; partials never touch the host."""
    fn = jax.shard_map(
        partial(
            _sharded_cov_and_mean,
            mean_centering=mean_centering,
            schedule=schedule,
        ),
        mesh=mesh,
        in_specs=(P(DATA_AXIS, FEATURE_AXIS), P(DATA_AXIS)),
        out_specs=(P(FEATURE_AXIS, None), P(FEATURE_AXIS)),
    )
    return fn(x, mask)


def _randomized_shard(
    g_row, *, k, oversample, n_iter, seed, flip_signs
):
    """Sharded-matvec subspace iteration (runs inside shard_map over the
    feature axis; the data axis is already reduced out of ``g_row``).

    The iterate Q (n × l, thin) is replicated; Cov stays sharded: each
    device multiplies its block row, then an all_gather over ``feature``
    reassembles the full (n, l) product. QR/eigh on the thin/l×l matrices
    run replicated — identical on every device, no extra communication.
    """
    n_loc, n = g_row.shape
    l = min(k + oversample, n)

    def matvec(v):
        y_loc = g_row @ v  # (n_loc, l)
        return lax.all_gather(y_loc, FEATURE_AXIS, axis=0, tiled=True)

    evals, evecs = subspace_iteration(
        matvec, n, l, n_iter, jax.random.PRNGKey(seed), g_row.dtype
    )
    total_var = lax.psum(_local_trace(g_row), FEATURE_AXIS)
    return topk_from_subspace(evals, evecs, k, total_var, flip_signs)


@partial(
    tracked_jit,
    static_argnames=(
        "mesh", "k", "oversample", "n_iter", "seed", "flip_signs"
    ),
)
def randomized_sharded_pca_kernel(
    g_rows: jnp.ndarray,
    *,
    mesh: Mesh,
    k: int,
    oversample: int = 10,
    n_iter: int = 4,
    seed: int = 0,
    flip_signs: bool = True,
):
    fn = jax.shard_map(
        partial(
            _randomized_shard,
            k=k,
            oversample=oversample,
            n_iter=n_iter,
            seed=seed,
            flip_signs=flip_signs,
        ),
        mesh=mesh,
        in_specs=(P(FEATURE_AXIS, None),),
        out_specs=(P(), P()),
        # Outputs are replicated by construction (thin iterates are
        # all_gathered, the small eigh runs identically everywhere), but the
        # static VMA checker cannot infer replication through all_gather.
        check_vma=False,
    )
    return fn(g_rows)


# Module-level wrapper so repeated eigh-solver fits hit the jit cache
# instead of re-tracing per call.
_jitted_pca_from_covariance = partial(
    tracked_jit, static_argnames=("k", "flip_signs")
)(pca_from_covariance)


def pad_cols_to_multiple(x: np.ndarray, multiple: int) -> np.ndarray:
    """Zero-pad columns so the feature dim divides the mesh. Zero columns
    contribute zero mean / zero covariance rows+cols, so they are inert in
    both solvers; outputs are sliced back to the true width."""
    rem = (-x.shape[1]) % multiple
    if rem:
        x = np.concatenate(
            [x, np.zeros((x.shape[0], rem), dtype=x.dtype)], axis=1
        )
    return x


def feature_sharded_pca_fit(
    x_host: np.ndarray,
    k: int,
    mesh: Mesh,
    mean_centering: bool = True,
    schedule: str = "ring",
    solver: str = "eigh",
    oversample: int = 10,
    n_iter: int = 4,
    flip_signs: bool = True,
    dtype=None,
    seed: int = 0,
) -> FeatureShardedPCAResult:
    """Full fit over a 2-D mesh: pad + place tiles, sharded covariance,
    then the chosen eigensolver. ``solver='eigh'`` gathers the covariance
    (exact, parity path); ``solver='randomized'`` keeps it sharded
    (large-n path)."""
    if schedule not in ("ring", "allgather"):
        raise ValueError(f"unknown schedule {schedule!r}")
    if solver not in ("eigh", "randomized"):
        raise ValueError(f"unknown solver {solver!r}")
    if DATA_AXIS not in mesh.axis_names or FEATURE_AXIS not in mesh.axis_names:
        raise ValueError(
            f"mesh must have ({DATA_AXIS!r}, {FEATURE_AXIS!r}) axes; "
            f"got {mesh.axis_names}"
        )
    n_data = mesh.shape[DATA_AXIS]
    n_feature = mesh.shape[FEATURE_AXIS]
    x_host = np.asarray(x_host)
    n_rows, n_features = x_host.shape
    if k > n_features:
        raise ValueError(
            f"k = {k} must be at most the number of features {n_features}"
        )
    x_padded, mask = pad_rows_to_multiple(x_host, n_data)
    x_padded = pad_cols_to_multiple(x_padded, n_feature)
    if dtype is not None:
        x_padded = x_padded.astype(dtype)
        mask = mask.astype(dtype)
    x_dev = jax.device_put(
        x_padded, NamedSharding(mesh, P(DATA_AXIS, FEATURE_AXIS))
    )
    mask_dev = jax.device_put(mask, NamedSharding(mesh, P(DATA_AXIS)))
    g_rows, mean = feature_sharded_covariance_kernel(
        x_dev, mask_dev, mesh=mesh,
        mean_centering=mean_centering, schedule=schedule,
    )
    if solver == "randomized":
        components, evr = randomized_sharded_pca_kernel(
            g_rows, mesh=mesh, k=k, oversample=oversample,
            n_iter=n_iter, seed=seed, flip_signs=flip_signs,
        )
    else:
        cov = jnp.asarray(g_rows)[:n_features, :n_features]
        components, evr = _jitted_pca_from_covariance(
            cov, k=k, flip_signs=flip_signs
        )
    result = FeatureShardedPCAResult(
        components=components[:n_features],
        explained_variance=evr,
        mean=jnp.asarray(mean)[:n_features],
    )
    return jax.block_until_ready(result)
