"""Distributed KMeans over the mesh.

Reuses the PCA pattern (``distributed_pca.py``): rows sharded over
``data``, per-shard sufficient statistics, ``psum`` all-reduce, replicated
small solve — with the psum running INSIDE the compiled Lloyd loop: one
all-reduce of (k×n sums, k counts, cost) per iteration over ICI, versus
the reference-era pattern of shipping assignments to a driver.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.ops.kmeans_kernel import (
    KMeansResult,
    kmeans_plus_plus_init,
    lloyd_iterations,
)
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, pad_rows_to_multiple, row_sharding


@partial(
    jax.jit, static_argnames=("mesh", "n_clusters", "max_iter")
)
def distributed_kmeans_fit_kernel(
    x: jnp.ndarray,
    mask: jnp.ndarray,
    key: jax.Array,
    *,
    mesh: Mesh,
    n_clusters: int,
    max_iter: int = 20,
    tol: float = 1e-4,
) -> KMeansResult:
    def shard_fn(x_shard, mask_shard, key_repl):
        # Seeding: shard 0 runs k-means++ over its local rows and the
        # result is broadcast with a psum (other shards contribute zeros) —
        # deterministic, one k×n all-reduce, and Lloyd over the full data
        # erases the locality of the seed sample.
        local = kmeans_plus_plus_init(x_shard, n_clusters, key_repl, mask_shard)
        is_first = (jax.lax.axis_index(DATA_AXIS) == 0).astype(local.dtype)
        init_centers = jax.lax.psum(local * is_first, DATA_AXIS)
        # plain tuple: shard_map out_specs prefixes don't match NamedTuples
        return tuple(
            lloyd_iterations(
                x_shard,
                init_centers,
                mask_shard,
                max_iter,
                tol,
                reduce_fn=lambda t: jax.lax.psum(t, DATA_AXIS),
            )
        )

    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P()),
        out_specs=(P(), P(), P(), P()),
    )
    centers, cost, n_iter, converged = fn(x, mask, key)
    return KMeansResult(centers, cost, n_iter, converged)


def distributed_kmeans_fit(
    x_host: np.ndarray,
    n_clusters: int,
    mesh: Mesh,
    max_iter: int = 20,
    tol: float = 1e-4,
    seed: int = 0,
    dtype=None,
) -> KMeansResult:
    x_host = np.asarray(x_host)
    n_dev = mesh.devices.size
    x_padded, mask = pad_rows_to_multiple(x_host, n_dev)
    if dtype is not None:
        x_padded = x_padded.astype(dtype)
        mask = mask.astype(dtype)
    x_dev = jax.device_put(x_padded, row_sharding(mesh))
    mask_dev = jax.device_put(mask, NamedSharding(mesh, P(DATA_AXIS)))
    key = jax.random.PRNGKey(seed)
    return jax.block_until_ready(
        distributed_kmeans_fit_kernel(
            x_dev, mask_dev, key,
            mesh=mesh, n_clusters=n_clusters, max_iter=max_iter, tol=tol,
        )
    )
