"""Distributed KMeans and LinearRegression over the mesh.

Both reuse the PCA pattern (``distributed_pca.py``): rows sharded over
``data``, per-shard sufficient statistics, ``psum`` all-reduce, replicated
small solve. For KMeans the psum runs INSIDE the compiled Lloyd loop —
one all-reduce of (k×n sums, k counts, cost) per iteration over ICI, versus
the reference-era pattern of shipping assignments to a driver.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.ops.kmeans_kernel import (
    KMeansResult,
    kmeans_plus_plus_init,
    lloyd_iterations,
)
from spark_rapids_ml_tpu.ops.linreg_kernel import (
    LinRegResult,
    linreg_partial_stats,
    solve_normal_equations,
)
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, pad_rows_to_multiple, row_sharding


@partial(
    jax.jit, static_argnames=("mesh", "n_clusters", "max_iter")
)
def distributed_kmeans_fit_kernel(
    x: jnp.ndarray,
    mask: jnp.ndarray,
    key: jax.Array,
    *,
    mesh: Mesh,
    n_clusters: int,
    max_iter: int = 20,
    tol: float = 1e-4,
) -> KMeansResult:
    def shard_fn(x_shard, mask_shard, key_repl):
        # Seeding: shard 0 runs k-means++ over its local rows and the
        # result is broadcast with a psum (other shards contribute zeros) —
        # deterministic, one k×n all-reduce, and Lloyd over the full data
        # erases the locality of the seed sample.
        local = kmeans_plus_plus_init(x_shard, n_clusters, key_repl, mask_shard)
        is_first = (jax.lax.axis_index(DATA_AXIS) == 0).astype(local.dtype)
        init_centers = jax.lax.psum(local * is_first, DATA_AXIS)
        # plain tuple: shard_map out_specs prefixes don't match NamedTuples
        return tuple(
            lloyd_iterations(
                x_shard,
                init_centers,
                mask_shard,
                max_iter,
                tol,
                reduce_fn=lambda t: jax.lax.psum(t, DATA_AXIS),
            )
        )

    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P()),
        out_specs=(P(), P(), P(), P()),
    )
    centers, cost, n_iter, converged = fn(x, mask, key)
    return KMeansResult(centers, cost, n_iter, converged)


def distributed_kmeans_fit(
    x_host: np.ndarray,
    n_clusters: int,
    mesh: Mesh,
    max_iter: int = 20,
    tol: float = 1e-4,
    seed: int = 0,
    dtype=None,
) -> KMeansResult:
    x_host = np.asarray(x_host)
    n_dev = mesh.devices.size
    x_padded, mask = pad_rows_to_multiple(x_host, n_dev)
    if dtype is not None:
        x_padded = x_padded.astype(dtype)
        mask = mask.astype(dtype)
    x_dev = jax.device_put(x_padded, row_sharding(mesh))
    mask_dev = jax.device_put(mask, NamedSharding(mesh, P(DATA_AXIS)))
    key = jax.random.PRNGKey(seed)
    return jax.block_until_ready(
        distributed_kmeans_fit_kernel(
            x_dev, mask_dev, key,
            mesh=mesh, n_clusters=n_clusters, max_iter=max_iter, tol=tol,
        )
    )


@partial(jax.jit, static_argnames=("mesh", "fit_intercept"))
def distributed_linreg_fit_kernel(
    x: jnp.ndarray,
    y: jnp.ndarray,
    mask: jnp.ndarray,
    *,
    mesh: Mesh,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
) -> LinRegResult:
    def shard_fn(x_shard, y_shard, mask_shard):
        stats = linreg_partial_stats(x_shard, y_shard, mask_shard)
        stats = type(stats)(*jax.lax.psum(tuple(stats), DATA_AXIS))
        return tuple(solve_normal_equations(stats, reg_param, fit_intercept))

    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P()),
    )
    coef, intercept = fn(x, y, mask)
    return LinRegResult(coef, intercept)


def distributed_linreg_fit(
    x_host: np.ndarray,
    y_host: np.ndarray,
    mesh: Mesh,
    reg_param: float = 0.0,
    fit_intercept: bool = True,
    dtype=None,
) -> LinRegResult:
    x_host = np.asarray(x_host)
    y_host = np.asarray(y_host).reshape(-1)
    n_dev = mesh.devices.size
    x_padded, mask = pad_rows_to_multiple(x_host, n_dev)
    y_padded = np.zeros(x_padded.shape[0], dtype=y_host.dtype)
    y_padded[: y_host.shape[0]] = y_host
    if dtype is not None:
        x_padded = x_padded.astype(dtype)
        y_padded = y_padded.astype(dtype)
        mask = mask.astype(dtype)
    x_dev = jax.device_put(x_padded, row_sharding(mesh))
    y_dev = jax.device_put(y_padded, NamedSharding(mesh, P(DATA_AXIS)))
    mask_dev = jax.device_put(mask, NamedSharding(mesh, P(DATA_AXIS)))
    return jax.block_until_ready(
        distributed_linreg_fit_kernel(
            x_dev, y_dev, mask_dev,
            mesh=mesh, reg_param=reg_param, fit_intercept=fit_intercept,
        )
    )
