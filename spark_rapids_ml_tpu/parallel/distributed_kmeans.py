"""Distributed KMeans over the mesh.

Reuses the PCA pattern (``distributed_pca.py``): rows sharded over
``data``, per-shard sufficient statistics, ``psum`` all-reduce, replicated
small solve — with the psum running INSIDE the compiled Lloyd loop: one
all-reduce of (k×n sums, k counts, cost) per iteration over ICI, versus
the reference-era pattern of shipping assignments to a driver.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jax import lax

from spark_rapids_ml_tpu.obs import (
    current_fit,
    current_run,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.ops.kmeans_kernel import (
    KMeansResult,
    lloyd_iterations,
)
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    collective_nbytes,
    pad_rows_to_multiple,
    row_sharding,
)


def _global_kmeans_pp(x_shard, mask_shard, key, n_clusters: int):
    """k-means++ seeding with GLOBAL D²-weighted sampling across shards.

    Spark's k-means|| samples over the whole dataset; seeding from one
    shard's local rows (the round-1 shortcut) is biased under non-IID row
    sharding — a shard holding one cluster's points seeds every center
    inside it. Exact global categorical sampling without gathering rows:
    the Gumbel-max trick. Each shard perturbs its local log-D² with Gumbel
    noise (per-shard folded key), takes its local argmax, and a ``pmax``
    picks the global winner — distributionally identical to sampling
    ∝ D² over the union. Per step: one pmax + two psums (scalar + row).
    """
    m, n = x_shard.shape
    valid = (
        jnp.ones(m, dtype=x_shard.dtype)
        if mask_shard is None
        else mask_shard.astype(x_shard.dtype)
    )
    j = lax.axis_index(DATA_AXIS)
    neg_inf = jnp.asarray(-jnp.inf, dtype=x_shard.dtype)

    def sample_global(logits, step_key):
        g = jax.random.gumbel(
            jax.random.fold_in(step_key, j), logits.shape, dtype=logits.dtype
        ) + logits
        local_best = jnp.max(g)
        local_row = x_shard[jnp.argmax(g)]
        global_best = lax.pmax(local_best, DATA_AXIS)
        owner = (local_best >= global_best).astype(x_shard.dtype)
        n_owners = lax.psum(owner, DATA_AXIS)  # ties: average (p≈0 event)
        return lax.psum(local_row * owner, DATA_AXIS) / jnp.maximum(n_owners, 1)

    key, sub = jax.random.split(key)
    first = sample_global(jnp.where(valid > 0, 0.0, neg_inf), sub)
    centers0 = jnp.zeros((n_clusters, n), dtype=x_shard.dtype).at[0].set(first)
    min_d0 = jnp.sum((x_shard - first[None, :]) ** 2, axis=1) * valid

    def body(i, state):
        centers, min_d, key = state
        key, sub = jax.random.split(key)
        logits = jnp.where(
            valid > 0, jnp.log(jnp.maximum(min_d, 1e-30)), neg_inf
        )
        c = sample_global(logits, sub)
        centers = centers.at[i].set(c)
        d_new = jnp.sum((x_shard - c[None, :]) ** 2, axis=1) * valid
        return centers, jnp.minimum(min_d, d_new), key

    centers, _, _ = lax.fori_loop(1, n_clusters, body, (centers0, min_d0, key))
    return centers


@partial(
    tracked_jit, static_argnames=("mesh", "n_clusters", "max_iter")
)
def distributed_kmeans_fit_kernel(
    x: jnp.ndarray,
    mask: jnp.ndarray,
    key: jax.Array,
    *,
    mesh: Mesh,
    n_clusters: int,
    max_iter: int = 20,
    tol: float = 1e-4,
) -> KMeansResult:
    def shard_fn(x_shard, mask_shard, key_repl):
        init_centers = _global_kmeans_pp(
            x_shard, mask_shard, key_repl, n_clusters
        )
        # plain tuple: shard_map out_specs prefixes don't match NamedTuples
        return tuple(
            lloyd_iterations(
                x_shard,
                init_centers,
                mask_shard,
                max_iter,
                tol,
                reduce_fn=lambda t: jax.lax.psum(t, DATA_AXIS),
            )
        )

    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P()),
        out_specs=(P(), P(), P(), P()),
    )
    centers, cost, n_iter, converged = fn(x, mask, key)
    return KMeansResult(centers, cost, n_iter, converged)


@fit_instrumentation("distributed_kmeans")
def distributed_kmeans_fit(
    x_host: np.ndarray,
    n_clusters: int,
    mesh: Mesh,
    max_iter: int = 20,
    tol: float = 1e-4,
    seed: int = 0,
    dtype=None,
) -> KMeansResult:
    ctx = current_fit()
    x_host = np.asarray(x_host)
    n_dev = mesh.devices.size
    with ctx.phase("prepare"):
        x_padded, mask = pad_rows_to_multiple(x_host, n_dev)
        if dtype is not None:
            x_padded = x_padded.astype(dtype)
            mask = mask.astype(dtype)
    with ctx.phase("placement"):
        x_dev = jax.device_put(x_padded, row_sharding(mesh))
        mask_dev = jax.device_put(mask, NamedSharding(mesh, P(DATA_AXIS)))
    key = jax.random.PRNGKey(seed)
    # The Lloyd loop runs INSIDE the compiled program (fori_loop + psum),
    # so the host-visible step is the whole blocked pass; the realized
    # iteration count and final cost ride along as convergence scalars.
    with ctx.phase("execute"), current_run().step(
        "lloyd", rows=x_host.shape[0]
    ) as step:
        result = jax.block_until_ready(
            distributed_kmeans_fit_kernel(
                x_dev, mask_dev, key,
                mesh=mesh, n_clusters=n_clusters, max_iter=max_iter, tol=tol,
            )
        )
        step.note(n_iter=int(result[2]), cost=float(result[1]),
                  converged=int(result[3]))
    n = x_host.shape[1]
    dt = x_padded.dtype
    n_iter = int(result[2])
    ctx.set_iterations(n_iter)
    # k-means++ seeding: per center one pmax (scalar) + two psums
    # (owner scalar + winning row)
    ctx.record_collective(
        "all_max", nbytes=collective_nbytes((1,), dt), count=n_clusters
    )
    ctx.record_collective(
        "all_reduce", nbytes=collective_nbytes((n + 1,), dt),
        count=n_clusters,
    )
    # Lloyd: one fused psum of (k×n sums, k counts, cost) per iteration
    ctx.record_collective(
        "all_reduce",
        nbytes=collective_nbytes((n_clusters * n + n_clusters + 1,), dt),
        count=max(n_iter, 1),
    )
    return result
