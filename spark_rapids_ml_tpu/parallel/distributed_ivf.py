"""Distributed IVF-Flat / IVF-PQ search: inverted lists sharded over the mesh.

The approximate-KNN analogue of ``distributed_knn``: the INDEX is what
grows, so the inverted lists shard over the ``data`` axis — each device
holds nlist/n_shards coarse cells (centroid + its bucket of items or PQ
codes), queries and PQ codebooks replicate. Each shard probes its local
top-``nprobe`` cells and emits its local top-k; the global answer is the
same two-level all_gather + merge reduction the brute-force path uses.

Semantics note: probing the top ``nprobe`` cells PER SHARD probes at
least every cell the single-device search would (each globally-nearest
cell is also among its own shard's nearest), plus up to
``nprobe·(n_shards−1)`` extras — so recall is ≥ the single-device
configuration at the same nprobe, approaching it from above as shards
grow. The PQ variant returns ADC-ranked results (the exact re-rank stays
a single-device refinement, where the raw rows live).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.obs import (
    current_fit,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.ops.knn_kernel import ivf_search, ivfpq_search, knn_merge
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, collective_nbytes

_FAR = 1e30  # padded-cell centroid fill: sorts after every real cell


def _pad_lists(arr: np.ndarray, nlist_padded: int, axis: int, fill=0):
    pad = nlist_padded - arr.shape[axis]
    if pad == 0:
        return arr
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, pad)
    return np.pad(arr, widths, constant_values=fill)


@partial(tracked_jit, static_argnames=("k", "nprobe", "mesh"))
def _sharded_ivf_flat(queries, centroids, b_items, b_ids, b_mask,
                      k: int, nprobe: int, mesh: Mesh):
    def per_shard(q, cent, items, ids, mask):
        local_lists = cent.shape[0]
        np_local = min(nprobe, local_lists)
        pool = np_local * items.shape[1]
        k_local = min(k, pool)
        d2, gids = ivf_search(q, cent, items, ids, mask, k_local, np_local)
        all_d = lax.all_gather(d2, DATA_AXIS, axis=1, tiled=True)
        all_i = lax.all_gather(gids, DATA_AXIS, axis=1, tiled=True)
        return knn_merge(all_d, all_i, k)

    return jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS, None), P(DATA_AXIS, None, None),
                  P(DATA_AXIS, None), P(DATA_AXIS, None)),
        out_specs=(P(), P()),
        check_vma=False,
    )(queries, centroids, b_items, b_ids, b_mask)


@partial(tracked_jit, static_argnames=("k", "nprobe", "mesh"))
def _sharded_ivf_pq(queries, centroids, codebooks, b_codes, b_ids, b_mask,
                    k: int, nprobe: int, mesh: Mesh):
    def per_shard(q, cent, books, codes, ids, mask):
        local_lists = cent.shape[0]
        np_local = min(nprobe, local_lists)
        pool = np_local * ids.shape[1]
        k_local = min(k, pool)
        d2, gids = ivfpq_search(q, cent, books, codes, ids, mask,
                                k_local, np_local)
        all_d = lax.all_gather(d2, DATA_AXIS, axis=1, tiled=True)
        all_i = lax.all_gather(gids, DATA_AXIS, axis=1, tiled=True)
        return knn_merge(all_d, all_i, k)

    return jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS, None), P(),
                  P(None, DATA_AXIS, None), P(DATA_AXIS, None),
                  P(DATA_AXIS, None)),
        out_specs=(P(), P()),
        check_vma=False,
    )(queries, centroids, codebooks, b_codes, b_ids, b_mask)


@fit_instrumentation("distributed_ivf")
def distributed_ivf_search(
    model,
    queries: np.ndarray,
    mesh: Mesh,
    k=None,
    dtype=jnp.float32,
) -> Tuple[np.ndarray, np.ndarray]:
    """(distances, indices) for a fitted approximate ``NearestNeighborsModel``
    with its inverted lists sharded over ``mesh``.

    Builds (or reuses) the model's single-host index, re-lays the list
    arrays out across the mesh (lists padded to the shard multiple with
    far-centroid empty cells), and runs the sharded search. ``algorithm``
    on the model selects ivfflat vs ivfpq.
    """
    algorithm = model.getAlgorithm()
    if algorithm not in ("ivfflat", "ivfpq"):
        raise ValueError(
            f"distributed_ivf_search needs algorithm ivfflat/ivfpq, "
            f"got {algorithm!r}"
        )
    k = model.getK() if k is None else k
    queries = np.asarray(queries, dtype=np.dtype(dtype))
    n_shards = int(np.prod(mesh.devices.shape))
    build_device = jax.local_devices()[0]
    if algorithm == "ivfflat":
        centroids, b_items, b_ids, b_mask, nlist = model._ivf_index(
            build_device, dtype
        )
    else:
        centroids, books, b_codes, b_ids, b_mask, nlist = (
            model._ivfpq_index(build_device, dtype)
        )
    nprobe = min(model.getNprobe(), nlist)
    nlist_p = -(-nlist // n_shards) * n_shards
    # the sharded analogue of the model's candidate-pool guard: every
    # shard contributes min(k, local_pool) candidates; the merged set must
    # still cover k, else top_k would fail with an opaque shape error
    lists_per_shard = nlist_p // n_shards
    max_size = int(np.asarray(b_ids).shape[1])
    per_shard = min(k, min(nprobe, lists_per_shard) * max_size)
    if n_shards * per_shard < k:
        raise ValueError(
            f"k = {k} exceeds the sharded candidate pool "
            f"({n_shards} shards x {per_shard}): raise nprobe or nlist, "
            "or use fewer shards"
        )
    cent = _pad_lists(np.asarray(centroids, dtype=np.dtype(dtype)),
                      nlist_p, 0, fill=_FAR)
    ids = _pad_lists(np.asarray(b_ids), nlist_p, 0)
    mask = _pad_lists(np.asarray(b_mask, dtype=np.dtype(dtype)), nlist_p, 0)
    shard_l = NamedSharding(mesh, P(DATA_AXIS, None))
    repl = NamedSharding(mesh, P())
    q_dev = jax.device_put(jnp.asarray(queries), repl)
    cent_dev = jax.device_put(jnp.asarray(cent), shard_l)
    ids_dev = jax.device_put(jnp.asarray(ids), shard_l)
    mask_dev = jax.device_put(jnp.asarray(mask), shard_l)
    ctx = current_fit()
    ctx.set_data(rows=queries.shape[0], features=queries.shape[1])
    # two-level reduction: all_gather of per-shard top-k distances + ids
    ctx.record_collective(
        "all_gather",
        nbytes=collective_nbytes(
            (queries.shape[0], per_shard * n_shards), dtype))
    ctx.record_collective(
        "all_gather",
        nbytes=collective_nbytes(
            (queries.shape[0], per_shard * n_shards), np.int32))
    if algorithm == "ivfflat":
        items = _pad_lists(
            np.asarray(b_items, dtype=np.dtype(dtype)), nlist_p, 0
        )
        items_dev = jax.device_put(
            jnp.asarray(items), NamedSharding(mesh, P(DATA_AXIS, None, None))
        )
        d2, i = _sharded_ivf_flat(
            q_dev, cent_dev, items_dev, ids_dev, mask_dev, k, nprobe, mesh
        )
    else:
        codes = _pad_lists(np.asarray(b_codes), nlist_p, 1)
        codes_dev = jax.device_put(
            jnp.asarray(codes), NamedSharding(mesh, P(None, DATA_AXIS, None))
        )
        books_dev = jax.device_put(jnp.asarray(books), repl)
        d2, i = _sharded_ivf_pq(
            q_dev, cent_dev, books_dev, codes_dev, ids_dev, mask_dev,
            k, nprobe, mesh,
        )
    return (
        np.sqrt(np.maximum(np.asarray(d2), 0.0)),
        np.asarray(i, dtype=np.int64),
    )
