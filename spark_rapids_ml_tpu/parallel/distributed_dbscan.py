"""Distributed DBSCAN: ε-graph row panels sharded over the mesh.

The tiled single-device kernel (``ops.dbscan_kernel.dbscan_labels_blocked``)
streams (block × n) distance panels sequentially under ``lax.map``; here
the SAME panels are computed concurrently, one row panel per device:
``x`` is replicated (n·d — small; it is the n² adjacency this
formulation never materializes), each device sweeps min-label
propagation over its own row range, and the updated label slices are
exchanged with one ``all_gather`` per sweep — the label vector is the
only cross-device traffic, O(n) per sweep instead of the reference-era
alternative of shipping neighbor lists. Convergence is a replicated
``psum``-free check on the gathered labels (identical on every device by
construction). Semantics match the single-device kernels exactly: core =
degree ≥ min_pts, min-label propagation to fixpoint, deterministic
minimum-core-neighbor border assignment, noise −1.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.obs import (
    current_fit,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.ops.knn_kernel import pairwise_sqdist
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    collective_nbytes,
    pad_rows_to_multiple,
)


@partial(tracked_jit, static_argnames=("min_pts", "inner_block", "mesh"))
def _sharded_dbscan(x, valid, eps, min_pts: int, inner_block: int,
                    mesh: Mesh):
    n = x.shape[0]
    dt = x.dtype
    inf = jnp.asarray(jnp.inf, dt)
    n_dev = int(np.prod(mesh.devices.shape))
    rows_per = n // n_dev
    assert rows_per % inner_block == 0
    nb = rows_per // inner_block
    valid_f = valid.astype(dt)
    x_panels = x.reshape(n_dev, rows_per, x.shape[1])

    def per_shard(x_panel):
        # x_panel: (1, rows_per, d) — this device's row range. Distance
        # panels are recomputed per sweep in (inner_block × n) tiles
        # under lax.map — the blocked kernel's streaming discipline, so
        # per-device memory is one tile, not rows_per × n.
        xp = x_panel[0]
        xpb = xp.reshape(nb, inner_block, xp.shape[1])
        idx0 = lax.axis_index(DATA_AXIS) * rows_per

        def degree_block(xi):
            d2 = pairwise_sqdist(xi, x)
            return jnp.sum(
                (d2 <= eps * eps).astype(dt) * valid_f[None, :], axis=1
            )

        my_valid = lax.dynamic_slice_in_dim(valid, idx0, rows_per)
        degree = lax.map(degree_block, xpb).reshape(rows_per)
        core_local = (degree >= min_pts) & my_valid
        core = lax.all_gather(core_local, DATA_AXIS, axis=0, tiled=True)
        core_f = core.astype(dt)

        labels0 = jnp.where(core, jnp.arange(n, dtype=dt), inf)

        def neighbor_min(labels):
            def blk(xi):
                d2 = pairwise_sqdist(xi, x)
                adj_core = (d2 <= eps * eps).astype(dt) * core_f[None, :]
                return jnp.min(
                    jnp.where(adj_core > 0, labels[None, :], inf), axis=1
                )

            return lax.map(blk, xpb).reshape(rows_per)

        def body(state):
            labels, _ = state
            mine = lax.dynamic_slice_in_dim(labels, idx0, rows_per)
            nxt_local = jnp.minimum(
                mine, jnp.where(core_local, neighbor_min(labels), inf)
            )
            nxt = lax.all_gather(nxt_local, DATA_AXIS, axis=0, tiled=True)
            return nxt, jnp.any(nxt != labels)

        labels_core, _ = lax.while_loop(
            lambda s: s[1], body, (labels0, jnp.asarray(True))
        )

        border_local = neighbor_min(labels_core)
        mine_core = lax.dynamic_slice_in_dim(labels_core, idx0, rows_per)
        final_local = jnp.where(core_local, mine_core, border_local)
        final_local = jnp.where(my_valid, final_local, inf)
        labels_int = jnp.where(
            jnp.isfinite(final_local), final_local, jnp.asarray(-1, dt)
        ).astype(jnp.int32)
        return labels_int[None, :], core_local[None, :]

    labels, core = jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None, None),),
        out_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None)),
        check_vma=False,
    )(x_panels)
    return labels.reshape(n), core.reshape(n)


@fit_instrumentation("distributed_dbscan")
def distributed_dbscan_labels(
    x_host: np.ndarray,
    eps: float,
    min_pts: int,
    mesh: Mesh,
    dtype=jnp.float32,
    inner_block: int = 1024,
) -> Tuple[np.ndarray, np.ndarray]:
    """(labels, core_mask) with the ε-graph row panels computed one per
    device, each panel streamed in (inner_block × n) tiles. Labels are
    cluster representatives (minimum row index), noise −1 — relabel with
    the estimator's helper for consecutive ids."""
    x_host = np.asarray(x_host, dtype=np.dtype(dtype))
    n = x_host.shape[0]
    if n > 2 ** 24:
        raise ValueError(
            f"{n} rows exceeds the f32 label-lane envelope (2^24)"
        )
    n_dev = int(np.prod(mesh.devices.shape))
    # rows pad to a multiple of n_dev·inner so each device's panel tiles
    # evenly. The tile SHRINKS to fit rather than the input padding up to
    # the tile: nb tiles of ceil(per_dev/nb) rows bounds padding by
    # n_dev·nb rows (padding to a blunt n_dev·inner_block multiple could
    # add up to 64% phantom rows and square into every distance panel)
    per_dev = -(-n // n_dev)
    nb = max(1, -(-per_dev // inner_block))
    inner = -(-per_dev // nb)
    x_pad, mask = pad_rows_to_multiple(x_host, n_dev * inner)
    valid = mask > 0
    x_dev = jax.device_put(jnp.asarray(x_pad), NamedSharding(mesh, P()))
    valid_dev = jax.device_put(jnp.asarray(valid), NamedSharding(mesh, P()))
    ctx = current_fit()
    n_pad = x_pad.shape[0]
    # one all_gather of the core mask, then one all_gather of the (n,)
    # label vector per label-propagation sweep; the sweep count is
    # data-dependent (compiled while_loop) — account the fixed payload once
    # and record the per-sweep payload so consumers can scale it.
    ctx.record_collective(
        "all_gather", nbytes=collective_nbytes((n_pad,), x_dev.dtype),
        count=2,
    )
    ctx.note(dbscan_sweep_payload_bytes=collective_nbytes(
        (n_pad,), x_dev.dtype))
    with ctx.phase("execute"):
        labels, core = _sharded_dbscan(
            x_dev, valid_dev, jnp.asarray(eps, dtype=x_dev.dtype), min_pts,
            inner, mesh,
        )
    return (
        np.asarray(labels)[:n],
        np.asarray(core, dtype=bool)[:n],
    )
