"""Distributed BisectingKMeans over the mesh.

The divisive hierarchy at mesh scale: rows stay sharded over ``data``
for the whole fit, the per-row leaf assignment lives as a sharded
int32 array updated ON DEVICE by each committed split, and every
bisection is ONE compiled program — global Gumbel-max k-means++(2)
seeding over the target leaf's rows, the psum'd Lloyd loop of
``distributed_kmeans.py``, a final assignment, and the child moments
(count, Σx, Σ‖x‖²) reduced with the same psum — so the host driver
only sees O(d) statistics per split, never rows (the Spark-plane
version of this algorithm is ``spark/moments_estimator.py``; this is
its ICI-collective sibling).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.obs import (
    current_fit,
    current_run,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.ops.kmeans_kernel import lloyd_iterations
from spark_rapids_ml_tpu.parallel.distributed_kmeans import (
    _global_kmeans_pp,
)
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    collective_nbytes,
    pad_rows_to_multiple,
    row_sharding,
)


class BisectingKMeansResult(NamedTuple):
    centers: jnp.ndarray        # (n_leaves, d) leaf centers
    cost: float                 # Σ per-leaf SSE about its mean
    labels: np.ndarray          # (n_rows,) compact center index per row


@partial(tracked_jit, static_argnames=("mesh", "max_iter"))
def _bisect_split_kernel(
    x: jnp.ndarray,
    mask: jnp.ndarray,
    leaf: jnp.ndarray,
    key: jax.Array,
    target: jnp.ndarray,
    new_id: jnp.ndarray,
    *,
    mesh: Mesh,
    max_iter: int = 20,
    tol: float = 1e-4,
):
    """One bisection of leaf ``target`` as a single sharded program.

    Returns (2-means centers, proposed leaf array with the target's
    side-1 rows re-labelled ``new_id``, per-side (count, Σx, Σ‖x‖²)).
    ``target``/``new_id`` are DYNAMIC replicated scalars, so every
    split of a fit reuses one compiled executable.
    """

    def shard_fn(xs, ms, ls, key_repl, tgt, nid):
        m2 = ms * (ls == tgt).astype(xs.dtype)
        init = _global_kmeans_pp(xs, m2, key_repl, 2)
        centers, _cost, _n_iter, _conv = lloyd_iterations(
            xs, init, m2, max_iter, tol,
            reduce_fn=lambda t: lax.psum(t, DATA_AXIS),
        )
        d = (
            (xs * xs).sum(axis=1)[:, None]
            + (centers * centers).sum(axis=1)[None, :]
            - 2.0 * (xs @ centers.T)
        )
        side = jnp.argmin(d, axis=1)
        new_ls = jnp.where(
            m2 > 0, jnp.where(side == 0, tgt, nid), ls
        ).astype(ls.dtype)
        w = jnp.stack([m2 * (side == 0), m2 * (side == 1)])  # (2, m)
        cnt = lax.psum(w.sum(axis=1), DATA_AXIS)             # (2,)
        sums = lax.psum(w @ xs, DATA_AXIS)                   # (2, d)
        sqs = lax.psum(w @ (xs * xs).sum(axis=1), DATA_AXIS)  # (2,)
        return centers, new_ls, cnt, sums, sqs

    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS),
                  P(), P(), P()),
        out_specs=(P(), P(DATA_AXIS), P(), P(), P()),
    )
    return fn(x, mask, leaf, key, target, new_id)


@fit_instrumentation("distributed_bisecting")
def distributed_bisecting_kmeans_fit(
    x_host: np.ndarray,
    k: int,
    mesh: Mesh,
    max_iter: int = 20,
    tol: float = 1e-4,
    seed: int = 0,
    min_divisible: float = 2.0,
    dtype=None,
) -> BisectingKMeansResult:
    """Host-side driver: pad + shard once, then one compiled bisection
    program per split; the hierarchy bookkeeping (which leaf splits
    next, divisibility) runs on O(leaves) statistics only."""
    x_host = np.asarray(x_host)
    n_rows = x_host.shape[0]
    if n_rows == 0:
        raise ValueError("empty dataset")
    n_dev = mesh.devices.size
    x_padded, mask = pad_rows_to_multiple(x_host, n_dev)
    if dtype is not None:
        x_padded = x_padded.astype(dtype)
        mask = mask.astype(dtype)
    x_dev = jax.device_put(x_padded, row_sharding(mesh))
    mask_dev = jax.device_put(
        np.asarray(mask, dtype=x_padded.dtype),
        NamedSharding(mesh, P(DATA_AXIS)),
    )
    leaf = jax.device_put(
        np.zeros(x_padded.shape[0], dtype=np.int32),
        NamedSharding(mesh, P(DATA_AXIS)),
    )

    # root stats on host (the driver already holds x_host — the same
    # posture as distributed_kmeans_fit's input contract)
    center0 = x_host.mean(axis=0)
    sse0 = float(((x_host - center0[None, :]) ** 2).sum())
    leaves = {0: {"center": center0, "sse": sse0,
                  "count": float(n_rows), "divisible": True}}

    n_splits = 0
    while len(leaves) < k:
        order = sorted(leaves, key=lambda lf: leaves[lf]["sse"],
                       reverse=True)
        target = next(
            (lf for lf in order
             if leaves[lf]["divisible"]
             and leaves[lf]["count"] >= min_divisible),
            None,
        )
        if target is None:
            break
        new_id = max(leaves) + 1
        key = jax.random.fold_in(jax.random.PRNGKey(seed), n_splits)
        # per split: k-means++(2) seeding (pmax + 2 psums per center),
        # the Lloyd loop's fused psum per iteration, and the final
        # (count, Σx, Σ‖x‖²) child-moments psum
        d = x_host.shape[1]
        current_fit().record_collective(
            "all_reduce",
            nbytes=collective_nbytes((2 * d + 3,), x_padded.dtype),
            count=max_iter + 3,
        )
        with current_run().step("bisect_split", rows=n_rows) as mon:
            centers2, new_leaf, cnt, sums, sqs = jax.block_until_ready(
                _bisect_split_kernel(
                    x_dev, mask_dev, leaf,
                    key,
                    jnp.asarray(target, dtype=jnp.int32),
                    jnp.asarray(new_id, dtype=jnp.int32),
                    mesh=mesh, max_iter=max_iter, tol=tol,
                )
            )
            mon.note(n_leaves=float(len(leaves)), target=float(target))
        cnt = np.asarray(cnt, dtype=np.float64)
        n_splits += 1
        if (cnt <= 0).any():
            # degenerate split (identical points / emptied side): keep
            # the leaf, stop re-trying it
            leaves[target]["divisible"] = False
            continue
        leaf = new_leaf  # commit the on-device assignment
        sums = np.asarray(sums, dtype=np.float64)
        sqs = np.asarray(sqs, dtype=np.float64)
        for side, lf in ((0, target), (1, new_id)):
            mean = sums[side] / cnt[side]
            sse = float(max(
                sqs[side] - (sums[side] @ sums[side]) / cnt[side], 0.0))
            leaves[lf] = {"center": mean, "sse": sse,
                          "count": float(cnt[side]), "divisible": True}

    order = sorted(leaves)
    centers = np.stack([leaves[lf]["center"] for lf in order])
    lut = np.full(max(leaves) + 1, -1, dtype=np.int64)
    lut[order] = np.arange(len(order))
    labels = lut[np.asarray(leaf)[:n_rows]]
    return BisectingKMeansResult(
        centers=jnp.asarray(centers),
        cost=float(sum(v["sse"] for v in leaves.values())),
        labels=labels,
    )
