"""Distributed GeneralizedLinearRegression over the mesh.

IRLS with the data plane sharded: every iteration's weighted working
statistics (XᵀWX, XᵀWz, sums, deviance — ``GlmStepOut``) come from ONE
sharded program (``irls_step_math`` per shard + a fused ``psum`` of the
tuple), and the tiny host solve + convergence rule reuse the ONE IRLS
driver loop every other GLM path shares
(``models/glm.py::GeneralizedLinearRegression._irls``) — so the mesh,
local, out-of-core, and Spark-plane fits walk identical driver code
over different statistics planes, for every (family, link) pair.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.obs import (
    current_fit,
    current_run,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.ops.glm_kernel import (
    GlmStepOut,
    irls_step_math,
    validate_label_range,
)
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    collective_nbytes,
    pad_rows_to_multiple,
    row_sharding,
)


@partial(tracked_jit, static_argnames=("mesh", "family", "link", "var_power",
                                   "link_power", "use_init_mu"))
def distributed_glm_step_kernel(
    x: jnp.ndarray,
    y: jnp.ndarray,
    w: jnp.ndarray,
    offset: jnp.ndarray,
    coef: jnp.ndarray,
    intercept: jnp.ndarray,
    *,
    mesh: Mesh,
    family: str,
    link: str,
    var_power: float,
    link_power: float,
    use_init_mu: bool,
) -> GlmStepOut:
    """One global IRLS pass. Padding rows carry weight 0 (and a benign
    y=1 dummy, valid for every family's domain), so every statistic
    they touch is exactly zero."""

    def shard_fn(xs, ys, ws, os_, c, b):
        out = irls_step_math(
            jnp, xs, ys, ws, os_, c, b, family=family, link=link,
            var_power=var_power, link_power=link_power,
            use_init_mu=use_init_mu)
        return tuple(lax.psum(t, DATA_AXIS) for t in out)

    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS),
                  P(DATA_AXIS), P(), P()),
        out_specs=tuple(P() for _ in GlmStepOut._fields),
    )
    return GlmStepOut(*fn(x, y, w, offset, coef, intercept))


@fit_instrumentation("distributed_glm")
def distributed_glm_fit(
    x_host: np.ndarray,
    y_host: np.ndarray,
    mesh: Mesh,
    family: str = "gaussian",
    link: str = None,
    var_power: float = 0.0,
    link_power: float = None,
    max_iter: int = 25,
    tol: float = 1e-6,
    reg_param: float = 0.0,
    weights: np.ndarray = None,
    offset: np.ndarray = None,
    dtype=jnp.float32,
):
    """Host-side driver. Returns the standard
    ``GeneralizedLinearRegressionModel`` (same class every other GLM
    path produces, with its summary surface populated)."""
    from spark_rapids_ml_tpu.models.glm import (
        GeneralizedLinearRegression,
    )
    from spark_rapids_ml_tpu.utils.timing import PhaseTimer

    x_host = np.asarray(x_host, dtype=np.float64)
    y = np.asarray(y_host, dtype=np.float64).reshape(-1)
    if y.shape[0] != x_host.shape[0]:
        raise ValueError(
            f"labels length {y.shape[0]} != rows {x_host.shape[0]}")
    if x_host.shape[0] == 0:
        raise ValueError("empty dataset")

    est = GeneralizedLinearRegression()
    est.set("family", family)
    if link is not None:
        est.set("link", link)
    est.set("variancePower", float(var_power))
    if link_power is not None:
        est.set("linkPower", float(link_power))
    est.set("maxIter", int(max_iter))
    est.set("tol", float(tol))
    est.set("regParam", float(reg_param))
    family_r, link_r, var_power_r, link_power_r = \
        est._resolved_family_link()
    validate_label_range(y, family=family_r, var_power=var_power_r)

    w = (np.ones(x_host.shape[0]) if weights is None
         else np.asarray(weights, dtype=np.float64).reshape(-1))
    o = (np.zeros(x_host.shape[0]) if offset is None
         else np.asarray(offset, dtype=np.float64).reshape(-1))
    for name, v in (("weights", w), ("offset", o)):
        if v.shape[0] != x_host.shape[0]:
            raise ValueError(
                f"{name} length {v.shape[0]} != rows {x_host.shape[0]}")
    if not np.isfinite(w).all() or (w < 0).any():
        # the same contract every other GLM path enforces via
        # _extract_weights — a NaN weight would otherwise psum into
        # silently-NaN coefficients
        raise ValueError("weights must be finite and non-negative")

    n_dev = mesh.devices.size
    x_padded, _mask = pad_rows_to_multiple(x_host, n_dev)
    n_pad = x_padded.shape[0]

    def pad_vec(v, fill=0.0):
        out = np.full(n_pad, fill)
        out[: v.shape[0]] = v
        return out

    nd = np.dtype(dtype)
    shard1 = NamedSharding(mesh, P(DATA_AXIS))
    x_dev = jax.device_put(np.asarray(x_padded, dtype=nd),
                           row_sharding(mesh))
    # y=1 on padding rows: inside every family's domain, so unit_dev
    # stays finite and the zero weight kills the contribution exactly
    y_dev = jax.device_put(np.asarray(pad_vec(y, 1.0), dtype=nd), shard1)
    w_dev = jax.device_put(np.asarray(pad_vec(w, 0.0), dtype=nd), shard1)
    o_dev = jax.device_put(np.asarray(pad_vec(o, 0.0), dtype=nd), shard1)

    ctx = current_fit()
    n_feat = x_host.shape[1]
    # each IRLS pass runs ONE fused psum of the GlmStepOut tuple
    # (XᵀWX, XᵀWz, and the scalar sums) — recorded per actual invocation
    step_nbytes = collective_nbytes(
        (n_feat * n_feat + n_feat + len(GlmStepOut._fields),), nd)

    def step(coef, intercept, first=False):
        ctx.record_collective("all_reduce", nbytes=step_nbytes)
        # host→float64 conversion blocks on the result, so the step's
        # wall time covers the full IRLS pass, not just the dispatch
        with current_run().step("irls_pass", rows=x_host.shape[0]):
            out = distributed_glm_step_kernel(
                x_dev, y_dev, w_dev, o_dev,
                jnp.asarray(coef, dtype=nd),
                jnp.asarray(intercept, dtype=nd),
                mesh=mesh, family=family_r, link=link_r,
                var_power=float(var_power_r),
                link_power=float(link_power_r),
                use_init_mu=bool(first))
            return GlmStepOut(*(np.asarray(v, dtype=np.float64)
                                for v in out))

    if offset is not None:
        # the fitted model must refuse offset-less scoring, exactly as
        # an offsetCol-trained local model does (predictions without
        # the training exposure would be silently wrong) — name the
        # column the caller must supply at transform time
        est.set("offsetCol", "offset")

    timer = PhaseTimer()
    coef, intercept, n_iter, dev = est._irls(step, x_host.shape[1],
                                             timer)
    return est._finish(coef, intercept, n_iter, dev, float(w.sum()),
                       timer)
