"""Distributed out-of-core PCA: streamed batches over a device mesh.

The north-star config (BASELINE.md #4: 10M×4096 over a multi-chip slice)
needs BOTH halves at once: rows too many for host/HBM (stream them) and
chips to spread them over (shard them). This module combines
``ops/streaming.py``'s donated accumulator with ``distributed_pca.py``'s
mesh layout:

* the accumulator keeps a PER-DEVICE leading axis — ``gram (D, n, n)``,
  ``col_sum (D, n)``, ``count (D,)`` — sharded over the ``data`` axis, so a
  batch update is pure local compute on every chip (NO collective per
  batch; the reference's analogue shipped one n×n partial per partition to
  the driver, ``RapidsRowMatrix.scala:168-202``);
* each incoming (B, n) host batch is placed row-sharded (B/D rows per
  chip) and folded into that chip's slice of the accumulator via a single
  donated jitted program;
* ``finalize`` runs ONE collective: the sum over the device axis (XLA
  partitions it into an all-reduce over ICI), then covariance → eigh →
  postprocess replicated.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.obs import (
    current_fit,
    current_run,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.ops.covariance import covariance_from_stats, partial_gram_stats
from spark_rapids_ml_tpu.ops.eigh import pca_from_covariance
from spark_rapids_ml_tpu.ops.pca_kernel import PCAFitResult
from spark_rapids_ml_tpu.ops.streaming import GramStats
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    collective_nbytes,
    row_sharding,
)


@partial(tracked_jit, static_argnames=("mesh",), donate_argnums=(0,))
def update_stats_sharded(
    stats: GramStats, batch: jnp.ndarray, mask: jnp.ndarray, *, mesh: Mesh
) -> GramStats:
    """Fold one row-sharded batch into the per-device accumulator slices.

    Local compute only — each device updates its own (1, n, n) block; the
    cross-device reduction is deferred to ``finalize_stats_sharded``.
    """

    def shard_fn(g, s, c, b, m):
        pg, ps, pc = partial_gram_stats(b.astype(g.dtype), m)
        return g + pg[None], s + ps[None], c + pc[None]

    fn = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(DATA_AXIS, None, None),
            P(DATA_AXIS, None),
            P(DATA_AXIS),
            P(DATA_AXIS, None),
            P(DATA_AXIS),
        ),
        out_specs=(P(DATA_AXIS, None, None), P(DATA_AXIS, None), P(DATA_AXIS)),
    )
    g, s, c = fn(stats.gram, stats.col_sum, stats.count, batch, mask)
    return GramStats(g, s, c)


@partial(
    tracked_jit, static_argnames=("k", "mean_centering", "flip_signs", "solver")
)
def finalize_stats_sharded(
    stats: GramStats, k: int, mean_centering: bool = True,
    flip_signs: bool = True, solver: str = "eigh",
) -> PCAFitResult:
    """One all-reduce (the axis-0 sum over sharded slices), then the same
    covariance → eigh → postprocess chain as every other fit path."""
    g = jnp.sum(stats.gram, axis=0)
    s = jnp.sum(stats.col_sum, axis=0)
    cnt = jnp.sum(stats.count, axis=0)
    cov = covariance_from_stats(g, s, cnt, mean_centering=mean_centering)
    mean = s / cnt if mean_centering else jnp.zeros_like(s)
    components, evr = pca_from_covariance(
        cov, k, flip_signs=flip_signs, solver=solver
    )
    return PCAFitResult(components, evr, mean)


class DistributedStreamingPCA:
    """``DistributedStreamingPCA(n, mesh).partial_fit(b)....finalize(k)`` —
    bounded HBM per chip AND data-parallel scale-out in one accumulator."""

    def __init__(self, n_features: int, mesh: Mesh, dtype=jnp.float32):
        self._mesh = mesh
        self._n = n_features
        d = mesh.devices.size
        shard3 = NamedSharding(mesh, P(DATA_AXIS, None, None))
        shard2 = NamedSharding(mesh, P(DATA_AXIS, None))
        shard1 = NamedSharding(mesh, P(DATA_AXIS))
        self._stats = GramStats(
            gram=jax.device_put(
                jnp.zeros((d, n_features, n_features), dtype=dtype), shard3
            ),
            col_sum=jax.device_put(jnp.zeros((d, n_features), dtype=dtype), shard2),
            count=jax.device_put(jnp.zeros((d,), dtype=jnp.int32), shard1),
        )

    def partial_fit(self, batch, mask=None) -> "DistributedStreamingPCA":
        batch = np.asarray(batch)
        d = self._mesh.devices.size
        if batch.shape[0] % d:
            raise ValueError(
                f"batch rows {batch.shape[0]} must divide evenly over the "
                f"{d}-device mesh (pad + mask the tail)"
            )
        if mask is None:
            mask = np.ones((batch.shape[0],), dtype=bool)
        x_dev = jax.device_put(batch, row_sharding(self._mesh))
        m_dev = jax.device_put(
            np.asarray(mask), NamedSharding(self._mesh, P(DATA_AXIS))
        )
        self._stats = update_stats_sharded(
            self._stats, x_dev, m_dev, mesh=self._mesh
        )
        return self

    @property
    def rows_seen(self) -> int:
        return int(np.asarray(jnp.sum(self._stats.count)))

    def finalize(
        self, k: int, mean_centering: bool = True, solver: str = "eigh"
    ) -> PCAFitResult:
        # the ONE collective of the streamed fit: the axis-0 sum over the
        # per-device (gram, col_sum, count) slices
        n = self._n
        current_fit().record_collective(
            "all_reduce",
            nbytes=collective_nbytes((n * n + n + 1,),
                                     self._stats.gram.dtype),
        )
        return jax.block_until_ready(
            finalize_stats_sharded(
                self._stats, k, mean_centering=mean_centering, solver=solver
            )
        )


@fit_instrumentation("distributed_streaming_pca")
def distributed_streaming_pca_fit(
    source,
    k: int,
    mesh: Mesh,
    mean_centering: bool = True,
    dtype=jnp.float32,
    solver: str = "eigh",
) -> PCAFitResult:
    """Out-of-core fit of a ``data.batches.BatchSource`` over a mesh.

    The source's fixed batch shape is rounded to the mesh size by
    construction (``BatchSource`` pads + masks its tail), so every batch
    update hits one cached executable per chip.
    """
    d = mesh.devices.size
    if source.batch_rows % d:
        raise ValueError(
            f"source batch_rows {source.batch_rows} must be a multiple of "
            f"the mesh size {d}"
        )
    ctx = current_fit()
    acc = DistributedStreamingPCA(source.n_features, mesh, dtype=dtype)
    host_dtype = np.dtype(jnp.zeros((), dtype=dtype).dtype.name)
    n_batches = 0
    with ctx.phase("stream"):
        for batch, mask in source.batches():
            # accumulator updates pipeline on device — each fold's step
            # measures the host-side fold time (placement + dispatch)
            with current_run().step(
                "stream_fold", rows=batch.shape[0]
            ) as mon:
                acc.partial_fit(
                    batch.astype(host_dtype, copy=False), mask)
                mon.note(fold=float(n_batches))
            n_batches += 1
    ctx.set_data(rows=acc.rows_seen, features=source.n_features)
    ctx.note(batches_streamed=n_batches)
    if mean_centering and acc.rows_seen < 2:
        raise ValueError("mean centering requires more than one row")
    with ctx.phase("finalize"), current_run().step(
        "finalize", rows=acc.rows_seen
    ):
        return acc.finalize(k, mean_centering=mean_centering, solver=solver)
