"""Distributed ALS over the mesh.

Row-parallel alternating least squares: the padded user table (and U)
shard over the ``data`` axis, the padded item table (and V) likewise;
each half-sweep is one SPMD program in which every device ``all_gather``s
the small opposite factor table over ICI and solves ITS block of normal
equations locally (batched MXU contractions + batched Cholesky — the
same ``_solve_side`` the single-chip kernel runs). The gathered factor
table (rows × rank) is the only collective payload — never ratings.

This replaces Spark ALS's in-block/out-block shuffle topology: where
Spark routes factor messages through a hash-partitioned shuffle each
half-sweep, the mesh form is a single all-gather over ICI with the
solve fused into the same compiled program.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


from spark_rapids_ml_tpu.obs import (
    current_fit,
    current_run,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.ops.als_kernel import _solve_side
from spark_rapids_ml_tpu.parallel.mesh import DATA_AXIS, collective_nbytes


def _pad_table(idx, val, mask, n_dev):
    n = idx.shape[0]
    pad = (-n) % n_dev
    if pad:
        idx = np.pad(idx, ((0, pad), (0, 0)))
        val = np.pad(val, ((0, pad), (0, 0)))
        mask = np.pad(mask, ((0, pad), (0, 0)))
    return idx, val, mask, n


@fit_instrumentation("distributed_als")
def distributed_als_fit(
    u_table: Tuple[np.ndarray, np.ndarray, np.ndarray],
    i_table: Tuple[np.ndarray, np.ndarray, np.ndarray],
    mesh: Mesh,
    *,
    rank: int = 10,
    reg: float = 0.1,
    alpha: float = 1.0,
    max_iter: int = 10,
    implicit: bool = False,
    nonneg: bool = False,
    seed: int = 0,
    dtype=jnp.float32,
):
    """(user_factors, item_factors) from padded CSR tables
    (``ops.als_kernel.build_padded_csr`` output). Padded rows carry
    zero masks → identity systems → zero factors; they are sliced off
    before returning."""
    n_dev = mesh.devices.size
    u_idx, u_val, u_mask, n_users = _pad_table(*u_table, n_dev)
    i_idx, i_val, i_mask, n_items = _pad_table(*i_table, n_dev)

    row_sh = NamedSharding(mesh, P(DATA_AXIS, None))
    put = partial(jax.device_put, device=row_sh)
    u_idx = put(jnp.asarray(u_idx))
    u_val = put(jnp.asarray(u_val, dtype=dtype))
    u_mask = put(jnp.asarray(u_mask, dtype=dtype))
    i_idx = put(jnp.asarray(i_idx))
    i_val = put(jnp.asarray(i_val, dtype=dtype))
    i_mask = put(jnp.asarray(i_mask, dtype=dtype))

    rng = np.random.default_rng(seed)
    scale = 1.0 / np.sqrt(rank)
    # signed init like the single-chip kernel (abs only for NNLS —
    # see ops/als_kernel.py's init note)
    u0 = rng.normal(size=(u_idx.shape[0], rank)) * scale
    v0 = rng.normal(size=(i_idx.shape[0], rank)) * scale
    if nonneg:
        u0 = np.abs(u0)
        v0 = np.abs(v0)
    # pad rows start at ZERO: implicit mode's dense YᵀY Gram sums the
    # whole gathered table, so random pad rows would bias the first
    # half-sweep's normal equations relative to the single-chip kernel
    u0[n_users:] = 0.0
    v0[n_items:] = 0.0
    u = put(jnp.asarray(u0, dtype=dtype))
    v = put(jnp.asarray(v0, dtype=dtype))
    reg_dev = jnp.asarray(reg, dtype=dtype)
    alpha_dev = jnp.asarray(alpha, dtype=dtype)

    @tracked_jit  # compile the SPMD program once; bare shard_map re-traces
    @partial(jax.shard_map, mesh=mesh,
             in_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None),
                       P(DATA_AXIS, None), P(DATA_AXIS, None),
                       P(DATA_AXIS, None), P(), P()),
             out_specs=P(DATA_AXIS, None))
    def half_sweep(other_shard, idx_s, val_s, mask_s, prev_s, reg_a,
                   alpha_a):
        # the opposite factor table rides ICI once; the solve is local
        other_full = lax.all_gather(other_shard, DATA_AXIS, tiled=True)
        return _solve_side(other_full, idx_s, val_s, mask_s, reg_a,
                           implicit, alpha_a, nonneg, prev_s)

    ctx = current_fit()
    ctx.set_data(rows=n_users + n_items, features=rank)
    ctx.set_iterations(max_iter)
    with ctx.phase("execute"):
        for sweep in range(max_iter):
            # both half-sweeps run inside one monitored step; blocking
            # on v bounds the step at the sweep's true completion
            with current_run().step(
                "als_sweep", rows=n_users + n_items
            ) as mon:
                # each half-sweep all_gathers the OPPOSITE factor table
                # over ICI
                ctx.record_collective(
                    "all_gather",
                    nbytes=collective_nbytes((v0.shape[0], rank), dtype))
                u = half_sweep(v, u_idx, u_val, u_mask, u, reg_dev,
                               alpha_dev)
                ctx.record_collective(
                    "all_gather",
                    nbytes=collective_nbytes((u0.shape[0], rank), dtype))
                v = half_sweep(u, i_idx, i_val, i_mask, v, reg_dev,
                               alpha_dev)
                jax.block_until_ready(v)
                mon.note(sweep=float(sweep))
    u = np.asarray(jax.block_until_ready(u), dtype=np.float64)
    v = np.asarray(jax.block_until_ready(v), dtype=np.float64)
    return u[:n_users], v[:n_items]
