"""Distributed RandomForest: rows sharded over the mesh, histograms psum'd.

The level-synchronous histogram formulation (``ops/forest_kernel.py``)
distributes for free: each shard histograms ITS rows into the tiny
(channels, nodes, features, bins) statistics tensor, one ``psum`` per
level combines them over ICI, and split selection runs replicated — the
identical partials-aggregation shape the reference used for distributed
covariance (``RapidsRowMatrix.scala:168-202``), here applied per tree
level. No data rows ever move; routing stays shard-local.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_ml_tpu.obs import (
    current_fit,
    current_run,
    fit_instrumentation,
    tracked_jit,
)
from spark_rapids_ml_tpu.ops.forest_kernel import (
    TreeEnsemble,
    grow_tree_classification,
    grow_tree_regression,
    quantile_bins,
)
from spark_rapids_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    collective_nbytes,
    pad_rows_to_multiple,
)


@partial(
    tracked_jit,
    static_argnames=("max_depth", "n_bins", "min_leaf", "n_classes", "mesh"),
)
def _sharded_grow(
    binned, y_or_oh, w, feat_mask, max_depth, n_bins, min_leaf,
    n_classes, mesh,
):
    def per_shard(b, yy, ww, fm):
        if n_classes:
            return grow_tree_classification(
                b, yy, ww, fm, max_depth, n_bins, n_classes, min_leaf,
                axis_name=DATA_AXIS,
            )
        return grow_tree_regression(
            b, yy, ww, fm, max_depth, n_bins, min_leaf, axis_name=DATA_AXIS,
        )

    y_spec = P(DATA_AXIS, None) if n_classes else P(DATA_AXIS)
    # outputs are replicated by construction (every shard sees the SAME
    # psum'd histograms and runs the same deterministic selection), but
    # the static analysis can't prove it through argmax/dynamic_update
    return jax.shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), y_spec, P(DATA_AXIS), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )(binned, y_or_oh, w, feat_mask)


@fit_instrumentation("distributed_forest")
def distributed_forest_fit(
    x: np.ndarray,
    y: np.ndarray,
    mesh: Mesh,
    n_trees: int = 20,
    max_depth: int = 5,
    n_bins: int = 32,
    min_leaf: int = 1,
    subsampling_rate: float = 1.0,
    classification: bool = False,
    seed: int = 0,
    dtype=jnp.float32,
) -> Tuple[TreeEnsemble, np.ndarray, np.ndarray, np.ndarray]:
    """(ensemble, edges, classes, split_gains) with rows sharded over
    ``mesh``.

    Bootstrap weights are drawn on host per tree; padding rows carry
    weight 0 so they contribute to no histogram. ``classes`` is None for
    regression; feed (ensemble.feature, split_gains) to
    ``ops.forest_kernel.feature_importances`` for Spark-style
    importances.
    """
    n_dev = int(np.prod(mesh.devices.shape))
    binned_np, edges = quantile_bins(x, n_bins)
    y = np.asarray(y, dtype=np.float64).reshape(-1)
    if classification:
        classes = np.unique(y)
        y_idx = np.searchsorted(classes, y)
        y_payload = np.eye(len(classes))[y_idx]
    else:
        classes = None
        y_payload = y
    binned_p, mask = pad_rows_to_multiple(binned_np, n_dev)
    y_p, _ = pad_rows_to_multiple(y_payload, n_dev)
    rng = np.random.default_rng(seed)
    d = x.shape[1]

    row_shard = NamedSharding(mesh, P(DATA_AXIS, None))
    vec_shard = NamedSharding(mesh, P(DATA_AXIS))
    binned_dev = jax.device_put(
        jnp.asarray(binned_p, dtype=jnp.int32), row_shard
    )
    if classification:
        y_dev = jax.device_put(jnp.asarray(y_p, dtype=dtype), row_shard)
    else:
        y_dev = jax.device_put(jnp.asarray(y_p, dtype=dtype), vec_shard)

    ctx = current_fit()
    # per tree, one histogram psum per depth level: (channels, nodes ≤
    # 2^depth, features, bins) — bounded program-level accounting
    channels = (len(classes) + 1) if classification else 3
    hist_nbytes = collective_nbytes(
        (channels, 2 ** max_depth, d, n_bins), np.dtype(dtype))
    feats_l, thrs_l, leaves_l, gains_l = [], [], [], []
    for tree in range(n_trees):
        ctx.record_collective(
            "all_reduce", nbytes=hist_nbytes, count=max_depth)
        w = rng.poisson(subsampling_rate, binned_p.shape[0]) * mask
        w_dev = jax.device_put(jnp.asarray(w, dtype=dtype), vec_shard)
        fm = jnp.asarray(
            np.ones((max_depth, d)), dtype=dtype
        )  # feature subsets: host-side choice mirrors the local fit
        # the np.asarray conversions block on the grown tree, so the
        # step's wall time covers the full level-synchronous growth
        with current_run().step(
            "grow_tree", rows=x.shape[0]
        ) as mon:
            f, t, leaf, g = _sharded_grow(
                binned_dev, y_dev, w_dev, fm, max_depth, n_bins,
                min_leaf, len(classes) if classification else 0, mesh,
            )
            feats_l.append(np.asarray(f))
            thrs_l.append(np.asarray(t))
            leaves_l.append(np.asarray(leaf))
            gains_l.append(np.asarray(g))
            mon.note(tree=float(tree))
    ensemble = TreeEnsemble(
        feature=np.stack(feats_l),
        threshold=np.stack(thrs_l),
        leaf_value=np.stack(leaves_l),
    )
    return ensemble, edges, classes, np.stack(gains_l)
