"""Unified telemetry: metrics registry, trace spans, per-fit reports.

One import surface for everything observability:

* ``get_registry()`` — the process-wide metrics registry (counters,
  gauges, histograms with labels; ``.snapshot()`` for JSON,
  ``.prometheus_text()`` / ``start_prometheus_server()`` for scraping);
* ``span(...)`` / ``get_recorder()`` — structured nested trace spans in a
  ring buffer, exportable as Chrome-trace/Perfetto JSON (env-gated on
  ``SPARK_RAPIDS_ML_TPU_TRACE_DIR``);
* ``fit_instrumentation`` / ``observed_fit`` / ``current_fit`` — the
  shared instrumentation entry points that give every distributed driver
  and estimator a uniform ``fit_report_``;
* ``observed_transform`` / ``current_transform`` / ``transform_phase`` —
  the serving tier (``obs.serving``): every transform/predict entry point
  yields a ``TransformReport``, feeds the latency quantile sketch
  (``obs.quantiles``), and passes the numerics sentinel;
* back-compat re-exports of the underlying ``utils`` primitives
  (``TraceRange``, ``PhaseTimer``, ``DeviceHealth``…), so telemetry
  consumers need only this package.
"""

from spark_rapids_ml_tpu.obs.metrics import (  # noqa: F401
    Counter,
    DEFAULT_BUCKETS,
    Gauge,
    Histogram,
    MetricsRegistry,
    Summary,
    get_registry,
    start_prometheus_server,
)
from spark_rapids_ml_tpu.obs.quantiles import (  # noqa: F401
    QuantileSketch,
    merge_all,
)
from spark_rapids_ml_tpu.obs.spans import (  # noqa: F401
    SpanEvent,
    SpanRecorder,
    TRACE_DIR_ENV,
    active_spans,
    assemble_trace,
    current_span_id,
    current_trace_id,
    get_recorder,
    maybe_export_trace,
    new_trace_id,
    recent_traces,
    record_event,
    span,
)
from spark_rapids_ml_tpu.obs.tracectx import (  # noqa: F401
    TRACEPARENT_HEADER,
    TraceContext,
    activate,
    capture,
    current_context,
    ensure_context,
    inflight_request,
    inflight_requests,
    new_context,
    new_span_id,
    parse_traceparent,
    traced_thread,
)
from spark_rapids_ml_tpu.obs.slo import (  # noqa: F401
    BURN_POLICIES,
    SLO,
    SloSet,
    WindowedCounts,
    default_slos,
)
from spark_rapids_ml_tpu.obs.xprof import (  # noqa: F401
    CompileEvent,
    STORM_ENV,
    TrackedJit,
    analytic_mfu,
    clear_all_signature_caches,
    compile_log,
    compile_stats,
    peak_flops_per_second,
    reset_compile_log,
    signature_count,
    track_compiles,
    tracked_jit,
)
from spark_rapids_ml_tpu.obs.aotcache import (  # noqa: F401
    ExecutableCache,
    configure_executable_cache,
    get_executable_cache,
)
from spark_rapids_ml_tpu.obs.memory import (  # noqa: F401
    device_memory_stats,
    host_peak_rss_bytes,
    memory_watermarks,
    peak_bytes_in_use,
    record_memory_metrics,
)
from spark_rapids_ml_tpu.obs.flight import (  # noqa: F401
    DUMP_DIR_ENV,
    FIT_BUDGET_ENV,
    TRANSFORM_BUDGET_ENV,
    Watchdog,
    build_dump,
    deadline,
    dump,
    dump_dir,
    get_watchdog,
)
from spark_rapids_ml_tpu.obs import flight  # noqa: F401
from spark_rapids_ml_tpu.obs.logging import (  # noqa: F401
    StructuredLogger,
    get_logger,
)
from spark_rapids_ml_tpu.obs.robust import (  # noqa: F401
    mad,
    noise_band,
    robust_zscore,
)
from spark_rapids_ml_tpu.obs.anomaly import (  # noqa: F401
    Detector,
    Finding,
    MadSpikeDetector,
    RateOfChangeDetector,
    ThresholdDetector,
    builtin_detectors,
)
from spark_rapids_ml_tpu.obs.incidents import (  # noqa: F401
    Incident,
    IncidentEngine,
    IncidentManager,
    get_incident_engine,
    reset_incident_engine,
)
from spark_rapids_ml_tpu.obs import retention  # noqa: F401
from spark_rapids_ml_tpu.obs.tsdb import (  # noqa: F401
    MetricsSampler,
    TimeSeriesStore,
    get_sampler,
    get_tsdb,
    start_sampling,
    stop_sampling,
)
from spark_rapids_ml_tpu.obs.devmon import (  # noqa: F401
    DeviceMonitor,
    get_device_monitor,
)
from spark_rapids_ml_tpu.obs import profiler  # noqa: F401
from spark_rapids_ml_tpu.obs.fitmon import (  # noqa: F401
    BackendWatchdog,
    FitMonitor,
    FitRun,
    StepMonitor,
    current_run,
    debug_fit_doc,
    detect_stragglers,
    device_peaks,
    fit_report,
    fit_run,
    get_fit_monitor,
    reset_fitmon,
    roofline_bound,
    step_mfu,
)
from spark_rapids_ml_tpu.obs.report import (  # noqa: F401
    FitContext,
    FitReport,
    REPORT_ATTR,
    attach_report,
    current_fit,
    fit_instrumentation,
    last_fit_report,
    observed_fit,
)
from spark_rapids_ml_tpu.obs.serving import (  # noqa: F401
    NUMERICS_SAMPLE_ENV,
    TRANSFORM_REPORT_ATTR,
    TransformContext,
    TransformReport,
    check_output_numerics,
    current_transform,
    last_transform_report,
    latency_quantiles,
    observed_transform,
    transform_phase,
)

# Back-compat shims: the pre-obs utils primitives, re-exported so telemetry
# call sites can import everything from one place (utils.* keeps working).
from spark_rapids_ml_tpu.utils.tracing import (  # noqa: F401
    TraceColor,
    TraceRange,
)
from spark_rapids_ml_tpu.utils.timing import PhaseTimer  # noqa: F401
from spark_rapids_ml_tpu.utils.health import (  # noqa: F401
    DeviceHealth,
    check_devices,
    check_devices_subprocess,
)

__all__ = [
    "BURN_POLICIES",
    "CompileEvent",
    "Counter",
    "DEFAULT_BUCKETS",
    "DUMP_DIR_ENV",
    "BackendWatchdog",
    "Detector",
    "DeviceHealth",
    "DeviceMonitor",
    "FIT_BUDGET_ENV",
    "Finding",
    "FitContext",
    "FitMonitor",
    "FitReport",
    "FitRun",
    "StepMonitor",
    "Gauge",
    "Histogram",
    "Incident",
    "IncidentEngine",
    "IncidentManager",
    "MadSpikeDetector",
    "RateOfChangeDetector",
    "ThresholdDetector",
    "MetricsRegistry",
    "MetricsSampler",
    "NUMERICS_SAMPLE_ENV",
    "PhaseTimer",
    "QuantileSketch",
    "REPORT_ATTR",
    "SLO",
    "STORM_ENV",
    "SloSet",
    "SpanEvent",
    "SpanRecorder",
    "StructuredLogger",
    "Summary",
    "TRACEPARENT_HEADER",
    "TRACE_DIR_ENV",
    "TRANSFORM_BUDGET_ENV",
    "TRANSFORM_REPORT_ATTR",
    "TimeSeriesStore",
    "TraceColor",
    "TraceContext",
    "TraceRange",
    "TrackedJit",
    "TransformContext",
    "TransformReport",
    "Watchdog",
    "activate",
    "active_spans",
    "analytic_mfu",
    "assemble_trace",
    "attach_report",
    "build_dump",
    "builtin_detectors",
    "capture",
    "check_devices",
    "check_devices_subprocess",
    "check_output_numerics",
    "compile_log",
    "clear_all_signature_caches",
    "compile_stats",
    "configure_executable_cache",
    "ExecutableCache",
    "get_executable_cache",
    "signature_count",
    "current_context",
    "current_fit",
    "current_run",
    "current_span_id",
    "current_trace_id",
    "current_transform",
    "deadline",
    "debug_fit_doc",
    "default_slos",
    "detect_stragglers",
    "device_memory_stats",
    "device_peaks",
    "dump",
    "dump_dir",
    "ensure_context",
    "fit_instrumentation",
    "fit_report",
    "fit_run",
    "flight",
    "get_device_monitor",
    "get_fit_monitor",
    "get_incident_engine",
    "get_logger",
    "get_recorder",
    "get_registry",
    "get_sampler",
    "get_tsdb",
    "get_watchdog",
    "host_peak_rss_bytes",
    "mad",
    "inflight_request",
    "inflight_requests",
    "last_fit_report",
    "last_transform_report",
    "latency_quantiles",
    "maybe_export_trace",
    "memory_watermarks",
    "merge_all",
    "new_context",
    "new_span_id",
    "new_trace_id",
    "noise_band",
    "observed_fit",
    "observed_transform",
    "parse_traceparent",
    "peak_bytes_in_use",
    "peak_flops_per_second",
    "profiler",
    "recent_traces",
    "record_event",
    "record_memory_metrics",
    "reset_compile_log",
    "reset_fitmon",
    "reset_incident_engine",
    "retention",
    "robust_zscore",
    "roofline_bound",
    "step_mfu",
    "span",
    "start_prometheus_server",
    "start_sampling",
    "stop_sampling",
    "traced_thread",
    "track_compiles",
    "tracked_jit",
    "transform_phase",
    "WindowedCounts",
]
