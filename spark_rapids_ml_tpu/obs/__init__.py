"""Unified telemetry: metrics registry, trace spans, per-fit reports.

One import surface for everything observability:

* ``get_registry()`` — the process-wide metrics registry (counters,
  gauges, histograms with labels; ``.snapshot()`` for JSON,
  ``.prometheus_text()`` / ``start_prometheus_server()`` for scraping);
* ``span(...)`` / ``get_recorder()`` — structured nested trace spans in a
  ring buffer, exportable as Chrome-trace/Perfetto JSON (env-gated on
  ``SPARK_RAPIDS_ML_TPU_TRACE_DIR``);
* ``fit_instrumentation`` / ``observed_fit`` / ``current_fit`` — the
  shared instrumentation entry points that give every distributed driver
  and estimator a uniform ``fit_report_``;
* back-compat re-exports of the underlying ``utils`` primitives
  (``TraceRange``, ``PhaseTimer``, ``DeviceHealth``…), so telemetry
  consumers need only this package.
"""

from spark_rapids_ml_tpu.obs.metrics import (  # noqa: F401
    Counter,
    DEFAULT_BUCKETS,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    start_prometheus_server,
)
from spark_rapids_ml_tpu.obs.spans import (  # noqa: F401
    SpanEvent,
    SpanRecorder,
    TRACE_DIR_ENV,
    current_trace_id,
    get_recorder,
    maybe_export_trace,
    new_trace_id,
    span,
)
from spark_rapids_ml_tpu.obs.report import (  # noqa: F401
    FitContext,
    FitReport,
    REPORT_ATTR,
    attach_report,
    current_fit,
    fit_instrumentation,
    last_fit_report,
    observed_fit,
    observed_transform,
)

# Back-compat shims: the pre-obs utils primitives, re-exported so telemetry
# call sites can import everything from one place (utils.* keeps working).
from spark_rapids_ml_tpu.utils.tracing import (  # noqa: F401
    TraceColor,
    TraceRange,
)
from spark_rapids_ml_tpu.utils.timing import PhaseTimer  # noqa: F401
from spark_rapids_ml_tpu.utils.health import (  # noqa: F401
    DeviceHealth,
    check_devices,
    check_devices_subprocess,
)

__all__ = [
    "Counter",
    "DEFAULT_BUCKETS",
    "DeviceHealth",
    "FitContext",
    "FitReport",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "PhaseTimer",
    "REPORT_ATTR",
    "SpanEvent",
    "SpanRecorder",
    "TRACE_DIR_ENV",
    "TraceColor",
    "TraceRange",
    "attach_report",
    "check_devices",
    "check_devices_subprocess",
    "current_fit",
    "current_trace_id",
    "fit_instrumentation",
    "get_recorder",
    "get_registry",
    "last_fit_report",
    "maybe_export_trace",
    "new_trace_id",
    "observed_fit",
    "observed_transform",
    "span",
    "start_prometheus_server",
]
