"""Serving-side observability: the instrumented transform/predict path.

PRs 1–2 made every *fit* measurable; this module does the same for the
per-request runtime path the north-star actually serves — every public
``transform``/``predict``/``predict_proba`` in ``models/`` and the
``spark/`` adapters is wrapped in ``@observed_transform("<algo>")``
(enforced statically by ``scripts/check_instrumentation.py``), producing:

* a ``TransformReport`` per call — rows, batches, bytes in/out, the
  device-put / compute / host-sync phase split (bodies record phases via
  ``transform_phase(...)``), and compile/recompile attribution fed by
  ``obs.xprof.tracked_jit`` exactly as fits get it;
* per-call latency into a mergeable streaming quantile sketch
  (``obs.quantiles``) behind a ``Summary`` metric, so the registry reports
  *true* p50/p95/p99 per algo — the fixed histogram buckets cannot;
* a **numerics sentinel**: a cheap NaN/Inf/all-zero check over the new
  output columns (env-gated sampling via
  ``SPARK_RAPIDS_ML_TPU_NUMERICS_SAMPLE``), counted per algo and surfaced
  in snapshots and the Prometheus text endpoint — a model silently
  emitting NaNs under traffic is an outage, not a curiosity;
* the ``obs.flight`` watchdog armed around every call
  (``SPARK_RAPIDS_ML_TPU_TRANSFORM_BUDGET_SECONDS``, default 120s), so a
  wedged serving call produces a flight dump instead of a silent hang.

Delegation shims (``Model.transform`` → ``self._transform``, both
decorated so the static check stays exhaustive) are deduplicated by
instance identity: re-entering the decorator on the *same* object extends
the open report instead of double-counting the call. Distinct nested
models (pipeline stages, adapter → local model) each get their own report,
tagged with the parent algo.

Telemetry never breaks a transform: everything outside the wrapped call is
exception-guarded, mirroring ``obs.report``.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import os
import random
import re
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Dict, List, NamedTuple, Optional

import numpy as np

from spark_rapids_ml_tpu.obs import spans
from spark_rapids_ml_tpu.obs.metrics import get_registry
from spark_rapids_ml_tpu.utils.timing import PhaseTimer
from spark_rapids_ml_tpu.utils.tracing import TraceColor

TRANSFORM_REPORT_ATTR = "transform_report_"
NUMERICS_SAMPLE_ENV = "SPARK_RAPIDS_ML_TPU_NUMERICS_SAMPLE"
LATENCY_SUMMARY = "sparkml_transform_latency_seconds"
LATENCY_QUANTILES = (0.5, 0.95, 0.99)
SKETCH_ALPHA = 0.01
# Sentinel cost ceiling: never isnan/isinf more than this many rows per
# call — large batches are strided down to the cap.
_SENTINEL_ROW_CAP = 65536


def numerics_sample_rate() -> float:
    """Fraction of transform calls whose outputs get the numerics check
    (default 1.0 — the check is vectorized and row-capped; set 0 to
    disable, 0.01 to spot-check one call in a hundred under load)."""
    try:
        rate = float(os.environ.get(NUMERICS_SAMPLE_ENV, "1.0"))
    except ValueError:
        return 1.0
    return min(max(rate, 0.0), 1.0)


# -- the per-call report ---------------------------------------------------


@dataclass
class TransformReport:
    """The uniform per-transform observability artifact (the serving-side
    sibling of ``FitReport``)."""

    algo: str
    trace_id: str
    started_utc: str
    wall_seconds: float
    span_id: Optional[str] = None
    phases: Dict[str, float] = field(default_factory=dict)
    rows: Optional[int] = None
    features: Optional[int] = None
    batches: int = 1
    bytes_in: Optional[int] = None
    bytes_out: Optional[int] = None
    rows_per_second: Optional[float] = None
    # XLA compile attribution for programs executed by this call
    compiles: int = 0
    recompiles: int = 0
    compile_seconds: float = 0.0
    analytic_flops: Optional[float] = None
    # numerics sentinel verdict for this call (None: not sampled/no arrays)
    numerics: Optional[Dict[str, Any]] = None
    nested_in: Optional[str] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    # The registry sketch for this algo rides along as a plain attribute
    # (set by the decorator, not a dataclass field) so quantiles resolve
    # LAZILY: the hot path pays nothing per call, readers get live values.

    @property
    def latency_quantiles(self) -> Dict[str, Optional[float]]:
        """Registry-wide sketch-backed p50/p95/p99 for this algo, resolved
        at read time (a ~50µs cached transform should not pay three
        quantile queries per call it never reads)."""
        sketch = getattr(self, "_sketch", None)
        if sketch is None:
            return {}
        return sketch.quantiles(LATENCY_QUANTILES)

    def as_dict(self) -> Dict[str, Any]:
        d = asdict(self)
        d["latency_quantiles"] = self.latency_quantiles
        return d

    def _quantile(self, q: float) -> Optional[float]:
        sketch = getattr(self, "_sketch", None)
        return sketch.quantile(q) if sketch is not None else None

    @property
    def p50(self) -> Optional[float]:
        return self._quantile(0.5)

    @property
    def p95(self) -> Optional[float]:
        return self._quantile(0.95)

    @property
    def p99(self) -> Optional[float]:
        return self._quantile(0.99)


class TransformContext:
    """Mutable accounting for one in-flight transform/predict call.

    Obtained inside an instrumented body via ``current_transform()``;
    bodies record phases (``with ctx.phase("device_put"): ...``) and may
    override the inferred data stats. ``obs.xprof`` feeds compile events
    into it exactly as it feeds the fit context.
    """

    __slots__ = (
        "algo", "trace_id", "span_id", "timer", "rows", "features",
        "batches", "bytes_in", "bytes_out", "compiles", "recompiles",
        "compile_seconds", "analytic_flops", "extra",
        "owner_id", "explicit", "nested_in", "_lock",
    )

    def __init__(self, algo: str, trace_id: Optional[str] = None,
                 owner_id: Optional[int] = None, explicit: bool = True,
                 nested_in: Optional[str] = None):
        self.algo = algo
        self.trace_id = trace_id or spans.new_trace_id()
        self.span_id: Optional[str] = None
        self.timer = PhaseTimer()
        self.rows: Optional[int] = None
        self.features: Optional[int] = None
        self.batches = 1
        self.bytes_in: Optional[int] = None
        self.bytes_out: Optional[int] = None
        self.compiles = 0
        self.recompiles = 0
        self.compile_seconds = 0.0
        self.analytic_flops = 0.0
        self.extra: Dict[str, Any] = {}
        self.owner_id = owner_id
        self.explicit = explicit
        self.nested_in = nested_in
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def phase(self, name: str):
        """Time a named serving phase AND emit a nested trace span."""
        with self.timer.phase(name), spans.span(
            f"{self.algo}:{name}", TraceColor.PURPLE
        ):
            yield

    def record_compile(self, label: str, seconds: float, *,
                       recompile: bool = False) -> None:
        """Called by ``obs.xprof`` when a tracked function compiles during
        this call."""
        with self._lock:
            self.compiles += 1
            if recompile:
                self.recompiles += 1
            self.compile_seconds += float(seconds)

    def record_program(self, label: str, flops: Optional[float],
                       nbytes: Optional[float]) -> None:
        with self._lock:
            if flops:
                self.analytic_flops += float(flops)

    def set_data(self, rows: Optional[int] = None,
                 features: Optional[int] = None,
                 nbytes: Optional[int] = None) -> None:
        if rows is not None:
            self.rows = int(rows)
        if features is not None:
            self.features = int(features)
        if nbytes is not None:
            self.bytes_in = int(nbytes)

    def add_batch(self, n: int = 1) -> None:
        with self._lock:
            self.batches += int(n)

    def note(self, **kwargs) -> None:
        self.extra.update(kwargs)


class _NullTransformContext(TransformContext):
    """No-op context so bodies may call ``current_transform()``
    unconditionally, even outside any instrumented entry point."""

    def __init__(self):
        super().__init__("_unobserved")

    @contextlib.contextmanager
    def phase(self, name: str):
        yield

    def record_compile(self, *args, **kwargs) -> None:
        pass

    def record_program(self, *args, **kwargs) -> None:
        pass

    def set_data(self, *args, **kwargs) -> None:
        pass

    def add_batch(self, *args, **kwargs) -> None:
        pass

    def note(self, **kwargs) -> None:
        pass


_NULL_CONTEXT = _NullTransformContext()
_current_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "sparkml_transform_ctx", default=None
)

_last_reports: Dict[Optional[str], TransformReport] = {}
_last_lock = threading.Lock()


def current_transform() -> TransformContext:
    """The active call's context, or a no-op context outside any call."""
    ctx = _current_ctx.get()
    return ctx if ctx is not None else _NULL_CONTEXT


@contextlib.contextmanager
def transform_phase(name: str):
    """Sugar for ``current_transform().phase(name)`` — what instrumented
    bodies use to record the device-put/compute/host-sync split."""
    with current_transform().phase(name):
        yield


def last_transform_report(algo: Optional[str] = None
                          ) -> Optional[TransformReport]:
    """Most recent report (optionally for one algo) — the escape hatch for
    outputs the report cannot be attached to."""
    with _last_lock:
        return _last_reports.get(algo)


def latency_quantiles(algo: str) -> Dict[str, Optional[float]]:
    """Registry-wide sketch-backed ``{"p50", "p95", "p99"}`` latency
    (seconds) for one algo's instrumented transforms."""
    summary = get_registry().summary(
        LATENCY_SUMMARY, "transform/predict call latency", ("algo",),
        alpha=SKETCH_ALPHA, quantiles=LATENCY_QUANTILES,
    )
    return summary.sketch(algo=algo).quantiles(LATENCY_QUANTILES)


# -- data-stat inference ---------------------------------------------------


def _array_nbytes(value) -> Optional[int]:
    nbytes = getattr(value, "nbytes", None)
    if nbytes is not None:
        return int(nbytes)
    return None


def _dataset_stats(value) -> Dict[str, Optional[int]]:
    """(rows, features, nbytes) for an ndarray or VectorFrame-like input.

    Deliberately cheap: never materializes vector columns — list columns
    are estimated at 8 bytes/element, ndarray columns read ``nbytes``.
    """
    out: Dict[str, Optional[int]] = {
        "rows": None, "features": None, "nbytes": None
    }
    shape = getattr(value, "shape", None)
    if isinstance(shape, tuple) and shape:
        out["rows"] = int(shape[0])
        out["features"] = int(shape[1]) if len(shape) > 1 else None
        out["nbytes"] = _array_nbytes(value)
        return out
    columns = getattr(value, "columns", None)
    column = getattr(value, "column", None)
    if callable(columns):
        columns = None  # Spark DataFrames: columns is an attr, ours too
    if not columns:
        return out
    try:
        out["rows"] = len(value)
    except TypeError:
        # pyspark DataFrames have no len(); counting would run the query
        return out
    if callable(column):
        total = 0
        for name in columns:
            try:
                col = column(name)
            except Exception:
                continue
            nbytes = _array_nbytes(col)
            if nbytes is None:
                # list column: 8 bytes per ELEMENT — vector rows carry
                # len(first) elements each, scalar rows one
                width = 1
                try:
                    first = col[0]
                    if hasattr(first, "__len__"):
                        width = max(len(first), 1)
                except (IndexError, KeyError, TypeError):
                    pass
                nbytes = out["rows"] * width * 8
            total += nbytes
        out["nbytes"] = total
    return out


# -- numerics sentinel -----------------------------------------------------


def _sample_rows(col):
    """A row-capped view/copy of a column for the sentinel check."""
    n = len(col)
    if n <= _SENTINEL_ROW_CAP:
        return col
    step = -(-n // _SENTINEL_ROW_CAP)  # ceil div: stride over the batch
    return col[::step]


def _as_numeric_matrix(col) -> Optional[np.ndarray]:
    """A float ndarray for one sampled output column, or None for
    non-numeric data (strings, token arrays, itemset lists...)."""
    try:
        if isinstance(col, np.ndarray):
            if not np.issubdtype(col.dtype, np.number):
                return None
            return col if np.issubdtype(col.dtype, np.floating) \
                else col.astype(np.float64, copy=False)
        rows = list(col)
        if not rows:
            return None
        first = rows[0]
        if hasattr(first, "toArray"):
            rows = [r.toArray() for r in rows]
        arr = np.asarray(rows, dtype=np.float64)
        if arr.dtype.kind not in "fc":
            return None
        return arr
    except (TypeError, ValueError):
        return None


# Column-name getters models expose for their INPUT columns; the sentinel
# never judges carried-over inputs, only what the model produced. (The
# input frame's columns are excluded too, but a bare-ndarray input has no
# column names — the getters close that gap.)
_INPUT_COL_GETTERS = (
    "getInputCol", "getFeaturesCol", "getItemsCol", "getUserCol",
    "getItemCol", "getLabelCol",
)


def _model_input_columns(model) -> List[str]:
    out: List[str] = []
    for getter in _INPUT_COL_GETTERS:
        fn = getattr(model, getter, None)
        if not callable(fn):
            continue
        try:
            name = fn()
        except Exception:
            continue
        if isinstance(name, str) and name:
            out.append(name)
    return out


def check_output_numerics(result, input_columns=()) -> Optional[
        Dict[str, Any]]:
    """The sentinel core: NaN / Inf / all-zero verdict over a transform's
    NEW output columns (or the raw prediction array).

    Returns ``{"checked_rows", "nan_rows", "inf_rows", "all_zero",
    "columns"}`` or None when the output carries nothing checkable (lazy
    Spark DataFrames, string columns, ...). Row-capped by striding — cost
    is bounded regardless of batch size.
    """
    targets: List[Any] = []
    names: List[str] = []
    if isinstance(result, np.ndarray):
        targets.append(result)
        names.append("<array>")
    else:
        columns = getattr(result, "columns", None)
        column = getattr(result, "column", None)
        if columns and not callable(columns) and callable(column):
            known = set(input_columns or ())
            for name in columns:
                if name in known:
                    continue
                try:
                    targets.append(column(name))
                    names.append(name)
                except Exception:
                    continue
    checked = 0
    nan_rows = 0
    inf_rows = 0
    all_zero = False
    checked_names: List[str] = []
    for name, col in zip(names, targets):
        matrix = _as_numeric_matrix(_sample_rows(col))
        if matrix is None or matrix.size == 0:
            continue
        flat = matrix.reshape(matrix.shape[0], -1) if matrix.ndim > 1 \
            else matrix.reshape(-1, 1)
        nan_mask = np.isnan(flat).any(axis=1)
        inf_mask = np.isinf(flat).any(axis=1)
        checked = max(checked, int(flat.shape[0]))
        nan_rows += int(nan_mask.sum())
        inf_rows += int(inf_mask.sum())
        if not np.any(flat):
            all_zero = True
        checked_names.append(name)
    if not checked_names:
        return None
    return {
        "checked_rows": checked,
        "nan_rows": nan_rows,
        "inf_rows": inf_rows,
        "all_zero": all_zero,
        "columns": checked_names,
    }


def _record_numerics(algo: str, verdict: Dict[str, Any]) -> None:
    reg = get_registry()
    reg.counter(
        "sparkml_numerics_checks_total",
        "transform outputs inspected by the numerics sentinel", ("algo",),
    ).inc(algo=algo)
    anomalies = reg.counter(
        "sparkml_numerics_anomalies_total",
        "anomalous transform outputs (rows with NaN/Inf) caught by the "
        "numerics sentinel", ("algo", "kind"),
    )
    if verdict["nan_rows"]:
        anomalies.inc(verdict["nan_rows"], algo=algo, kind="nan")
    if verdict["inf_rows"]:
        anomalies.inc(verdict["inf_rows"], algo=algo, kind="inf")
    if verdict["all_zero"]:
        # All-zero is a heads-up, not an anomaly: class-0 prediction
        # batches, cluster 0, and sparse binarized features are all
        # legitimately zero. Its own series keeps it watchable without
        # polluting the paging counter.
        reg.counter(
            "sparkml_numerics_all_zero_total",
            "all-zero transform output batches (informational — "
            "legitimately nonzero for label/sparse outputs)", ("algo",),
        ).inc(algo=algo)


# -- report assembly / publication -----------------------------------------


_utcnow = spans.utcnow_iso

_CAMEL_RE = re.compile(r"(?<=[a-z0-9])(?=[A-Z])")


def _derive_algo(obj) -> str:
    """A metrics-label-safe algo name from the instance's class:
    ``StandardScalerModel`` → ``standard_scaler``."""
    name = type(obj).__name__.lstrip("_")
    for suffix in ("Model", "Adapter"):
        if name.endswith(suffix) and len(name) > len(suffix):
            name = name[: -len(suffix)]
    return _CAMEL_RE.sub("_", name).lower()


def _build_report(ctx: TransformContext, started: str,
                  wall: float) -> TransformReport:
    phases = ctx.timer.as_dict()
    phases.setdefault("total", wall)
    rows_per_second = None
    if ctx.rows and wall > 0:
        rows_per_second = ctx.rows / wall
    return TransformReport(
        algo=ctx.algo,
        trace_id=ctx.trace_id,
        started_utc=started,
        wall_seconds=wall,
        span_id=ctx.span_id,
        phases=phases,
        rows=ctx.rows,
        features=ctx.features,
        batches=ctx.batches,
        bytes_in=ctx.bytes_in,
        bytes_out=ctx.bytes_out,
        rows_per_second=rows_per_second,
        compiles=ctx.compiles,
        recompiles=ctx.recompiles,
        compile_seconds=ctx.compile_seconds,
        analytic_flops=ctx.analytic_flops or None,
        nested_in=ctx.nested_in,
        extra=dict(ctx.extra),
    )


def _record_metrics(report: TransformReport) -> None:
    reg = get_registry()
    algo = report.algo
    reg.counter(
        "sparkml_transforms_total", "completed transform/predict calls",
        ("algo",),
    ).inc(algo=algo)
    # Fixed-bucket histogram AND sketch summary: buckets for rate queries,
    # the sketch for true percentiles.
    reg.histogram(
        "sparkml_transform_seconds", "transform/predict wall-clock seconds",
        ("algo",),
    ).observe(report.wall_seconds, algo=algo)
    summary = reg.summary(
        LATENCY_SUMMARY, "transform/predict call latency", ("algo",),
        alpha=SKETCH_ALPHA, quantiles=LATENCY_QUANTILES,
    )
    # trace-id exemplar: a worsening p99 names the exact calls behind it
    summary.observe(report.wall_seconds, trace_id=report.trace_id,
                    algo=algo)
    report._sketch = summary.sketch(algo=algo)  # lazy quantile source
    if report.rows:
        reg.counter(
            "sparkml_rows_transformed_total", "rows seen by transforms",
            ("algo",),
        ).inc(report.rows, algo=algo)
    if report.bytes_in:
        reg.counter(
            "sparkml_transform_bytes_in_total",
            "input bytes seen by transforms", ("algo",),
        ).inc(report.bytes_in, algo=algo)
    if report.bytes_out:
        reg.counter(
            "sparkml_transform_bytes_out_total",
            "output bytes produced by transforms", ("algo",),
        ).inc(report.bytes_out, algo=algo)
    if report.compiles:
        reg.counter(
            "sparkml_transform_compiles_total",
            "XLA compilations attributed to transforms", ("algo",),
        ).inc(report.compiles, algo=algo)
    if report.recompiles:
        reg.counter(
            "sparkml_transform_recompiles_total",
            "XLA re-compilations attributed to transforms", ("algo",),
        ).inc(report.recompiles, algo=algo)


def _publish(report: TransformReport) -> None:
    with _last_lock:
        _last_reports[report.algo] = report
        _last_reports[None] = report
    spans.maybe_export_trace(report.trace_id, f"transform_{report.algo}")


def _flight_deadline(algo: str, trace_id: str):
    try:
        from spark_rapids_ml_tpu.obs import flight

        return flight.deadline(
            f"transform:{algo}",
            budget_seconds=flight.transform_budget_seconds(),
            trace_id=trace_id,
        )
    except Exception:
        return contextlib.nullcontext()


# -- the pipelined (async-dispatch) serving path ---------------------------


class ServingProgram(NamedTuple):
    """A model's device-resident serving program for the pipelined
    micro-batcher (``serve.batching``): the three hot-path steps split so
    the batcher can overlap them across batches.

    * ``put(host_matrix) → device_handle`` — start the host→device
      transfer of a staged (bucket, d) batch (``jax.device_put``);
    * ``run(device_handle) → device_result`` — launch the compiled
      transform via JAX **async dispatch**, returning without forcing a
      host sync;
    * ``fetch(device_result) → np.ndarray`` — THE host sync
      (``np.asarray``), called only from the batcher's designated
      completion step (rule 9 of ``scripts/check_instrumentation.py``).

    ``dtype`` is the numpy dtype the batcher coerces/stages requests in
    (the model's transform dtype — the submit-time f64 blanket coercion
    is gone); ``algo`` labels the per-batch TransformReport; ``precision``
    records which variant ladder (native / bf16 / int8) is compiled.
    """

    put: Callable[[np.ndarray], Any]
    run: Callable[[Any], Any]
    fetch: Callable[[Any], np.ndarray]
    dtype: Any
    algo: str
    precision: str = "native"
    # optional compile-without-execute hook (``TrackedJit.prime``): the
    # warm-restart replay primes each bucket's executable — a disk-cache
    # load when the persistent cache is on — without paying a zero-batch
    # execution per bucket. None → warmup falls back to put/run/fetch.
    prime: Optional[Callable[[Any], bool]] = None
    # device bytes the program's staged weights occupy (summed over the
    # weights actually device_put at build time; replicated sharding
    # counts every physical copy). The resource ledger
    # (``obs.accounting``) charges this per replica — 0 means the
    # builder could not size its weights, not that they are free.
    weight_bytes: int = 0


class PipelineTransform:
    """Per-batch observability for the pipelined serving path.

    The async pipeline runs AROUND the models' decorated ``transform``
    entry points (the decorator's blocking call-shape cannot span a
    stage/dispatch/sync split that interleaves across batches), so this
    object replaces it batch-for-batch: same ``TransformReport`` artifact,
    same latency sketch, same numerics sentinel — with the phase split
    attributed as ``stage`` (pad + host→device transfer), ``dispatch``
    (async launch) and ``sync`` (the completion-step host sync) instead of
    device_put/compute/host_sync. Compile events from ``tracked_jit``
    attribute through ``dispatch_scope()`` exactly as they do for
    decorated calls. Telemetry never breaks serving: ``finish`` is
    exception-guarded end to end.
    """

    __slots__ = ("_ctx", "_started", "_t0")

    def __init__(self, algo: str, trace_id: Optional[str] = None,
                 precision: str = "native"):
        self._ctx = TransformContext(algo, trace_id=trace_id)
        if precision and precision != "native":
            self._ctx.note(precision=precision)
        self._ctx.note(pipelined=True)
        self._started = _utcnow()
        self._t0 = time.perf_counter()

    def add_phase(self, name: str, seconds: float) -> None:
        """Accumulate one pre-measured pipeline phase (stage / dispatch /
        sync) into the report's phase split."""
        try:
            self._ctx.timer.add(name, seconds)
        except Exception:
            pass

    @contextlib.contextmanager
    def dispatch_scope(self):
        """Activate this batch's context around the async dispatch call so
        ``tracked_jit`` compile/recompile events attribute to THIS batch's
        report (warmup misses surface per batch, not as mystery stalls)."""
        token = _current_ctx.set(self._ctx)
        try:
            yield self._ctx
        finally:
            _current_ctx.reset(token)

    def finish(self, result: Optional[np.ndarray] = None, *,
               rows: Optional[int] = None,
               features: Optional[int] = None,
               bytes_in: Optional[int] = None,
               error: Optional[BaseException] = None,
               parent_span_id: Optional[str] = None,
               ) -> Optional[TransformReport]:
        """Close the batch: build/record/publish its TransformReport (or
        count the error — failed batches never feed the success sketch).
        Also files the batch's ``transform:<algo>`` span (externally
        timed, stage start → completion) so an assembled request tree
        keeps the server → queue → batch → transform shape the decorated
        sync path produces; ``parent_span_id`` nests it under the
        batcher's fan-in batch span."""
        try:
            ctx = self._ctx
            if error is not None:
                get_registry().counter(
                    "sparkml_transform_errors_total",
                    "transform/predict calls that raised",
                    ("algo", "error"),
                ).inc(algo=ctx.algo, error=type(error).__name__)
                return None
            wall = time.perf_counter() - self._t0
            ctx.span_id = spans.record_event(
                f"transform:{ctx.algo}",
                self._t0, self._t0 + wall,
                trace_id=ctx.trace_id, parent_span_id=parent_span_id,
                rows=rows, pipelined=True,
            ).span_id
            ctx.set_data(rows=rows, features=features, nbytes=bytes_in)
            if result is not None and ctx.bytes_out is None:
                ctx.bytes_out = _array_nbytes(result)
            report = _build_report(ctx, self._started, wall)
            rate = numerics_sample_rate()
            if result is not None and rate > 0 and (
                    rate >= 1.0 or random.random() < rate):
                verdict = check_output_numerics(result)
                if verdict is not None:
                    report.numerics = verdict
                    _record_numerics(ctx.algo, verdict)
            _record_metrics(report)
            _publish(report)
            return report
        except Exception:
            return None  # telemetry must never break a serving batch


# -- the decorator ---------------------------------------------------------


def observed_transform(algo=None, *, check_numerics: bool = True):
    """Wrap a ``transform``/``predict``/``predict_proba`` method with the
    full serving instrumentation (see module doc).

    Usable with an explicit label (``@observed_transform("pca")``) or bare
    (``@observed_transform`` — the label derives from the class name at
    call time). ``check_numerics=False`` opts the entry point out of the
    NaN/Inf/all-zero sentinel — for models whose CONTRACT emits NaN (ALS
    scores NaN for unseen ids); counting those would page on healthy
    traffic. ``scripts/check_instrumentation.py`` statically enforces
    presence on every serving entry point in ``models/`` and ``spark/``.
    """
    if callable(algo):  # bare @observed_transform
        return _instrument(algo, None, check_numerics)

    def decorator(method):
        return _instrument(method, algo, check_numerics)

    return decorator


def _instrument(method, algo: Optional[str], check_numerics: bool = True):
    @functools.wraps(method)
    def wrapper(self, *args, **kwargs):
        parent = _current_ctx.get()
        if parent is not None and parent.owner_id == id(self):
            # Delegation shim (transform → _transform on the same object):
            # one call, one report. A decorated inner method may refine an
            # auto-derived label with its explicit one.
            if algo and not parent.explicit:
                parent.algo = algo
                parent.explicit = True
            return method(self, *args, **kwargs)
        name = algo or _derive_algo(self)
        ctx = TransformContext(
            name,
            trace_id=spans.current_trace_id(),
            owner_id=id(self),
            explicit=bool(algo),
            nested_in=parent.algo if parent is not None else None,
        )
        token = _current_ctx.set(ctx)
        started = _utcnow()
        t0 = time.perf_counter()
        try:
            with _flight_deadline(name, ctx.trace_id), spans.span(
                f"transform:{name}", TraceColor.PURPLE,
                trace_id=ctx.trace_id
            ), ctx.timer.phase("total"):
                ctx.span_id = spans.current_span_id()
                result = method(self, *args, **kwargs)
        except Exception as exc:
            # Failing serving traffic must be visible on the dashboard:
            # flat transforms_total with a healthy p99 reads as "no
            # traffic", not "outage". Errors count separately; failed
            # calls never feed the success-latency sketch.
            try:
                get_registry().counter(
                    "sparkml_transform_errors_total",
                    "transform/predict calls that raised",
                    ("algo", "error"),
                ).inc(algo=name, error=type(exc).__name__)
            except Exception:
                pass
            raise
        finally:
            _current_ctx.reset(token)
        wall = time.perf_counter() - t0
        try:
            dataset = args[0] if args else next(iter(kwargs.values()), None)
            if ctx.rows is None and dataset is not None:
                stats = _dataset_stats(dataset)
                ctx.set_data(rows=stats["rows"], features=stats["features"],
                             nbytes=stats["nbytes"])
            if ctx.bytes_out is None and result is not None:
                ctx.bytes_out = _dataset_stats(result)["nbytes"]
            report = _build_report(ctx, started, wall)
            rate = numerics_sample_rate() if check_numerics else 0.0
            if rate > 0 and (rate >= 1.0 or random.random() < rate):
                input_columns = getattr(dataset, "columns", None)
                if input_columns is None or callable(input_columns):
                    input_columns = ()
                input_columns = list(input_columns) + \
                    _model_input_columns(self)
                verdict = check_output_numerics(result, input_columns)
                if verdict is not None:
                    report.numerics = verdict
                    _record_numerics(ctx.algo, verdict)
            _record_metrics(report)
            _publish(report)
            try:
                setattr(self, TRANSFORM_REPORT_ATTR, report)
            except (AttributeError, TypeError):
                pass
            try:
                from spark_rapids_ml_tpu.obs.report import attach_report

                result = attach_report(result, report,
                                       attr=TRANSFORM_REPORT_ATTR)
            except Exception:
                pass
        except Exception:
            pass  # telemetry must never break a transform
        return result

    wrapper.__obs_instrumented__ = algo or True
    return wrapper
