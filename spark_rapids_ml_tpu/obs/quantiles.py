"""Streaming quantile sketch: mergeable, bounded-memory, relative-error.

The fixed-bucket histograms in ``obs.metrics`` answer "how many calls took
under 100ms" but cannot report a true p99 — the answer is quantized to
whatever bucket boundary the latency lands in, and serving-path latencies
span six orders of magnitude (a cached 50µs scaler transform vs a 30s
first-compile PCA projection). This module is the DDSketch-style fix
(Masson et al., VLDB 2019 — the same family Flare-style query-path
attribution leans on): logarithmic buckets sized so every quantile estimate
is within a *relative* error ``alpha`` of a true sample value, regardless
of the distribution's scale or shape.

Guarantee (documented bound, tested in ``tests/test_obs_quantiles.py``):
for any quantile ``q`` whose true sample value is ``x`` (positive or
negative, within the un-collapsed index range), the estimate ``x̂``
satisfies ``|x̂ - x| <= alpha * |x|``. Zero is represented exactly.

Properties the serving tier needs:

* **streaming** — ``observe`` is O(1) dict updates under one lock;
* **mergeable** — ``merge``/``merged`` add bucket counts pointwise, so
  per-thread / per-process / per-host sketches combine losslessly
  (merge is associative and commutative — tested);
* **bounded memory** — at most ``max_bins`` buckets per sign; overflowing
  collapses the *smallest-magnitude* buckets together (the DDSketch
  "collapse lowest" policy), preserving the bound for the large-magnitude
  tail that p95/p99 live in;
* **serializable** — ``to_dict``/``from_dict`` round-trip for embedding in
  bench records and merging offline.

Thread safety: all public methods take the instance lock; concurrent
``observe`` from Spark-style worker threads is safe and lossless.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, Optional, Tuple

DEFAULT_ALPHA = 0.01
DEFAULT_MAX_BINS = 4096

# Smallest magnitude the log-index can represent without float underflow;
# observations below it (in magnitude) count into the zero bucket — for
# latency/throughput/output values this is far below measurement noise.
_MIN_INDEXABLE = 1e-300


class QuantileSketch:
    """DDSketch-style log-bucket quantile sketch (see module doc).

    ``alpha`` is the guaranteed relative accuracy; ``max_bins`` bounds
    memory per sign (4096 bins at alpha=0.01 covers ~36 decades — nothing
    collapses in practice, the cap is a safety rail).
    """

    def __init__(self, alpha: float = DEFAULT_ALPHA,
                 max_bins: int = DEFAULT_MAX_BINS):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        if max_bins < 2:
            raise ValueError("max_bins must be >= 2")
        self.alpha = float(alpha)
        self.max_bins = int(max_bins)
        self._gamma = (1.0 + alpha) / (1.0 - alpha)
        self._log_gamma = math.log(self._gamma)
        self._pos: Dict[int, int] = {}
        self._neg: Dict[int, int] = {}
        self._zero = 0
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        self._collapsed = False
        self._lock = threading.Lock()

    # -- indexing ----------------------------------------------------------

    def _index(self, magnitude: float) -> int:
        return int(math.ceil(math.log(magnitude) / self._log_gamma))

    def _value(self, index: int) -> float:
        # bucket i covers (gamma^(i-1), gamma^i]; its midpoint estimate
        # 2*gamma^i/(gamma+1) is within alpha of every value in the range
        try:
            return 2.0 * math.exp(index * self._log_gamma) / (self._gamma + 1.0)
        except OverflowError:
            return math.inf

    def _collapse_locked(self, store: Dict[int, int]) -> None:
        """Merge smallest-magnitude buckets until under the cap — the
        large-magnitude tail (upper quantiles of latency) keeps its bound."""
        while len(store) > self.max_bins:
            lowest = min(store)
            second = min(k for k in store if k != lowest)
            store[second] += store.pop(lowest)
            self._collapsed = True

    # -- ingestion ---------------------------------------------------------

    def observe(self, value: float) -> None:
        """Add one observation. NaN is ignored (a sketch of latencies or
        outputs must never be poisoned by one bad sample); infinities are
        clamped into the largest representable bucket."""
        value = float(value)
        if math.isnan(value):
            return
        with self._lock:
            self._count += 1
            self._sum += value if math.isfinite(value) else 0.0
            if self._min is None or value < self._min:
                self._min = value
            if self._max is None or value > self._max:
                self._max = value
            magnitude = abs(value)
            if magnitude < _MIN_INDEXABLE:
                self._zero += 1
                return
            store = self._pos if value > 0 else self._neg
            if math.isinf(magnitude):
                index = self._index(1e308)
            else:
                index = self._index(magnitude)
            store[index] = store.get(index, 0) + 1
            self._collapse_locked(store)

    def add(self, values: Iterable[float]) -> None:
        for v in values:
            self.observe(v)

    # -- merging -----------------------------------------------------------

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """In-place pointwise merge; sketches must share ``alpha``."""
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with alpha {self.alpha} and "
                f"{other.alpha}"
            )
        # Snapshot other under its lock, then fold under ours (consistent
        # ordering is irrelevant: merge never takes both locks at once).
        with other._lock:
            pos = dict(other._pos)
            neg = dict(other._neg)
            zero, count, total = other._zero, other._count, other._sum
            omin, omax = other._min, other._max
            collapsed = other._collapsed
        with self._lock:
            for idx, c in pos.items():
                self._pos[idx] = self._pos.get(idx, 0) + c
            for idx, c in neg.items():
                self._neg[idx] = self._neg.get(idx, 0) + c
            self._zero += zero
            self._count += count
            self._sum += total
            if omin is not None and (self._min is None or omin < self._min):
                self._min = omin
            if omax is not None and (self._max is None or omax > self._max):
                self._max = omax
            self._collapsed = self._collapsed or collapsed
            self._collapse_locked(self._pos)
            self._collapse_locked(self._neg)
        return self

    def merged(self, other: "QuantileSketch") -> "QuantileSketch":
        """Non-destructive merge returning a fresh sketch."""
        out = QuantileSketch(alpha=self.alpha, max_bins=self.max_bins)
        out.merge(self)
        out.merge(other)
        return out

    # -- queries -----------------------------------------------------------

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def min(self) -> Optional[float]:
        with self._lock:
            return self._min

    @property
    def max(self) -> Optional[float]:
        with self._lock:
            return self._max

    @property
    def collapsed(self) -> bool:
        with self._lock:
            return self._collapsed

    def bin_count(self) -> int:
        with self._lock:
            return len(self._pos) + len(self._neg) + (1 if self._zero else 0)

    def quantile(self, q: float) -> Optional[float]:
        """The value at quantile ``q`` in [0, 1], or None when empty.

        q=0 and q=1 return the exact tracked min/max; interior quantiles
        return the bucket estimate (within ``alpha`` relative error of a
        true sample value at that rank).
        """
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            if self._count == 0:
                return None
            if q == 0.0:
                return self._min
            if q == 1.0:
                return self._max
            rank = q * (self._count - 1)
            # ascending value order: negatives (large magnitude first),
            # zero, positives (small magnitude first)
            seen = 0
            for idx in sorted(self._neg, reverse=True):
                seen += self._neg[idx]
                if seen > rank:
                    estimate = -self._value(idx)
                    if self._min is not None:
                        estimate = max(estimate, self._min)
                    if self._max is not None:
                        estimate = min(estimate, self._max)
                    return estimate
            seen += self._zero
            if self._zero and seen > rank:
                return 0.0
            for idx in sorted(self._pos):
                seen += self._pos[idx]
                if seen > rank:
                    estimate = self._value(idx)
                    if self._max is not None:
                        estimate = min(estimate, self._max)
                    if self._min is not None:
                        estimate = max(estimate, self._min)
                    return estimate
            return self._max

    def quantiles(self, qs: Iterable[float]) -> Dict[str, Optional[float]]:
        """``{"p50": v, "p99": v, ...}`` for fractional ``qs`` — the shape
        bench records and ``TransformReport`` embed."""
        out: Dict[str, Optional[float]] = {}
        for q in qs:
            label = f"p{q * 100:g}".replace(".", "_")
            out[label] = self.quantile(q)
        return out

    # -- serialization -----------------------------------------------------

    def to_dict(self) -> Dict[str, object]:
        with self._lock:
            return {
                "alpha": self.alpha,
                "max_bins": self.max_bins,
                "pos": {str(k): v for k, v in self._pos.items()},
                "neg": {str(k): v for k, v in self._neg.items()},
                "zero": self._zero,
                "count": self._count,
                "sum": self._sum,
                "min": self._min,
                "max": self._max,
                "collapsed": self._collapsed,
            }

    @classmethod
    def from_dict(cls, doc: Dict[str, object]) -> "QuantileSketch":
        sketch = cls(alpha=float(doc.get("alpha", DEFAULT_ALPHA)),
                     max_bins=int(doc.get("max_bins", DEFAULT_MAX_BINS)))
        sketch._pos = {int(k): int(v)
                       for k, v in dict(doc.get("pos", {})).items()}
        sketch._neg = {int(k): int(v)
                       for k, v in dict(doc.get("neg", {})).items()}
        sketch._zero = int(doc.get("zero", 0))
        sketch._count = int(doc.get("count", 0))
        sketch._sum = float(doc.get("sum", 0.0))
        sketch._min = None if doc.get("min") is None else float(doc["min"])
        sketch._max = None if doc.get("max") is None else float(doc["max"])
        sketch._collapsed = bool(doc.get("collapsed", False))
        return sketch

    def __repr__(self) -> str:
        return (f"QuantileSketch(alpha={self.alpha}, count={self._count}, "
                f"bins={len(self._pos) + len(self._neg)})")


def merge_all(sketches: Iterable[QuantileSketch]) -> Optional[QuantileSketch]:
    """Fold any number of sketches into one (None for an empty iterable)."""
    out: Optional[QuantileSketch] = None
    for sketch in sketches:
        if out is None:
            out = QuantileSketch(alpha=sketch.alpha,
                                 max_bins=sketch.max_bins)
        out.merge(sketch)
    return out
