"""Fleet telemetry federation: per-process export snapshots and the
aggregator that merges N serving processes into one host-labeled view.

Every TSDB, incident engine, and ``/debug/*`` surface in this repo is
process-local; a fleet of serving processes is only operable with one
merged view. This module is that plane:

* ``fleet_export(cursor)`` — the ``GET /debug/fleet/export`` document:
  TSDB ring deltas since the caller's cursor (points strictly newer
  than ``cursor``; the reply's ``cursor`` field is what to send next —
  re-polling with an old cursor is harmless because the merge is
  last-in-bucket idempotent), mergeable quantile-sketch states
  (``obs.quantiles`` sketches serialize losslessly and merge
  associatively — NEVER averaged percentiles), compact incident
  digests, the engine's replica/tiering/autoscale state, host identity
  and backend provenance. Aggregator-derived series
  (``sparkml_fleet_*``, ``sparkml_forecast_*``, anything already
  host-labeled) are excluded so federation stays one level deep.
* ``FleetAggregator`` — polls peer export URLs at a bounded
  injectable-clock cadence (``poll_once(now)`` is fully test-drivable;
  ``fetch_fn`` is injectable so tests use fake peers, zero sockets) and
  merges each peer's series into the local store under a ``host=``
  label. It publishes ``sparkml_fleet_host_up{host}`` /
  ``sparkml_fleet_host_staleness_seconds{host}`` gauges into the
  process registry, so an unreachable/stale peer flows through the
  EXISTING sampler → ``fleet_host_down`` ThresholdDetector →
  IncidentEngine pipeline and raises exactly one auto-resolving
  incident per host — no parallel alerting path. Open incidents that
  share (detector, labels) across hosts dedup into ONE fleet incident
  carrying per-host evidence.
* ``rollup()`` — the ``GET /debug/fleet`` document: per-host table
  (up/staleness/cursor/replica state), fleet-wide SLO burn from the
  merged host-labeled burn series, merged-sketch latency quantiles,
  and the forecast panel when a ``Forecaster`` is attached.

Host identity is ``SPARK_RAPIDS_ML_TPU_FLEET_HOST`` when set (the load
harness pins it per child so a respawned peer keeps its label and its
``fleet_host_down`` incident can resolve), else ``hostname:pid``.

Every peer-poll outcome (ok / unreachable / stale), merged point, and
incident-dedup decision increments a counter in the same function that
took it (``check_instrumentation`` rule 18), and this module never
reads the wall clock directly (rule 8) — time flows from the injected
``clock`` or the caller's ``now``.

Knobs (env): SPARK_RAPIDS_ML_TPU_FLEET_HOST (identity override),
SPARK_RAPIDS_ML_TPU_FLEET_POLL_S (2.0 — aggregator cadence),
SPARK_RAPIDS_ML_TPU_FLEET_STALE_S (10.0 — grace before a silent peer
counts as down), SPARK_RAPIDS_ML_TPU_FLEET_TIMEOUT_S (1.0 — per-fetch
HTTP timeout), SPARK_RAPIDS_ML_TPU_FLEET_PEERS (comma-separated peer
base URLs, optionally ``host=url``; consumed by ``peers_from_env``).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

from spark_rapids_ml_tpu.obs import metrics as metrics_mod
from spark_rapids_ml_tpu.obs import quantiles as quantiles_mod
from spark_rapids_ml_tpu.obs import spans as spans_mod
from spark_rapids_ml_tpu.obs import tsdb as tsdb_mod
from spark_rapids_ml_tpu.obs.logging import get_logger

HOST_ENV = "SPARK_RAPIDS_ML_TPU_FLEET_HOST"
POLL_ENV = "SPARK_RAPIDS_ML_TPU_FLEET_POLL_S"
STALE_ENV = "SPARK_RAPIDS_ML_TPU_FLEET_STALE_S"
TIMEOUT_ENV = "SPARK_RAPIDS_ML_TPU_FLEET_TIMEOUT_S"
PEERS_ENV = "SPARK_RAPIDS_ML_TPU_FLEET_PEERS"

EXPORT_VERSION = 1
HOST_UP_METRIC = "sparkml_fleet_host_up"
INCIDENT_NAME = "fleet_host_down"

_DEFAULT_POLL_S = 2.0
_DEFAULT_STALE_S = 10.0
_DEFAULT_TIMEOUT_S = 1.0
# export size guards: a snapshot is a poll payload, not an archive
_MAX_EXPORT_SERIES = 512
_MAX_EXPORT_SKETCHES = 128
_MAX_ROLLUP_SKETCHES = 32
_DIGEST_RECENT = 8
# series the export refuses: aggregator-local families would otherwise
# echo back and forth between two aggregating processes
_EXPORT_EXCLUDE_PREFIXES = ("sparkml_fleet_", "sparkml_forecast_")

_log = get_logger("obs.federation")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def host_identity() -> str:
    """This process's stable fleet label: the env override when set
    (respawned peers keep their label, so their ``fleet_host_down``
    incident can auto-resolve), else ``hostname:pid``."""
    override = os.environ.get(HOST_ENV, "").strip()
    if override:
        return override
    return f"{socket.gethostname()}:{os.getpid()}"


def backend_provenance() -> Dict[str, Any]:
    """Which accelerator stack this process actually resolved — guarded
    (an export must work on a process that never imported jax)."""
    doc: Dict[str, Any] = {"pid": os.getpid()}
    try:
        import jax

        doc["jax_platform"] = jax.default_backend()
        doc["device_count"] = jax.device_count()
    except Exception as exc:  # noqa: BLE001 - provenance is best-effort
        doc["jax_error"] = f"{type(exc).__name__}: {exc}"
    return doc


def peers_from_env() -> List[Tuple[Optional[str], str]]:
    """Parse ``SPARK_RAPIDS_ML_TPU_FLEET_PEERS``: comma-separated base
    URLs, each optionally prefixed ``host=`` to pin the label before
    the first successful poll."""
    out: List[Tuple[Optional[str], str]] = []
    for part in os.environ.get(PEERS_ENV, "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part and not part.split("=", 1)[0].startswith("http"):
            host, url = part.split("=", 1)
            out.append((host.strip() or None, url.strip()))
        else:
            out.append((None, part))
    return out


# -- the export side (every serving process) ----------------------------------


def _sketch_states(registry: metrics_mod.MetricsRegistry,
                   limit: int = _MAX_EXPORT_SKETCHES
                   ) -> List[Dict[str, Any]]:
    """Every Summary family's per-child sketch state — the mergeable
    transport (states merge losslessly; percentiles would not)."""
    out: List[Dict[str, Any]] = []
    for family in registry.families():
        if not isinstance(family, metrics_mod.Summary):
            continue
        for labels, state in family.sketch_states():
            out.append({"name": family.name, "labels": labels,
                        "state": state})
            if len(out) >= limit:
                return out
    return out


def _incident_digest(engine=None) -> Dict[str, Any]:
    """Compact open/recent incident digests (no evidence bundles — an
    export is a poll payload)."""
    if engine is None:
        try:
            from spark_rapids_ml_tpu.obs import incidents as incidents_mod

            if not incidents_mod.enabled():
                return {"open": [], "recent": []}
            engine = incidents_mod.get_incident_engine()
        except Exception:  # noqa: BLE001 - digest is best-effort
            return {"open": [], "recent": []}
    try:
        return engine.digest()
    except Exception:  # noqa: BLE001
        return {"open": [], "recent": []}


def fleet_export(cursor: float = 0.0, *,
                 store: Optional[tsdb_mod.TimeSeriesStore] = None,
                 registry: Optional[metrics_mod.MetricsRegistry] = None,
                 engine=None, incident_engine=None,
                 now: Optional[float] = None) -> Dict[str, Any]:
    """Assemble one ``GET /debug/fleet/export`` snapshot.

    ``cursor`` is the ``cursor`` field of the previous reply (0 for the
    first poll → the full retained window). Points returned are
    STRICTLY newer than ``cursor``; because the aggregator's merge is
    last-in-bucket idempotent, overlap from a stale cursor never
    double-counts.
    """
    store = store if store is not None else tsdb_mod.get_tsdb()
    registry = (registry if registry is not None
                else metrics_mod.get_registry())
    ts = store.clock() if now is None else float(now)
    max_window = max(span for _, span in store.tiers)
    window = max_window if cursor <= 0 else min(
        max(ts - cursor, 0.0) + 1.0, max_window)
    m_export = registry.counter(
        "sparkml_fleet_export_total",
        "fleet export snapshots served, by outcome", ("outcome",))
    series_out: List[Dict[str, Any]] = []
    truncated = 0
    for name in store.series_names():
        if name.startswith(_EXPORT_EXCLUDE_PREFIXES):
            continue
        for child in store.range_query(name, None, window, now=ts):
            if "host" in child["labels"]:
                continue  # already federated once — stay one level deep
            points = [[p_ts, p_v] for p_ts, p_v in child["points"]
                      if p_ts > cursor]
            if not points:
                continue
            if len(series_out) >= _MAX_EXPORT_SERIES:
                truncated += 1
                continue
            series_out.append({
                "name": name,
                "labels": child["labels"],
                "kind": child["kind"],
                "points": points,
            })
    state: Dict[str, Any] = {}
    if engine is not None:
        try:
            state = engine.fleet_state()
        except Exception:  # noqa: BLE001 - state is best-effort
            state = {}
    doc = {
        "version": EXPORT_VERSION,
        "host": host_identity(),
        "now": ts,
        "cursor": ts,
        "backend": backend_provenance(),
        "series": series_out,
        "series_truncated": truncated,
        "sketches": _sketch_states(registry),
        "incidents": _incident_digest(incident_engine),
        "state": state,
    }
    m_export.inc(outcome="truncated" if truncated else "ok")
    return doc


def _http_fetch(url: str, timeout: float) -> Dict[str, Any]:
    """Default ``fetch_fn``: one bounded HTTP GET returning the parsed
    export document."""
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


# -- the aggregator side ------------------------------------------------------


class _PeerState:
    __slots__ = ("url", "host", "cursor", "last_ok_ts", "polls",
                 "failures", "consecutive_failures", "sketches",
                 "incidents", "state", "backend", "last_error",
                 "merged_points")

    def __init__(self, url: str, host: Optional[str]):
        self.url = url
        self.host = host  # learned from the first export when None
        self.cursor = 0.0
        self.last_ok_ts: Optional[float] = None
        self.polls = 0
        self.failures = 0
        self.consecutive_failures = 0
        self.sketches: List[Dict[str, Any]] = []
        self.incidents: Dict[str, Any] = {"open": [], "recent": []}
        self.state: Dict[str, Any] = {}
        self.backend: Dict[str, Any] = {}
        self.last_error: Optional[str] = None
        self.merged_points = 0

    def label(self) -> str:
        if self.host:
            return self.host
        # never-seen peer: a url-derived label keeps its down incident
        # addressable before the first successful poll
        return "".join(c if (c.isalnum() or c in ".-_") else "_"
                       for c in self.url.split("://")[-1])[:60]


class FleetAggregator:
    """Polls peer export endpoints and maintains the merged fleet view.

    Runnable inside any serving process or standalone: the merge target
    defaults to the process TSDB/registry, so ``/debug/history?host=``
    and the incident pipeline see federated series with zero extra
    plumbing. ``poll_once(now)`` is the whole cadence unit — the
    background thread just calls it on an interval; tests call it
    directly with injected clocks and fake ``fetch_fn`` peers.
    """

    def __init__(
        self,
        peers,
        *,
        store: Optional[tsdb_mod.TimeSeriesStore] = None,
        registry: Optional[metrics_mod.MetricsRegistry] = None,
        poll_interval_s: Optional[float] = None,
        stale_after_s: Optional[float] = None,
        fetch_timeout_s: Optional[float] = None,
        fetch_fn: Optional[Callable[[str, float], Dict[str, Any]]] = None,
        forecaster=None,
        clock: Callable[[], float] = time.time,
    ):
        self._store = store
        self._registry = registry
        self.poll_interval_s = float(
            poll_interval_s if poll_interval_s is not None
            else _env_float(POLL_ENV, _DEFAULT_POLL_S))
        self.stale_after_s = float(
            stale_after_s if stale_after_s is not None
            else _env_float(STALE_ENV, _DEFAULT_STALE_S))
        self.fetch_timeout_s = float(
            fetch_timeout_s if fetch_timeout_s is not None
            else _env_float(TIMEOUT_ENV, _DEFAULT_TIMEOUT_S))
        self.fetch_fn = fetch_fn if fetch_fn is not None else _http_fetch
        self.forecaster = forecaster
        self.clock = clock
        self._lock = threading.Lock()
        self._peers: List[_PeerState] = []
        for entry in peers:
            if isinstance(entry, str):
                self._peers.append(_PeerState(entry, None))
            else:
                host, url = entry
                self._peers.append(_PeerState(url, host))
        self._polls = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = self._reg()
        self._m_polls = reg.counter(
            "sparkml_fleet_polls_total",
            "peer poll outcomes (ok / unreachable within grace / stale "
            "beyond grace)", ("outcome",))
        for outcome in ("ok", "unreachable", "stale"):
            self._m_polls.inc(0, outcome=outcome)
        self._m_merged = reg.counter(
            "sparkml_fleet_merged_points_total",
            "series points merged into the fleet store, by host",
            ("host",))
        self._m_dedup = reg.counter(
            "sparkml_fleet_incident_dedup_total",
            "fleet incident grouping decisions (grouped = the same "
            "(detector, labels) was open on 2+ hosts)", ("outcome",))
        self._g_up = reg.gauge(
            HOST_UP_METRIC,
            "1 while the peer's export endpoint answers within the "
            "staleness grace; the fleet_host_down detector pages on 0",
            ("host",))
        self._g_staleness = reg.gauge(
            "sparkml_fleet_host_staleness_seconds",
            "seconds since the peer's last successful export poll",
            ("host",))

    def _reg(self) -> metrics_mod.MetricsRegistry:
        return (self._registry if self._registry is not None
                else metrics_mod.get_registry())

    def store(self) -> tsdb_mod.TimeSeriesStore:
        return (self._store if self._store is not None
                else tsdb_mod.get_tsdb())

    @property
    def total_polls(self) -> int:
        return self._polls

    def peer_hosts(self) -> List[str]:
        with self._lock:
            return [p.label() for p in self._peers]

    # -- the cadence unit ---------------------------------------------------

    def poll_once(self, now: Optional[float] = None) -> Dict[str, str]:
        """Poll every peer once; returns {host_label: outcome} with
        outcome ∈ ok | unreachable (failed, within grace) | stale
        (failed, beyond grace → host_up drops to 0). Each outcome is
        counted here, in the function that decided it (rule 18)."""
        ts = self.clock() if now is None else float(now)
        outcomes: Dict[str, str] = {}
        with self._lock:
            self._polls += 1
            peers = list(self._peers)
        for peer in peers:
            url = (peer.url.rstrip("/")
                   + f"/debug/fleet/export?cursor={peer.cursor!r}")
            try:
                doc = self.fetch_fn(url, self.fetch_timeout_s)
                self._merge_export(peer, doc, ts)
                outcome = "ok"
                self._m_polls.inc(outcome="ok")
            except Exception as exc:  # noqa: BLE001 - a dead peer is data
                with self._lock:
                    peer.polls += 1
                    peer.failures += 1
                    peer.consecutive_failures += 1
                    peer.last_error = f"{type(exc).__name__}: {exc}"
                    last_ok = peer.last_ok_ts
                beyond_grace = (last_ok is None
                                or ts - last_ok > self.stale_after_s)
                outcome = "stale" if beyond_grace else "unreachable"
                self._m_polls.inc(outcome=outcome)
            self._publish_host_health(peer, ts)
            outcomes[peer.label()] = outcome
        return outcomes

    def _merge_export(self, peer: _PeerState, doc: Dict[str, Any],
                      ts: float) -> int:
        """Fold one export document into the fleet view; returns and
        counts the number of points merged."""
        host = str(doc.get("host") or peer.label())
        store = self.store()
        merged = 0
        for series in doc.get("series", ()):
            labels = dict(series.get("labels") or {})
            labels["host"] = host
            name = str(series.get("name"))
            kind = str(series.get("kind") or "gauge")
            for p_ts, p_v in series.get("points", ()):
                # record at the PEER's timestamp: last-in-bucket makes
                # re-merging an overlapping delta idempotent
                store.record(name, labels, float(p_v), kind=kind,
                             now=float(p_ts))
                merged += 1
        with self._lock:
            peer.host = host
            peer.cursor = float(doc.get("cursor") or ts)
            peer.last_ok_ts = ts
            peer.polls += 1
            peer.consecutive_failures = 0
            peer.last_error = None
            peer.merged_points += merged
            peer.sketches = list(doc.get("sketches") or ())
            peer.incidents = dict(
                doc.get("incidents") or {"open": [], "recent": []})
            peer.state = dict(doc.get("state") or {})
            peer.backend = dict(doc.get("backend") or {})
        if merged:
            self._m_merged.inc(merged, host=host)
        return merged

    def _publish_host_health(self, peer: _PeerState, ts: float) -> None:
        host = peer.label()
        last_ok = peer.last_ok_ts
        staleness = (ts - last_ok) if last_ok is not None else float(
            "inf")
        up = 1.0 if staleness <= self.stale_after_s else 0.0
        self._g_up.set(up, host=host)
        self._g_staleness.set(
            staleness if staleness != float("inf") else -1.0, host=host)

    # -- fleet incident dedup -----------------------------------------------

    def _dedup_fleet_incidents(self) -> List[Dict[str, Any]]:
        """Group peers' open incidents by (detector, labels): the same
        anomaly on N hosts is ONE fleet incident with per-host
        evidence, not N pages. Counts every grouping decision."""
        grouped: Dict[Tuple, Dict[str, Any]] = {}
        with self._lock:
            peers = [(p.label(), dict(p.incidents)) for p in self._peers]
        for host, digest in peers:
            for inc in digest.get("open", ()):
                labels = dict(inc.get("labels") or {})
                key = (inc.get("detector"),
                       tuple(sorted(labels.items())))
                entry = grouped.get(key)
                if entry is None:
                    grouped[key] = {
                        "detector": inc.get("detector"),
                        "kind": inc.get("kind"),
                        "severity": inc.get("severity"),
                        "metric": inc.get("metric"),
                        "labels": labels,
                        "hosts": {},
                    }
                    entry = grouped[key]
                entry["hosts"][host] = {
                    "id": inc.get("id"),
                    "opened_ts": inc.get("opened_ts"),
                    "value": inc.get("value"),
                    "reason": inc.get("reason"),
                }
        out: List[Dict[str, Any]] = []
        for entry in grouped.values():
            entry["host_count"] = len(entry["hosts"])
            self._m_dedup.inc(outcome=(
                "grouped" if entry["host_count"] > 1 else "single"))
            out.append(entry)
        out.sort(key=lambda e: (-e["host_count"],
                                str(e["detector"])))
        return out

    # -- merged sketch view -------------------------------------------------

    def _sketch_rollup(self) -> List[Dict[str, Any]]:
        """Merge identical (name, labels) sketch states across hosts —
        pooled-observation quantiles, never averaged percentiles."""
        with self._lock:
            states: List[Dict[str, Any]] = []
            for peer in self._peers:
                states.extend(peer.sketches)
        merged: Dict[Tuple, quantiles_mod.QuantileSketch] = {}
        meta: Dict[Tuple, Tuple[str, Dict[str, str]]] = {}
        for doc in states:
            try:
                sketch = quantiles_mod.QuantileSketch.from_dict(
                    doc["state"])
            except Exception:  # noqa: BLE001 - a bad state is skipped
                continue
            labels = dict(doc.get("labels") or {})
            key = (doc.get("name"), tuple(sorted(labels.items())))
            if key in merged:
                try:
                    merged[key].merge(sketch)
                except ValueError:
                    continue  # alpha mismatch across versions: skip
            else:
                merged[key] = sketch
                meta[key] = (str(doc.get("name")), labels)
        out: List[Dict[str, Any]] = []
        for key, sketch in merged.items():
            name, labels = meta[key]
            out.append({
                "name": name,
                "labels": labels,
                "count": sketch.count,
                "sum": sketch.sum,
                "quantiles": {
                    "p50": sketch.quantile(0.5),
                    "p95": sketch.quantile(0.95),
                    "p99": sketch.quantile(0.99),
                },
            })
        out.sort(key=lambda e: (-e["count"], e["name"]))
        return out[:_MAX_ROLLUP_SKETCHES]

    # -- the /debug/fleet document ------------------------------------------

    def rollup(self, now: Optional[float] = None) -> Dict[str, Any]:
        ts = self.clock() if now is None else float(now)
        store = self.store()
        hosts: List[Dict[str, Any]] = []
        up_count = 0
        with self._lock:
            peers = list(self._peers)
        for peer in peers:
            last_ok = peer.last_ok_ts
            staleness = (ts - last_ok) if last_ok is not None else None
            up = (staleness is not None
                  and staleness <= self.stale_after_s)
            up_count += 1 if up else 0
            state = dict(peer.state)
            hosts.append({
                "host": peer.label(),
                "url": peer.url,
                "up": up,
                "staleness_seconds": staleness,
                "cursor": peer.cursor,
                "polls": peer.polls,
                "failures": peer.failures,
                "consecutive_failures": peer.consecutive_failures,
                "last_error": peer.last_error,
                "merged_points": peer.merged_points,
                "open_incidents": len(peer.incidents.get("open", ())),
                "replicas": state.get("replicas"),
                "backend": dict(peer.backend),
            })
        burn_by_host: Dict[str, float] = {}
        for series in store.range_query(
                "sparkml_slo_burn_rate", None, 120.0, now=ts):
            labels = series["labels"]
            if labels.get("window") != "5m" or "host" not in labels:
                continue
            if series["points"]:
                host = labels["host"]
                burn_by_host[host] = max(
                    burn_by_host.get(host, 0.0),
                    series["points"][-1][1])
        doc = {
            "now": ts,
            "aggregator_host": host_identity(),
            "poll_interval_s": self.poll_interval_s,
            "stale_after_s": self.stale_after_s,
            "polls": self._polls,
            "hosts_total": len(hosts),
            "hosts_up": up_count,
            "hosts": hosts,
            "fleet_incidents": self._dedup_fleet_incidents(),
            "slo_burn": {
                "by_host": burn_by_host,
                "max": max(burn_by_host.values(), default=0.0),
            },
            "merged_sketches": self._sketch_rollup(),
        }
        if self.forecaster is not None:
            try:
                doc["forecast"] = self.forecaster.snapshot()
            except Exception:  # noqa: BLE001 - panel is best-effort
                doc["forecast"] = None
        return doc

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Start the polling thread (idempotent)."""
        from spark_rapids_ml_tpu.obs import tracectx

        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = tracectx.traced_thread(
                self._run, name="sparkml-fleet-aggregator",
                daemon=True, fresh=True)
            self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        with self._lock:
            self._stop.set()
            thread = self._thread
            self._thread = None
        if thread is not None:
            thread.join(timeout)

    @property
    def running(self) -> bool:
        thread = self._thread
        return thread is not None and thread.is_alive()

    def _run(self) -> None:
        while not self._stop.is_set():
            t0 = time.perf_counter()
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - the loop must survive
                _log.warning("fleet poll failed", exc_info=True)
            spans_mod.record_event(
                "fleet:poll", t0, time.perf_counter(),
                peers=len(self._peers))
            self._stop.wait(self.poll_interval_s)


# -- the process-wide aggregator ----------------------------------------------

_singleton_lock = threading.Lock()
_aggregator: Optional[FleetAggregator] = None


def get_aggregator() -> Optional[FleetAggregator]:
    """The aggregator serving ``/debug/fleet`` in this process (None
    when this process does not aggregate)."""
    with _singleton_lock:
        return _aggregator


def set_aggregator(aggregator: Optional[FleetAggregator]
                   ) -> Optional[FleetAggregator]:
    """Install (or clear, with None) the process-wide aggregator;
    returns the previous one so callers can stop it."""
    global _aggregator
    with _singleton_lock:
        previous = _aggregator
        _aggregator = aggregator
        return previous


__all__ = [
    "EXPORT_VERSION",
    "FleetAggregator",
    "HOST_ENV",
    "HOST_UP_METRIC",
    "INCIDENT_NAME",
    "PEERS_ENV",
    "POLL_ENV",
    "STALE_ENV",
    "TIMEOUT_ENV",
    "backend_provenance",
    "fleet_export",
    "get_aggregator",
    "host_identity",
    "peers_from_env",
    "set_aggregator",
]
