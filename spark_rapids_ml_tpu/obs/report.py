"""Uniform per-fit reports: the shared instrumentation entry point.

Every distributed driver is wrapped in ``@fit_instrumentation("<algo>")``
and every user-facing estimator ``fit`` in ``@observed_fit("<algo>")``; both
produce one ``FitReport`` surfaced as ``fit_report_`` on the fitted
model/result (replacing the ad-hoc ``fit_timings_`` dict, which is kept
populated for back-compat), increment the process metrics registry, and —
when ``SPARK_RAPIDS_ML_TPU_TRACE_DIR`` is set — export the fit's span
timeline as Chrome-trace JSON.

The report carries what the ROADMAP's perf work needs per fit: the phase
wall-clock split, rows/bytes processed, the mesh shape and device platform,
the cached ``DeviceHealth`` verdict, and host-side accounting of every
collective the compiled program runs (kind → invocation count + payload
bytes). Collective counts are *program-level* accounting declared by the
drivers (exact for host-looped collectives, schedule×payload for
collectives inside compiled loops) — the XLA-visible truth, not hardware
counters.

Telemetry is never allowed to break a fit: everything outside the wrapped
call itself is exception-guarded.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

from spark_rapids_ml_tpu.obs import spans
from spark_rapids_ml_tpu.obs.metrics import get_registry
from spark_rapids_ml_tpu.utils.timing import PhaseTimer
from spark_rapids_ml_tpu.utils.tracing import TraceColor

REPORT_ATTR = "fit_report_"


@dataclass
class FitReport:
    """The uniform per-fit observability artifact."""

    algo: str
    trace_id: str
    started_utc: str
    wall_seconds: float
    phases: Dict[str, float] = field(default_factory=dict)
    rows: Optional[int] = None
    features: Optional[int] = None
    bytes_processed: Optional[int] = None
    mesh_shape: Optional[Tuple[int, ...]] = None
    mesh_axes: Optional[Tuple[str, ...]] = None
    device_platform: Optional[str] = None
    device_count: Optional[int] = None
    healthy: Optional[bool] = None
    health: Optional[Dict[str, Any]] = None
    collectives: Dict[str, Dict[str, int]] = field(default_factory=dict)
    n_iter: Optional[int] = None
    # XLA compile attribution (obs.xprof tracked_jit accounting)
    compiles: int = 0
    recompiles: int = 0
    compile_seconds: float = 0.0
    # HLO cost-analysis accounting over every tracked program this fit ran
    analytic_flops: Optional[float] = None
    analytic_bytes: Optional[float] = None
    flops_by_phase: Dict[str, float] = field(default_factory=dict)
    analytic_mfu: Optional[float] = None
    # Device-memory watermark (obs.memory; host RSS on statless backends)
    peak_device_bytes: Optional[int] = None
    memory: Optional[Dict[str, Any]] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    def as_dict(self) -> Dict[str, Any]:
        d = asdict(self)
        if self.mesh_shape is not None:
            d["mesh_shape"] = list(self.mesh_shape)
        if self.mesh_axes is not None:
            d["mesh_axes"] = list(self.mesh_axes)
        return d

    def total_collective_bytes(self) -> int:
        return sum(int(v.get("bytes", 0)) for v in self.collectives.values())

    def total_collective_calls(self) -> int:
        return sum(int(v.get("count", 0)) for v in self.collectives.values())

    def phase_mfu(self, peak_flops: Optional[float] = None
                  ) -> Dict[str, Optional[float]]:
        """Per-phase analytic MFU: cost-analysis FLOPs attributed to each
        phase over that phase's wall-clock over the chip peak (None entries
        when the peak or the phase time is unknown)."""
        if peak_flops is None:
            from spark_rapids_ml_tpu.obs.xprof import peak_flops_per_second

            peak_flops = peak_flops_per_second()
        out: Dict[str, Optional[float]] = {}
        for phase, flops in self.flops_by_phase.items():
            seconds = self.phases.get(phase)
            if peak_flops and seconds:
                out[phase] = flops / seconds / peak_flops
            else:
                out[phase] = None
        return out


class FitContext:
    """Mutable accounting for one in-flight fit.

    Obtained inside an instrumented driver via ``current_fit()``; drivers
    record phases (``with ctx.phase("placement"): ...``) and collectives
    (``ctx.record_collective("all_reduce", shape=(n, n), dtype=dt)``).
    """

    __slots__ = (
        "algo", "trace_id", "timer", "collectives", "extra",
        "rows", "features", "bytes_processed", "n_iter", "_lock",
        "compiles", "recompiles", "compile_seconds",
        "analytic_flops", "analytic_bytes", "flops_by_phase",
        "_phase_stack",
    )

    def __init__(self, algo: str, trace_id: Optional[str] = None):
        self.algo = algo
        self.trace_id = trace_id or spans.new_trace_id()
        self.timer = PhaseTimer()
        self.collectives: Dict[str, Dict[str, int]] = {}
        self.extra: Dict[str, Any] = {}
        self.rows: Optional[int] = None
        self.features: Optional[int] = None
        self.bytes_processed: Optional[int] = None
        self.n_iter: Optional[int] = None
        self.compiles = 0
        self.recompiles = 0
        self.compile_seconds = 0.0
        self.analytic_flops = 0.0
        self.analytic_bytes = 0.0
        self.flops_by_phase: Dict[str, float] = {}
        self._phase_stack: Tuple[str, ...] = ()
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def phase(self, name: str):
        """Time a named phase AND emit a nested span for the trace file."""
        with self.timer.phase(name), spans.span(
            f"{self.algo}:{name}", TraceColor.CYAN
        ):
            # NOTE: the phase stack attributes tracked-program FLOPs to the
            # innermost phase of whichever thread entered it last; drivers
            # run phases sequentially on one thread, which is the contract.
            prev = self._phase_stack
            self._phase_stack = prev + (name,)
            try:
                yield
            finally:
                self._phase_stack = prev

    def record_compile(self, label: str, seconds: float, *,
                       recompile: bool = False) -> None:
        """Called by ``obs.xprof`` when a tracked function compiles during
        this fit."""
        with self._lock:
            self.compiles += 1
            if recompile:
                self.recompiles += 1
            self.compile_seconds += float(seconds)

    def record_program(self, label: str, flops: Optional[float],
                       nbytes: Optional[float]) -> None:
        """Called by ``obs.xprof`` on every tracked-program execution:
        accumulates HLO cost-analysis FLOPs/bytes, attributed to the
        innermost active phase."""
        with self._lock:
            if flops:
                self.analytic_flops += float(flops)
                phase = self._phase_stack[-1] if self._phase_stack \
                    else "_unphased"
                self.flops_by_phase[phase] = (
                    self.flops_by_phase.get(phase, 0.0) + float(flops)
                )
            if nbytes:
                self.analytic_bytes += float(nbytes)

    def record_collective(
        self,
        kind: str,
        *,
        shape: Optional[Tuple[int, ...]] = None,
        dtype=None,
        nbytes: Optional[int] = None,
        count: int = 1,
    ) -> None:
        """Account ``count`` invocations of a collective, each moving the
        payload described by ``shape``+``dtype`` (or raw ``nbytes``)."""
        if nbytes is None:
            if shape is None:
                nbytes = 0
            else:
                itemsize = np.dtype(dtype).itemsize if dtype is not None else 4
                nbytes = int(np.prod([int(s) for s in shape])) * itemsize
        with self._lock:
            entry = self.collectives.setdefault(
                kind, {"count": 0, "bytes": 0}
            )
            entry["count"] += int(count)
            entry["bytes"] += int(nbytes) * int(count)
        try:
            # mirror into the live fit-path monitor so /debug/fit shows
            # comms accounting while the fit is still running
            from spark_rapids_ml_tpu.obs import fitmon

            fitmon.current_run().record_collective(
                kind, nbytes=int(nbytes), count=int(count)
            )
        except Exception:
            pass

    def set_data(
        self,
        rows: Optional[int] = None,
        features: Optional[int] = None,
        nbytes: Optional[int] = None,
    ) -> None:
        if rows is not None:
            self.rows = int(rows)
        if features is not None:
            self.features = int(features)
        if nbytes is not None:
            self.bytes_processed = int(nbytes)

    def set_iterations(self, n_iter) -> None:
        try:
            self.n_iter = int(n_iter)
        except (TypeError, ValueError):
            pass

    def note(self, **kwargs) -> None:
        self.extra.update(kwargs)


class _NullFitContext(FitContext):
    """No-op context: lets drivers call ``current_fit()`` unconditionally
    even when invoked outside an instrumented entry point."""

    def __init__(self):
        super().__init__("_unobserved")

    @contextlib.contextmanager
    def phase(self, name: str):
        yield

    def record_collective(self, *args, **kwargs) -> None:
        pass

    def record_compile(self, *args, **kwargs) -> None:
        pass

    def record_program(self, *args, **kwargs) -> None:
        pass

    def set_data(self, *args, **kwargs) -> None:
        pass

    def set_iterations(self, *args) -> None:
        pass

    def note(self, **kwargs) -> None:
        pass


_NULL_CONTEXT = _NullFitContext()
_current_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "sparkml_fit_ctx", default=None
)

_last_reports: Dict[Optional[str], FitReport] = {}
_last_lock = threading.Lock()


def current_fit() -> FitContext:
    """The active fit's context, or a no-op context outside any fit."""
    ctx = _current_ctx.get()
    return ctx if ctx is not None else _NULL_CONTEXT


def last_fit_report(algo: Optional[str] = None) -> Optional[FitReport]:
    """Most recent report (optionally for one algo) — the escape hatch for
    results the report cannot be attached to."""
    with _last_lock:
        return _last_reports.get(algo)


# -- health / device environment (probed once per process) -----------------

_health_cache: Optional[Dict[str, Any]] = None
_health_lock = threading.Lock()


def _health_once() -> Optional[Dict[str, Any]]:
    global _health_cache
    with _health_lock:
        if _health_cache is None:
            try:
                from spark_rapids_ml_tpu.utils.health import check_devices

                _health_cache = dict(check_devices().__dict__)
            except Exception:
                _health_cache = {}
        return _health_cache or None


# -- report assembly -------------------------------------------------------


_utcnow = spans.utcnow_iso


def _find_mesh(args, kwargs):
    try:
        from jax.sharding import Mesh
    except Exception:
        return None
    mesh = kwargs.get("mesh")
    if isinstance(mesh, Mesh):
        return mesh
    for a in args:
        if isinstance(a, Mesh):
            return a
    return None


def _array_stats(value):
    """(rows, features, nbytes) for an array-like, else None."""
    shape = getattr(value, "shape", None)
    if not shape or not isinstance(shape, tuple):
        return None
    try:
        rows = int(shape[0])
        features = int(shape[1]) if len(shape) > 1 else None
        nbytes = getattr(value, "nbytes", None)
        if nbytes is None:
            itemsize = getattr(
                getattr(value, "dtype", None), "itemsize", 8
            )
            nbytes = int(np.prod([int(s) for s in shape])) * itemsize
        return rows, features, int(nbytes)
    except (TypeError, ValueError):
        return None


def _infer_data_stats(ctx: FitContext, args, kwargs) -> None:
    """Fill rows/features/bytes from the call's array arguments unless the
    driver already set them explicitly."""
    if ctx.rows is not None and ctx.bytes_processed is not None:
        return
    total_bytes = 0
    first = None
    flat = []
    for a in list(args) + list(kwargs.values()):
        if isinstance(a, tuple):
            flat.extend(a)
        else:
            flat.append(a)
    for a in flat:
        stats = _array_stats(a)
        if stats is None:
            continue
        if first is None:
            first = stats
        total_bytes += stats[2]
    if first is not None:
        if ctx.rows is None:
            ctx.rows = first[0]
        if ctx.features is None:
            ctx.features = first[1]
    if ctx.bytes_processed is None and total_bytes:
        ctx.bytes_processed = total_bytes


def _mesh_fields(mesh) -> Dict[str, Any]:
    if mesh is None:
        return {}
    try:
        from spark_rapids_ml_tpu.parallel.mesh import mesh_shape

        summary = mesh_shape(mesh)
        return {
            "mesh_shape": summary["shape"],
            "mesh_axes": summary["axes"],
            "device_platform": summary["platform"],
            "device_count": summary["devices"],
        }
    except Exception:
        return {}


def _memory_fields() -> Dict[str, Any]:
    """End-of-fit device-memory watermark (PJRT peak, host RSS fallback)."""
    try:
        from spark_rapids_ml_tpu.obs.memory import (
            memory_watermarks,
            record_memory_metrics,
        )

        wm = memory_watermarks()
        record_memory_metrics(wm)
        return {"peak_device_bytes": wm.get("peak_bytes"), "memory": wm}
    except Exception:
        return {}


def _build_report(
    ctx: FitContext, started: str, wall: float, mesh
) -> FitReport:
    phases = ctx.timer.as_dict()
    phases.setdefault("total", wall)
    health = _health_once()
    fields: Dict[str, Any] = _mesh_fields(mesh)
    if health:
        fields.setdefault("device_platform", health.get("platform"))
        fields.setdefault("device_count", health.get("device_count"))
    fields.update(_memory_fields())
    try:
        from spark_rapids_ml_tpu.obs.xprof import analytic_mfu

        mfu = analytic_mfu(ctx.analytic_flops, wall)
    except Exception:
        mfu = None
    return FitReport(
        algo=ctx.algo,
        trace_id=ctx.trace_id,
        started_utc=started,
        wall_seconds=wall,
        phases=phases,
        rows=ctx.rows,
        features=ctx.features,
        bytes_processed=ctx.bytes_processed,
        healthy=health.get("healthy") if health else None,
        health=health,
        collectives={k: dict(v) for k, v in ctx.collectives.items()},
        n_iter=ctx.n_iter,
        compiles=ctx.compiles,
        recompiles=ctx.recompiles,
        compile_seconds=ctx.compile_seconds,
        analytic_flops=ctx.analytic_flops or None,
        analytic_bytes=ctx.analytic_bytes or None,
        flops_by_phase=dict(ctx.flops_by_phase),
        analytic_mfu=mfu,
        extra=dict(ctx.extra),
        **fields,
    )


def _flight_deadline(algo: str, trace_id: str):
    """The watchdog context for one fit (no-op if flight is unavailable)."""
    try:
        from spark_rapids_ml_tpu.obs import flight

        return flight.deadline(f"fit:{algo}", trace_id=trace_id)
    except Exception:
        return contextlib.nullcontext()


def _fitmon_run(algo: str, trace_id: str):
    """The fit-path step monitor's run context (obs/fitmon.py): every
    instrumented driver is a monitored FitRun, so its steps land in
    ``/debug/fit`` and the ``sparkml_fit_*`` history. No-op when fitmon
    is disabled or unavailable."""
    try:
        from spark_rapids_ml_tpu.obs import fitmon

        return fitmon.fit_run(algo, trace_id=trace_id)
    except Exception:
        return contextlib.nullcontext()


def _record_metrics(report: FitReport) -> None:
    reg = get_registry()
    algo = report.algo
    reg.counter(
        "sparkml_fits_total", "completed fits", ("algo",)
    ).inc(algo=algo)
    if report.compiles:
        reg.counter(
            "sparkml_fit_compiles_total",
            "XLA compilations attributed to fits", ("algo",),
        ).inc(report.compiles, algo=algo)
    if report.recompiles:
        reg.counter(
            "sparkml_fit_recompiles_total",
            "XLA re-compilations attributed to fits", ("algo",),
        ).inc(report.recompiles, algo=algo)
    if report.analytic_flops:
        reg.counter(
            "sparkml_analytic_flops_total",
            "HLO cost-analysis FLOPs executed by fits", ("algo",),
        ).inc(report.analytic_flops, algo=algo)
    reg.histogram(
        "sparkml_fit_seconds", "fit wall-clock seconds", ("algo",)
    ).observe(report.wall_seconds, algo=algo)
    if report.rows:
        reg.counter(
            "sparkml_rows_processed_total", "rows seen by fits", ("algo",)
        ).inc(report.rows, algo=algo)
    if report.bytes_processed:
        reg.counter(
            "sparkml_bytes_processed_total", "input bytes seen by fits",
            ("algo",),
        ).inc(report.bytes_processed, algo=algo)
    for kind, entry in report.collectives.items():
        reg.counter(
            "sparkml_collective_calls_total",
            "collective invocations (program-level accounting)",
            ("algo", "kind"),
        ).inc(entry.get("count", 0), algo=algo, kind=kind)
        reg.counter(
            "sparkml_collective_bytes_total",
            "collective payload bytes (program-level accounting)",
            ("algo", "kind"),
        ).inc(entry.get("bytes", 0), algo=algo, kind=kind)
    if report.device_platform:
        reg.gauge(
            "sparkml_device_count", "visible devices", ("platform",)
        ).set(report.device_count or 0, platform=report.device_platform)


def _publish(report: FitReport) -> None:
    with _last_lock:
        _last_reports[report.algo] = report
        _last_reports[None] = report
    _record_metrics(report)
    spans.maybe_export_trace(report.trace_id, report.algo)


# -- result attachment -----------------------------------------------------

_subclass_cache: Dict[type, type] = {}
_subclass_lock = threading.Lock()


def _reporting_subclass(cls: type) -> type:
    """A cached subclass of ``cls`` that accepts instance attributes.

    NamedTuple/tuple results have ``__slots__ = ()`` and refuse attributes;
    a trivial subclass (same name, no slots) behaves identically —
    unpacking, ``_fields``, isinstance — but carries ``fit_report_``.
    """
    with _subclass_lock:
        sub = _subclass_cache.get(cls)
        if sub is None:
            sub = type(cls.__name__, (cls,), {"__obs_reported__": True})
            _subclass_cache[cls] = sub
        return sub


def attach_report(result, report, attr: str = REPORT_ATTR):
    """Attach a report to a result under ``attr``, wrapping when needed.

    Handles model objects (plain setattr), NamedTuples and tuples
    (attribute-capable subclass), and ndarrays (subclass view). Results
    that cannot carry attributes are returned unchanged — the report stays
    reachable via ``last_fit_report()`` / ``last_transform_report()``.
    """
    try:
        setattr(result, attr, report)
        return result
    except (AttributeError, TypeError):
        pass
    try:
        if isinstance(result, np.ndarray):
            out = result.view(_reporting_subclass(type(result)))
            setattr(out, attr, report)
            return out
        if isinstance(result, tuple):
            cls = type(result)
            sub = _reporting_subclass(cls)
            if hasattr(cls, "_make"):  # NamedTuple
                out = sub._make(result)
            else:
                out = tuple.__new__(sub, result)
            setattr(out, attr, report)
            return out
    except Exception:
        pass
    return result


# -- the two decorators ----------------------------------------------------


def fit_instrumentation(algo: str, attach: bool = True):
    """Wrap a distributed driver: fit context + root span + report.

    The decorated function's result gains ``fit_report_`` (wrapped into an
    attribute-capable subclass when needed). ``scripts/
    check_instrumentation.py`` statically enforces that every
    ``parallel/distributed_*`` entry point carries this decorator.
    """

    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            ctx = FitContext(algo, trace_id=spans.current_trace_id())
            token = _current_ctx.set(ctx)
            started = _utcnow()
            t0 = time.perf_counter()
            fitmon_run = None
            try:
                with _flight_deadline(algo, ctx.trace_id), _fitmon_run(
                    algo, ctx.trace_id
                ) as fitmon_run, spans.span(
                    f"fit:{algo}", TraceColor.GREEN, trace_id=ctx.trace_id
                ), ctx.timer.phase("total"):
                    result = fn(*args, **kwargs)
            finally:
                _current_ctx.reset(token)
            wall = time.perf_counter() - t0
            try:
                _infer_data_stats(ctx, args, kwargs)
                report = _build_report(
                    ctx, started, wall, _find_mesh(args, kwargs)
                )
                _publish(report)
                if fitmon_run is not None and getattr(
                    fitmon_run, "run_id", None
                ):
                    # join the finished run to its uniform report so
                    # /debug/fit shows the same rollup the model carries
                    fitmon_run.report = {
                        "wall_seconds": report.wall_seconds,
                        "rows": report.rows,
                        "n_iter": report.n_iter,
                        "analytic_mfu": report.analytic_mfu,
                        "collective_bytes":
                            report.total_collective_bytes(),
                    }
                if attach:
                    result = attach_report(result, report)
            except Exception:
                pass  # telemetry must never break a fit
            return result

        wrapper.__obs_instrumented__ = algo
        return wrapper

    return decorator


def observed_fit(algo: str):
    """Wrap an estimator ``fit`` method: the fitted model gains a uniform
    ``fit_report_`` (phases merged from the model's ``fit_timings_``, which
    stays populated for back-compat)."""

    def decorator(method):
        @functools.wraps(method)
        def wrapper(self, dataset, *args, **kwargs):
            ctx = FitContext(algo, trace_id=spans.current_trace_id())
            token = _current_ctx.set(ctx)
            started = _utcnow()
            t0 = time.perf_counter()
            try:
                with _flight_deadline(algo, ctx.trace_id), spans.span(
                    f"fit:{algo}", TraceColor.GREEN, trace_id=ctx.trace_id
                ):
                    model = method(self, dataset, *args, **kwargs)
            finally:
                _current_ctx.reset(token)
            wall = time.perf_counter() - t0
            try:
                stats = _array_stats(dataset)
                if stats is not None:
                    ctx.set_data(
                        rows=stats[0], features=stats[1], nbytes=stats[2]
                    )
                for name, seconds in (
                    getattr(model, "fit_timings_", None) or {}
                ).items():
                    ctx.timer.add(name, seconds)
                report = _build_report(ctx, started, wall, None)
                _publish(report)
                try:
                    setattr(model, REPORT_ATTR, report)
                except (AttributeError, TypeError):
                    pass
            except Exception:
                pass  # telemetry must never break a fit
            return model

        wrapper.__obs_instrumented__ = algo
        return wrapper

    return decorator


def observed_transform(algo=None):
    """Moved: the serving-tier decorator lives in ``obs.serving`` (full
    ``TransformReport`` + sketch latency + numerics sentinel). This alias
    keeps old import paths working."""
    from spark_rapids_ml_tpu.obs.serving import observed_transform as _ot

    return _ot(algo)
