"""Device-memory watermark telemetry (PJRT ``memory_stats``).

One shared reader for what ``scripts/bench_scale.py`` used to hand-roll:
per-device ``memory_stats()`` (PJRT maintains ``peak_bytes_in_use`` as a
true high-watermark, so an end-of-phase read IS the watermark — no sampling
thread needed), folded uniformly into ``FitReport.peak_device_bytes`` /
``FitReport.memory``, the metrics registry, and every bench record.

Backends without PJRT stats (CPU included) report the process RSS peak
(``getrusage ru_maxrss``) instead, with ``source: "host_rss"`` so a host
number is never mistaken for an HBM number.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


def device_memory_stats(device) -> Optional[Dict[str, Any]]:
    """``device.memory_stats()`` guarded: None when the backend has no
    stats (CPU) or the call fails (wedged tunnel must not break telemetry)."""
    try:
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    return dict(stats)


def peak_bytes_in_use(device) -> Optional[int]:
    """One device's peak bytes in use (falls back to current bytes in use
    on runtimes that track no peak), or None without stats."""
    stats = device_memory_stats(device)
    if stats is None:
        return None
    peak = int(stats.get("peak_bytes_in_use",
                         stats.get("bytes_in_use", 0)))
    return peak or None


def host_peak_rss_bytes() -> Optional[int]:
    """Process-lifetime RSS high-watermark (ru_maxrss is KiB on Linux,
    bytes on macOS)."""
    try:
        import resource
        import sys

        scale = 1 if sys.platform == "darwin" else 1024
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * scale
    except Exception:
        return None


def host_current_rss_bytes() -> Optional[int]:
    """CURRENT process RSS (``/proc/self/statm`` resident pages × page
    size) — unlike ``ru_maxrss`` this goes DOWN when memory is freed, so
    a gauge fed from it shows a trend, not a high-watermark. None where
    /proc is unavailable (macOS)."""
    try:
        import resource

        with open("/proc/self/statm") as f:
            resident_pages = int(f.read().split()[1])
        return resident_pages * resource.getpagesize()
    except Exception:
        return None


def memory_watermarks(devices=None) -> Dict[str, Any]:
    """The uniform watermark snapshot every report/bench embeds.

    Returns ``{"source": "pjrt"|"host_rss"|"none", "peak_bytes": int|None,
    "host_peak_rss_bytes": int|None, "per_device": [...]}`` — ``peak_bytes``
    is the max PJRT per-device watermark when any device exposes stats,
    else the host RSS peak (so a CPU run still carries a concrete number,
    visibly host-sourced).
    """
    per_device: List[Dict[str, Any]] = []
    if devices is None:
        try:
            import jax

            devices = jax.devices()
        except Exception:
            devices = []
    device_peaks = []
    for d in devices:
        stats = device_memory_stats(d)
        entry: Dict[str, Any] = {"device": str(d)}
        if stats is not None:
            peak = int(stats.get("peak_bytes_in_use",
                                 stats.get("bytes_in_use", 0)))
            entry["peak_bytes_in_use"] = peak
            entry["bytes_in_use"] = int(stats.get("bytes_in_use", 0))
            if "bytes_limit" in stats:
                entry["bytes_limit"] = int(stats["bytes_limit"])
            device_peaks.append(peak)
        per_device.append(entry)
    rss = host_peak_rss_bytes()
    if device_peaks:
        source = "pjrt"
        peak: Optional[int] = max(device_peaks)
    elif rss is not None:
        source = "host_rss"
        peak = rss
    else:
        source = "none"
        peak = None
    return {
        "source": source,
        "peak_bytes": peak,
        "host_peak_rss_bytes": rss,
        "per_device": per_device,
    }


def record_memory_metrics(watermarks: Optional[Dict[str, Any]] = None) -> None:
    """Export a watermark snapshot into the process metrics registry
    (``sparkml_device_peak_bytes{device=}`` + host RSS gauge)."""
    try:
        from spark_rapids_ml_tpu.obs.metrics import get_registry

        wm = watermarks if watermarks is not None else memory_watermarks()
        reg = get_registry()
        for entry in wm.get("per_device", ()):
            if "peak_bytes_in_use" in entry:
                reg.gauge(
                    "sparkml_device_peak_bytes",
                    "per-device peak bytes in use (PJRT watermark)",
                    ("device",),
                ).set(entry["peak_bytes_in_use"], device=entry["device"])
        if wm.get("host_peak_rss_bytes") is not None:
            reg.gauge(
                "sparkml_host_peak_rss_bytes",
                "process RSS high-watermark",
            ).set(wm["host_peak_rss_bytes"])
    except Exception:
        pass  # telemetry must never break the caller
