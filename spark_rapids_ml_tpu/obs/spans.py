"""Structured trace spans: nested, per-fit trace ids, exportable.

Layered on ``utils.tracing.TraceRange`` (which keeps forwarding to
``jax.profiler.TraceAnnotation`` and the native ring buffer when present):
every completed range/span lands in an in-process ring buffer, tagged with
the innermost active trace id, and a whole fit's spans can be written out as
Chrome-trace/Perfetto JSON. Export is env-gated on
``SPARK_RAPIDS_ML_TPU_TRACE_DIR`` — unset (the default) means zero files,
zero syscalls; the ring buffer alone costs one deque append per span.

The division of labor with ``TraceRange``:

* ``TraceRange`` is the raw annotation primitive (profiler + native
  forwarding). On exit it files itself into this module's recorder via a
  lazy hook, so EVERY existing instrumentation site feeds the exportable
  timeline without being touched.
* ``span(...)`` is the structured layer: it additionally participates in
  the nesting stack (contextvar — correct across threads), inherits or
  mints a trace id, and carries key/value args into the exported JSON.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from spark_rapids_ml_tpu.obs import tracectx
from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange

TRACE_DIR_ENV = "SPARK_RAPIDS_ML_TPU_TRACE_DIR"


def utcnow_iso() -> str:
    """Microsecond-precision UTC timestamp — the one formatter every obs
    artifact (fit/transform reports, flight dumps) shares, so telemetry
    from different tiers orders correctly within a second."""
    import datetime

    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.%fZ"
    )


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass
class SpanEvent:
    """One completed span, Chrome-trace "complete event" shaped.

    ``span_id``/``parent_span_id`` give each trace's events a tree
    structure (``assemble_trace``); ``links`` carries OTHER trace ids this
    span fans in — the coalesced serving batch span links every member
    request's trace, the Dapper fan-in edge."""

    name: str
    ts_us: float
    dur_us: float
    trace_id: Optional[str]
    depth: int
    tid: int
    color: Optional[str] = None
    args: Dict[str, Any] = field(default_factory=dict)
    span_id: Optional[str] = None
    parent_span_id: Optional[str] = None
    links: tuple = ()


class SpanRecorder:
    """Bounded in-process ring buffer of completed spans."""

    def __init__(self, capacity: int = 8192):
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=capacity)

    @property
    def capacity(self) -> int:
        return self._buf.maxlen

    def record(self, event: SpanEvent) -> None:
        with self._lock:
            self._buf.append(event)

    def events(self, trace_id: Optional[str] = None) -> List[SpanEvent]:
        with self._lock:
            evs = list(self._buf)
        if trace_id is None:
            return evs
        return [e for e in evs if e.trace_id == trace_id]

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def chrome_trace(self, trace_id: Optional[str] = None) -> Dict[str, Any]:
        """The buffer (optionally one fit's slice) as a Chrome-trace dict.

        "Complete" events (``ph: "X"``) with microsecond ``ts``/``dur`` —
        loadable by ``chrome://tracing`` and Perfetto directly.
        """
        pid = os.getpid()
        trace_events = []
        for e in self.events(trace_id):
            args = dict(e.args)
            if e.trace_id:
                args["trace_id"] = e.trace_id
            if e.span_id:
                args["span_id"] = e.span_id
            if e.parent_span_id:
                args["parent_span_id"] = e.parent_span_id
            if e.links:
                args["links"] = list(e.links)
            if e.color:
                args["color"] = e.color
            args["depth"] = e.depth
            trace_events.append(
                {
                    "name": e.name,
                    "cat": "spark_rapids_ml_tpu",
                    "ph": "X",
                    "ts": round(e.ts_us, 3),
                    "dur": round(e.dur_us, 3),
                    "pid": pid,
                    "tid": e.tid,
                    "args": args,
                }
            )
        return {"traceEvents": trace_events, "displayTimeUnit": "ms"}

    def export_chrome_trace(
        self, path: str, trace_id: Optional[str] = None
    ) -> str:
        doc = self.chrome_trace(trace_id)
        with open(path, "w") as f:
            json.dump(doc, f)
        return path


_recorder = SpanRecorder()


def get_recorder() -> SpanRecorder:
    return _recorder


@dataclass(frozen=True)
class _ActiveSpan:
    name: str
    trace_id: str
    span_id: str = ""


_stack: contextvars.ContextVar = contextvars.ContextVar(
    "sparkml_span_stack", default=()
)

# Cross-thread registry of OPEN spans (the flight recorder reads it from
# the watchdog thread, where contextvars of the stalled thread are
# invisible): id(token) -> info dict, guarded by one lock.
_active_lock = threading.Lock()
_active: Dict[int, Dict[str, Any]] = {}
_active_seq = 0


def active_spans() -> List[Dict[str, Any]]:
    """Every currently-open span across all threads (oldest first):
    ``{name, trace_id, tid, started_monotonic, elapsed_seconds}``."""
    now = time.perf_counter()
    with _active_lock:
        entries = sorted(_active.values(), key=lambda e: e["seq"])
        return [
            {
                "name": e["name"],
                "trace_id": e["trace_id"],
                "tid": e["tid"],
                "elapsed_seconds": now - e["t0"],
            }
            for e in entries
        ]


def _activate(name: str, trace_id: str, t0: float,
              span_id: Optional[str] = None,
              parent_span_id: Optional[str] = None) -> int:
    global _active_seq
    with _active_lock:
        _active_seq += 1
        handle = _active_seq
        _active[handle] = {
            "seq": handle,
            "name": name,
            "trace_id": trace_id,
            "tid": threading.get_ident(),
            "t0": t0,
            # span identity, so assemble_trace can synthesize a
            # provisional node for a STILL-OPEN span: the serving root
            # (serve:http:predict) is recorded at context exit, AFTER
            # the response bytes hit the socket — a fast client
            # assembling its trace in that window must still see one
            # rooted tree, not orphaned children.
            "span_id": span_id,
            "parent_span_id": parent_span_id,
        }
    return handle


def _deactivate(handle: int) -> None:
    with _active_lock:
        _active.pop(handle, None)


def current_trace_id() -> Optional[str]:
    """The innermost open span's trace id; falls back to the activated
    ``TraceContext`` (the serving request identity) when no span is open
    in this thread yet."""
    st = _stack.get()
    if st:
        return st[-1].trace_id
    ctx = tracectx.current_context()
    return ctx.trace_id if ctx is not None else None


def current_span_id() -> Optional[str]:
    """The innermost open span's id (None outside any span) — what a
    ``TransformReport`` stamps so a report points at its exact span."""
    st = _stack.get()
    if st:
        return st[-1].span_id or None
    ctx = tracectx.current_context()
    return ctx.span_id if ctx is not None else None


def record_trace_range(
    name: str, color, t0_seconds: float, t1_seconds: float
) -> None:
    """Exit hook for ``TraceRange``: file the completed range under the
    innermost active trace (trace id None when no span is open — still
    recorded, just not attributable to one fit)."""
    _recorder.record(
        SpanEvent(
            name=name,
            ts_us=t0_seconds * 1e6,
            dur_us=max(t1_seconds - t0_seconds, 0.0) * 1e6,
            trace_id=current_trace_id(),
            depth=len(_stack.get()),
            tid=threading.get_ident(),
            color=getattr(color, "name", None),
            span_id=tracectx.new_span_id(),
            parent_span_id=current_span_id(),
        )
    )


def record_event(
    name: str,
    t0_seconds: float,
    t1_seconds: float,
    *,
    trace_id: Optional[str] = None,
    span_id: Optional[str] = None,
    parent_span_id: Optional[str] = None,
    links: tuple = (),
    color: Optional[str] = None,
    **args,
) -> SpanEvent:
    """File a span whose interval was measured elsewhere (queue-wait
    spans: the enqueue thread stamps t0, the batcher worker files the
    event at pop time — a ``with span(...)`` there would time the wrong
    thing). Timestamps are ``time.perf_counter()`` seconds, the same
    clock ``span`` uses, so manual and context-managed events interleave
    correctly on one timeline."""
    event = SpanEvent(
        name=name,
        ts_us=t0_seconds * 1e6,
        dur_us=max(t1_seconds - t0_seconds, 0.0) * 1e6,
        trace_id=trace_id,
        depth=0,
        tid=threading.get_ident(),
        color=color,
        args=dict(args),
        span_id=span_id or tracectx.new_span_id(),
        parent_span_id=parent_span_id,
        links=tuple(links),
    )
    _recorder.record(event)
    return event


@contextmanager
def span(
    name: str,
    color: TraceColor = TraceColor.WHITE,
    trace_id: Optional[str] = None,
    links: tuple = (),
    **attrs,
):
    """Structured nested span. Yields the effective trace id.

    Inherits the parent span's trace id — or, at the root, the activated
    serving ``TraceContext``'s — minting one only when neither exists;
    still pushes a ``TraceRange`` underneath so the profiler/native
    timelines see the same name. ``links`` carries OTHER trace ids this
    span fans in (the coalesced-batch → member-request edges).
    """
    parent = _stack.get()
    ctx = tracectx.current_context() if not parent else None
    tid_ = trace_id or (
        parent[-1].trace_id if parent
        else (ctx.trace_id if ctx is not None else new_trace_id())
    )
    span_id = tracectx.new_span_id()
    if parent:
        parent_span_id = parent[-1].span_id or None
    elif ctx is not None and ctx.trace_id == tid_:
        parent_span_id = ctx.span_id
    else:
        parent_span_id = None
    token = _stack.set(parent + (_ActiveSpan(name, tid_, span_id),))
    # record=False: this function records the event itself (with args and
    # the right depth); letting TraceRange's exit hook also fire would
    # duplicate it.
    rng = TraceRange(name, color, record=False)
    rng.__enter__()
    t0 = time.perf_counter()
    active_handle = _activate(name, tid_, t0, span_id=span_id,
                              parent_span_id=parent_span_id)
    error_type: Optional[str] = None
    try:
        yield tid_
    except BaseException as exc:
        error_type = type(exc).__name__
        raise
    finally:
        t1 = time.perf_counter()
        _deactivate(active_handle)
        rng.__exit__(None, None, None)
        _stack.reset(token)
        args = dict(attrs)
        if error_type is not None:
            args["error"] = error_type
        _recorder.record(
            SpanEvent(
                name=name,
                ts_us=t0 * 1e6,
                dur_us=(t1 - t0) * 1e6,
                trace_id=tid_,
                depth=len(parent),
                tid=threading.get_ident(),
                color=getattr(color, "name", None),
                args=args,
                span_id=span_id,
                parent_span_id=parent_span_id,
                links=tuple(links),
            )
        )


# -- trace-tree assembly -----------------------------------------------------


def _span_node(e: SpanEvent, link: bool = False) -> Dict[str, Any]:
    node: Dict[str, Any] = {
        "name": e.name,
        "trace_id": e.trace_id,
        "span_id": e.span_id,
        "parent_span_id": e.parent_span_id,
        "start_us": round(e.ts_us, 3),
        "duration_ms": round(e.dur_us / 1000.0, 6),
        "tid": e.tid,
        "children": [],
    }
    if e.args:
        node["args"] = dict(e.args)
    if e.links:
        node["links"] = list(e.links)
    if link:
        node["link"] = True  # fanned in from another trace
    return node


def _build_forest(events: List[SpanEvent], link: bool = False
                  ) -> List[Dict[str, Any]]:
    """Events of ONE trace → root nodes (children nested, sorted by
    start). A parent missing from the ring (still open, or evicted)
    promotes its children to roots — assembly degrades, never fails."""
    nodes = {e.span_id: _span_node(e, link=link)
             for e in events if e.span_id}
    roots: List[Dict[str, Any]] = []
    for e in sorted(events, key=lambda ev: ev.ts_us):
        node = nodes.get(e.span_id)
        if node is None:
            continue
        parent = nodes.get(e.parent_span_id) if e.parent_span_id else None
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    return roots


def assemble_trace(trace_id: str,
                   recorder: Optional[SpanRecorder] = None
                   ) -> Dict[str, Any]:
    """One request's trace tree from the span ring.

    Spans whose ``trace_id`` matches nest by ``parent_span_id``; spans in
    OTHER traces that ``links``-reference this trace (the coalesced batch
    span and everything under it — the transform, its phases) are grafted
    under the request's root marked ``"link": true``, so the returned
    document is ONE tree spanning server → queue → batch → transform.
    """
    rec = recorder or _recorder
    open_entries: List[Dict[str, Any]] = []
    if rec is _recorder:
        # Snapshot the OPEN-span table BEFORE the ring: a span exiting
        # between the two reads then lands in the ring snapshot — the
        # other order would miss it in both and intermittently return
        # an orphaned forest.
        with _active_lock:
            open_entries = [dict(e) for e in _active.values()
                            if e["trace_id"] == trace_id
                            and e.get("span_id")]
    events = rec.events()
    own = [e for e in events if e.trace_id == trace_id]
    if open_entries:
        # Graft still-open spans in as provisional nodes (duration-so-
        # far, marked "open"): a span records only at context exit,
        # which for the serving root (serve:http:predict) happens AFTER
        # the response bytes are on the socket — a client assembling
        # its trace immediately after the reply must still see ONE
        # rooted tree. A span that exited between the snapshots is in
        # both; the recorded event wins.
        now = time.perf_counter()
        have = {e.span_id for e in own}
        for entry in open_entries:
            if entry["span_id"] in have:
                continue
            own.append(SpanEvent(
                name=entry["name"],
                ts_us=entry["t0"] * 1e6,
                dur_us=max(now - entry["t0"], 0.0) * 1e6,
                trace_id=trace_id,
                depth=0,
                tid=entry["tid"],
                args={"open": True},
                span_id=entry["span_id"],
                parent_span_id=entry.get("parent_span_id"),
            ))
    linked_trace_ids: List[str] = []
    for e in events:
        if e.links and trace_id in e.links and e.trace_id and \
                e.trace_id != trace_id and e.trace_id not in linked_trace_ids:
            linked_trace_ids.append(e.trace_id)
    roots = _build_forest(own)
    linked_forest: List[Dict[str, Any]] = []
    for linked_tid in linked_trace_ids:
        linked_events = [e for e in events if e.trace_id == linked_tid]
        linked_forest.extend(_build_forest(linked_events, link=True))
    if roots and linked_forest:
        roots[0]["children"].extend(linked_forest)
        linked_forest = []

    def _count(nodes):
        return sum(1 + _count(n["children"]) for n in nodes)

    doc: Dict[str, Any] = {
        "trace_id": trace_id,
        "span_count": _count(roots) + _count(linked_forest),
        "spans": roots,
    }
    if linked_forest:  # no own root to graft under (ring rolled over)
        doc["linked"] = linked_forest
    return doc


def recent_traces(limit: int = 20,
                  recorder: Optional[SpanRecorder] = None,
                  name_prefix=None
                  ) -> List[Dict[str, Any]]:
    """Summaries of the most recent distinct traces in the ring (newest
    first): ``{trace_id, root, spans, started_us, duration_ms, links}``.
    ``name_prefix`` (a string or tuple of strings) keeps only traces
    whose earliest span name starts with it (``("serve:http",
    "serve:request")`` → request traces only, batch/fit traces filtered
    out)."""
    rec = recorder or _recorder
    by_trace: Dict[str, List[SpanEvent]] = {}
    order: List[str] = []
    for e in rec.events():
        if not e.trace_id:
            continue
        if e.trace_id not in by_trace:
            by_trace[e.trace_id] = []
            order.append(e.trace_id)
        by_trace[e.trace_id].append(e)
    out: List[Dict[str, Any]] = []
    for tid in reversed(order):
        events = by_trace[tid]
        root = min(events, key=lambda ev: ev.ts_us)
        if name_prefix and not root.name.startswith(name_prefix):
            continue
        t0 = min(e.ts_us for e in events)
        t1 = max(e.ts_us + e.dur_us for e in events)
        links: List[str] = []
        for e in events:
            links.extend(lk for lk in e.links if lk not in links)
        out.append({
            "trace_id": tid,
            "root": root.name,
            "spans": len(events),
            "started_us": round(t0, 3),
            "duration_ms": round((t1 - t0) / 1000.0, 6),
            "links": links,
        })
        if len(out) >= limit:
            break
    return out


def trace_dir() -> Optional[str]:
    return os.environ.get(TRACE_DIR_ENV) or None


def maybe_export_trace(trace_id: str, label: str) -> Optional[str]:
    """Write one fit's spans as Chrome-trace JSON when the env gate is set.

    Returns the written path, or None (gate unset / export failed — trace
    export must never break a fit)."""
    directory = trace_dir()
    if not directory:
        return None
    try:
        os.makedirs(directory, exist_ok=True)
        safe_label = "".join(
            c if (c.isalnum() or c in "-_") else "_" for c in label
        )
        path = os.path.join(
            directory, f"trace_{safe_label}_{trace_id}.json"
        )
        return _recorder.export_chrome_trace(path, trace_id=trace_id)
    except Exception:
        return None
