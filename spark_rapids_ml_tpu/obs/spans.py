"""Structured trace spans: nested, per-fit trace ids, exportable.

Layered on ``utils.tracing.TraceRange`` (which keeps forwarding to
``jax.profiler.TraceAnnotation`` and the native ring buffer when present):
every completed range/span lands in an in-process ring buffer, tagged with
the innermost active trace id, and a whole fit's spans can be written out as
Chrome-trace/Perfetto JSON. Export is env-gated on
``SPARK_RAPIDS_ML_TPU_TRACE_DIR`` — unset (the default) means zero files,
zero syscalls; the ring buffer alone costs one deque append per span.

The division of labor with ``TraceRange``:

* ``TraceRange`` is the raw annotation primitive (profiler + native
  forwarding). On exit it files itself into this module's recorder via a
  lazy hook, so EVERY existing instrumentation site feeds the exportable
  timeline without being touched.
* ``span(...)`` is the structured layer: it additionally participates in
  the nesting stack (contextvar — correct across threads), inherits or
  mints a trace id, and carries key/value args into the exported JSON.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from spark_rapids_ml_tpu.utils.tracing import TraceColor, TraceRange

TRACE_DIR_ENV = "SPARK_RAPIDS_ML_TPU_TRACE_DIR"


def utcnow_iso() -> str:
    """Microsecond-precision UTC timestamp — the one formatter every obs
    artifact (fit/transform reports, flight dumps) shares, so telemetry
    from different tiers orders correctly within a second."""
    import datetime

    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.%fZ"
    )


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


@dataclass
class SpanEvent:
    """One completed span, Chrome-trace "complete event" shaped."""

    name: str
    ts_us: float
    dur_us: float
    trace_id: Optional[str]
    depth: int
    tid: int
    color: Optional[str] = None
    args: Dict[str, Any] = field(default_factory=dict)


class SpanRecorder:
    """Bounded in-process ring buffer of completed spans."""

    def __init__(self, capacity: int = 8192):
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=capacity)

    @property
    def capacity(self) -> int:
        return self._buf.maxlen

    def record(self, event: SpanEvent) -> None:
        with self._lock:
            self._buf.append(event)

    def events(self, trace_id: Optional[str] = None) -> List[SpanEvent]:
        with self._lock:
            evs = list(self._buf)
        if trace_id is None:
            return evs
        return [e for e in evs if e.trace_id == trace_id]

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def chrome_trace(self, trace_id: Optional[str] = None) -> Dict[str, Any]:
        """The buffer (optionally one fit's slice) as a Chrome-trace dict.

        "Complete" events (``ph: "X"``) with microsecond ``ts``/``dur`` —
        loadable by ``chrome://tracing`` and Perfetto directly.
        """
        pid = os.getpid()
        trace_events = []
        for e in self.events(trace_id):
            args = dict(e.args)
            if e.trace_id:
                args["trace_id"] = e.trace_id
            if e.color:
                args["color"] = e.color
            args["depth"] = e.depth
            trace_events.append(
                {
                    "name": e.name,
                    "cat": "spark_rapids_ml_tpu",
                    "ph": "X",
                    "ts": round(e.ts_us, 3),
                    "dur": round(e.dur_us, 3),
                    "pid": pid,
                    "tid": e.tid,
                    "args": args,
                }
            )
        return {"traceEvents": trace_events, "displayTimeUnit": "ms"}

    def export_chrome_trace(
        self, path: str, trace_id: Optional[str] = None
    ) -> str:
        doc = self.chrome_trace(trace_id)
        with open(path, "w") as f:
            json.dump(doc, f)
        return path


_recorder = SpanRecorder()


def get_recorder() -> SpanRecorder:
    return _recorder


@dataclass(frozen=True)
class _ActiveSpan:
    name: str
    trace_id: str


_stack: contextvars.ContextVar = contextvars.ContextVar(
    "sparkml_span_stack", default=()
)

# Cross-thread registry of OPEN spans (the flight recorder reads it from
# the watchdog thread, where contextvars of the stalled thread are
# invisible): id(token) -> info dict, guarded by one lock.
_active_lock = threading.Lock()
_active: Dict[int, Dict[str, Any]] = {}
_active_seq = 0


def active_spans() -> List[Dict[str, Any]]:
    """Every currently-open span across all threads (oldest first):
    ``{name, trace_id, tid, started_monotonic, elapsed_seconds}``."""
    now = time.perf_counter()
    with _active_lock:
        entries = sorted(_active.values(), key=lambda e: e["seq"])
        return [
            {
                "name": e["name"],
                "trace_id": e["trace_id"],
                "tid": e["tid"],
                "elapsed_seconds": now - e["t0"],
            }
            for e in entries
        ]


def _activate(name: str, trace_id: str, t0: float) -> int:
    global _active_seq
    with _active_lock:
        _active_seq += 1
        handle = _active_seq
        _active[handle] = {
            "seq": handle,
            "name": name,
            "trace_id": trace_id,
            "tid": threading.get_ident(),
            "t0": t0,
        }
    return handle


def _deactivate(handle: int) -> None:
    with _active_lock:
        _active.pop(handle, None)


def current_trace_id() -> Optional[str]:
    st = _stack.get()
    return st[-1].trace_id if st else None


def record_trace_range(
    name: str, color, t0_seconds: float, t1_seconds: float
) -> None:
    """Exit hook for ``TraceRange``: file the completed range under the
    innermost active trace (trace id None when no span is open — still
    recorded, just not attributable to one fit)."""
    _recorder.record(
        SpanEvent(
            name=name,
            ts_us=t0_seconds * 1e6,
            dur_us=max(t1_seconds - t0_seconds, 0.0) * 1e6,
            trace_id=current_trace_id(),
            depth=len(_stack.get()),
            tid=threading.get_ident(),
            color=getattr(color, "name", None),
        )
    )


@contextmanager
def span(
    name: str,
    color: TraceColor = TraceColor.WHITE,
    trace_id: Optional[str] = None,
    **attrs,
):
    """Structured nested span. Yields the effective trace id.

    Inherits the parent span's trace id (or mints one at the root) and
    still pushes a ``TraceRange`` underneath so the profiler/native
    timelines see the same name.
    """
    parent = _stack.get()
    tid_ = trace_id or (parent[-1].trace_id if parent else new_trace_id())
    token = _stack.set(parent + (_ActiveSpan(name, tid_),))
    # record=False: this function records the event itself (with args and
    # the right depth); letting TraceRange's exit hook also fire would
    # duplicate it.
    rng = TraceRange(name, color, record=False)
    rng.__enter__()
    t0 = time.perf_counter()
    active_handle = _activate(name, tid_, t0)
    error_type: Optional[str] = None
    try:
        yield tid_
    except BaseException as exc:
        error_type = type(exc).__name__
        raise
    finally:
        t1 = time.perf_counter()
        _deactivate(active_handle)
        rng.__exit__(None, None, None)
        _stack.reset(token)
        args = dict(attrs)
        if error_type is not None:
            args["error"] = error_type
        _recorder.record(
            SpanEvent(
                name=name,
                ts_us=t0 * 1e6,
                dur_us=(t1 - t0) * 1e6,
                trace_id=tid_,
                depth=len(parent),
                tid=threading.get_ident(),
                color=getattr(color, "name", None),
                args=args,
            )
        )


def trace_dir() -> Optional[str]:
    return os.environ.get(TRACE_DIR_ENV) or None


def maybe_export_trace(trace_id: str, label: str) -> Optional[str]:
    """Write one fit's spans as Chrome-trace JSON when the env gate is set.

    Returns the written path, or None (gate unset / export failed — trace
    export must never break a fit)."""
    directory = trace_dir()
    if not directory:
        return None
    try:
        os.makedirs(directory, exist_ok=True)
        safe_label = "".join(
            c if (c.isalnum() or c in "-_") else "_" for c in label
        )
        path = os.path.join(
            directory, f"trace_{safe_label}_{trace_id}.json"
        )
        return _recorder.export_chrome_trace(path, trace_id=trace_id)
    except Exception:
        return None
