"""Robust baseline statistics shared by the perf sentinel and the
online anomaly detectors.

The perf sentinel has judged bench records against a
``max(tolerance, 2·MAD/median)`` noise band since PR 2; the auto-incident
engine (``obs.anomaly``) needs the exact same arithmetic to judge live
series against their own trailing history. One implementation, two
consumers — the offline and online verdicts can never diverge.

Deliberately **stdlib-only with no package imports**:
``scripts/perf_sentinel.py`` loads this file by path
(``importlib.util.spec_from_file_location``) so judging a JSON record
never pays for — or depends on — a jax import.

The MAD is scaled by 1/0.6745 in ``robust_zscore`` (the normal
consistency constant), so a robust z of 3 means the same thing a
3-sigma excursion means on Gaussian data — but one outlier in the
baseline cannot inflate the band the way it would inflate a stddev.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

# MAD → sigma consistency constant for normal data: sigma ≈ MAD / 0.6745.
MAD_CONSISTENCY = 0.6745


def median(values: Sequence[float]) -> float:
    """The sample median (mean of the middle two for even n)."""
    vs = sorted(values)
    n = len(vs)
    if n == 0:
        raise ValueError("median of an empty sequence")
    mid = n // 2
    return vs[mid] if n % 2 else 0.5 * (vs[mid - 1] + vs[mid])


def mad(values: Sequence[float], center: Optional[float] = None) -> float:
    """Median absolute deviation around ``center`` (default: the
    median). 0.0 for a constant series — callers must guard the
    division (``robust_zscore`` does)."""
    med = median(values) if center is None else center
    return median([abs(v - med) for v in values])


def noise_band(values: Sequence[float], tolerance: float) -> float:
    """Relative half-width of the acceptance band around the median:
    ``max(tolerance, 2·MAD/|median|)``. THE perf-sentinel band —
    single samples (and an exactly-zero median) fall back to the
    tolerance; noisy histories widen to the observed spread."""
    if len(values) < 2:
        return tolerance
    med = median(values)
    if not med:
        return tolerance
    return max(tolerance, 2.0 * mad(values, center=med) / abs(med))


def robust_zscore(value: float, baseline: Sequence[float]) -> float:
    """How many robust sigmas ``value`` sits above/below the baseline's
    median (``0.6745 · (value - median) / MAD``).

    A constant baseline has MAD 0: the z-score is 0.0 when the value
    matches it exactly and ±inf otherwise — callers pair the z test
    with an absolute/relative step guard (``obs.anomaly`` does) so a
    0.1% wiggle off a flat line cannot read as an infinite anomaly.
    """
    med = median(baseline)
    m = mad(baseline, center=med)
    if m == 0.0:
        if value == med:
            return 0.0
        return float("inf") if value > med else float("-inf")
    return MAD_CONSISTENCY * (value - med) / m


def baseline_stats(values: Sequence[float],
                   tolerance: float = 0.15) -> dict:
    """The (median, MAD, band) triple detectors and verdicts report —
    one dict so incident records and sentinel verdicts read alike."""
    med = median(values)
    return {
        "median": med,
        "mad": mad(values, center=med),
        "band": noise_band(values, tolerance),
        "n_samples": len(values),
    }


__all__: List[str] = [
    "MAD_CONSISTENCY",
    "baseline_stats",
    "mad",
    "median",
    "noise_band",
    "robust_zscore",
]
