"""Auto-incident engine: detectors → open/update/resolve lifecycle →
evidence bundles captured while the anomaly is still happening.

``obs.anomaly`` notices; this module remembers and diagnoses. An
``IncidentEngine`` runs the detector catalog once per metrics-sampler
sweep (``install()`` hooks ``MetricsSampler.register_post_sweep`` — no
new thread, and the sweep cost lands in
``sparkml_obs_overhead_seconds_total{component="anomaly"}``), feeding an
``IncidentManager`` that applies the alerting hygiene a paging system
needs:

* **hysteresis** — a detector must fire ``open_after`` consecutive
  sweeps to open (one noisy sample never pages) and stay quiet
  ``resolve_after`` consecutive sweeps to resolve (a flapping signal
  never storms the log);
* **dedup** — one open incident per (detector, series); continued
  firing updates it (``updates`` count, latest value) instead of
  opening siblings;
* **cooldown** — a just-resolved key cannot reopen for
  ``cooldown_seconds`` (counted in
  ``sparkml_obs_incidents_suppressed_total``, never silent);
* **severity from burn rate** — the detector's own severity is
  escalated by the live 5-minute SLO burn gauge through the same
  SRE-workbook ladder the alert policies use
  (``obs.slo.severity_for_burn``).

Opening an incident assembles an **evidence bundle** on disk
(``<dump_dir>/incidents/<id>/``) while the metrics still show the
lead-up:

* ``incident.json`` — the record itself (rewritten at resolve);
* ``history.json`` — last-5-minutes of the implicated series plus the
  standard serve/SLO/device context tail;
* ``traces.json`` — slowest-request trace-id exemplars from the
  latency summaries, each assembled into a full span tree;
* ``breakers.json`` — circuit-breaker transition ring + live states
  (via the flight recorder's registered dump section — no obs → serve
  import);
* a **flight dump** (stacks, open spans, in-flight requests, metrics);
* for latency/memory incidents, a **guarded profile capture**
  (``obs.profiler.start_capture`` — single-flight; skipped, and
  recorded as skipped, when one is already running).

Operator surface: ``GET /debug/incidents`` + the dashboard timeline
(``serve.server``), ``sparkml_obs_incidents_total{detector,severity}``,
``sparkml_obs_incidents_open``, and a structured ERROR log line per
open — the pointer to the bundle survives any UI.

All timestamps flow from the caller's ``now`` (the sampler's injectable
clock): this module never reads the wall clock directly
(``check_instrumentation`` rule 8), so tests drive the whole
open→update→resolve lifecycle with zero real sleeps.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from spark_rapids_ml_tpu.obs import anomaly as anomaly_mod
from spark_rapids_ml_tpu.obs import flight
from spark_rapids_ml_tpu.obs import metrics as metrics_mod
from spark_rapids_ml_tpu.obs import profiler as profiler_mod
from spark_rapids_ml_tpu.obs import spans as spans_mod
from spark_rapids_ml_tpu.obs import tsdb as tsdb_mod
from spark_rapids_ml_tpu.obs.logging import _env_float, get_logger
from spark_rapids_ml_tpu.obs.slo import severity_for_burn

# one guarded-eval helper for the whole obs layer, not a copy per module
_safe = flight._safe

ENABLED_ENV = "SPARK_RAPIDS_ML_TPU_OBS_INCIDENTS"
OPEN_AFTER_ENV = "SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_OPEN_AFTER"
RESOLVE_AFTER_ENV = "SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_RESOLVE_AFTER"
COOLDOWN_ENV = "SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_COOLDOWN_S"
CAPTURE_ENV = "SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_CAPTURE_S"

_DEFAULT_OPEN_AFTER = 2
_DEFAULT_RESOLVE_AFTER = 5
_DEFAULT_COOLDOWN_S = 60.0
_DEFAULT_CAPTURE_S = 3.0
_HISTORY_WINDOW_S = 300.0
_RECENT_LIMIT = 32
_MAX_TRACE_TREES = 3
# Summaries whose slowest-trace exemplars seed the bundle's trace trees.
_EXEMPLAR_FAMILIES = (
    "sparkml_serve_request_latency_seconds",
    "sparkml_http_request_latency_seconds",
)
_SEVERITY_RANK = {s: i for i, s in enumerate(anomaly_mod.SEVERITIES)}

_log = get_logger("obs.incidents")


def enabled() -> bool:
    """The auto-incident engine's kill switch (default on)."""
    return os.environ.get(ENABLED_ENV, "1").strip().lower() not in (
        "0", "false", "off", "no")


def incidents_dir() -> str:
    return os.path.join(flight.dump_dir(), "incidents")


def _safe_id_part(text: str) -> str:
    return "".join(c if (c.isalnum() or c in "-_") else "_"
                   for c in str(text))[:60]


class Incident:
    """One detected anomaly's lifecycle: open → update* → resolve."""

    __slots__ = ("id", "detector", "kind", "severity", "metric",
                 "labels", "state", "opened_ts", "updated_ts",
                 "resolved_ts", "value", "baseline", "reason",
                 "updates", "quiet_sweeps", "evidence")

    def __init__(self, incident_id: str, finding: anomaly_mod.Finding,
                 severity: str, now: float):
        self.id = incident_id
        self.detector = finding.detector
        self.kind = finding.kind
        self.severity = severity
        self.metric = finding.metric
        self.labels = dict(finding.labels)
        self.state = "open"
        self.opened_ts = now
        self.updated_ts = now
        self.resolved_ts: Optional[float] = None
        self.value = finding.value
        self.baseline = finding.baseline
        self.reason = finding.reason
        self.updates = 0
        self.quiet_sweeps = 0
        self.evidence: Dict[str, Any] = {}

    def as_dict(self) -> Dict[str, Any]:
        return {
            "id": self.id,
            "detector": self.detector,
            "kind": self.kind,
            "severity": self.severity,
            "metric": self.metric,
            "labels": dict(self.labels),
            "state": self.state,
            "opened_ts": self.opened_ts,
            "updated_ts": self.updated_ts,
            "resolved_ts": self.resolved_ts,
            "duration_seconds": (
                (self.resolved_ts if self.resolved_ts is not None
                 else self.updated_ts) - self.opened_ts
            ),
            "value": self.value,
            "baseline": self.baseline,
            "reason": self.reason,
            "updates": self.updates,
            "evidence": dict(self.evidence),
        }


class IncidentManager:
    """Hysteresis, dedup, cooldown, and evidence capture over findings.

    ``observe(findings, now, store)`` is the one entry point, called
    once per detector sweep with THAT sweep's findings and timestamp —
    the manager itself never reads a clock.
    """

    def __init__(
        self,
        *,
        open_after: Optional[int] = None,
        resolve_after: Optional[int] = None,
        cooldown_seconds: Optional[float] = None,
        capture_seconds: Optional[float] = None,
        evidence_root: Optional[str] = None,
        history_window: float = _HISTORY_WINDOW_S,
        recent_limit: int = _RECENT_LIMIT,
        registry: Optional[metrics_mod.MetricsRegistry] = None,
    ):
        self.open_after = max(int(
            open_after if open_after is not None
            else _env_float(OPEN_AFTER_ENV, _DEFAULT_OPEN_AFTER)), 1)
        self.resolve_after = max(int(
            resolve_after if resolve_after is not None
            else _env_float(RESOLVE_AFTER_ENV, _DEFAULT_RESOLVE_AFTER)),
            1)
        self.cooldown_seconds = float(
            cooldown_seconds if cooldown_seconds is not None
            else _env_float(COOLDOWN_ENV, _DEFAULT_COOLDOWN_S))
        self.capture_seconds = float(
            capture_seconds if capture_seconds is not None
            else _env_float(CAPTURE_ENV, _DEFAULT_CAPTURE_S))
        self._evidence_root = evidence_root
        self.history_window = float(history_window)
        self.recent_limit = int(recent_limit)
        self._registry = registry
        self._lock = threading.Lock()
        self._open: Dict[Tuple, Incident] = {}
        self._streaks: Dict[Tuple, int] = {}
        self._last_resolved: Dict[Tuple, float] = {}
        self._recent: List[Incident] = []
        self.opened_total = 0
        self.resolved_total = 0
        self.suppressed_total = 0

    def _reg(self) -> metrics_mod.MetricsRegistry:
        return (self._registry if self._registry is not None
                else metrics_mod.get_registry())

    def evidence_root(self) -> str:
        return self._evidence_root or incidents_dir()

    # -- the sweep entry point ---------------------------------------------

    def observe(self, findings: List[anomaly_mod.Finding], now: float,
                store: Optional[tsdb_mod.TimeSeriesStore] = None,
                ) -> List[Incident]:
        """Apply one sweep's findings; returns incidents OPENED by it.

        State transitions happen under the lock; evidence capture and
        logging happen AFTER it releases — the flight dump an open
        triggers runs every registered dump section, including this
        manager's own, and bundle I/O must never block a
        ``/debug/incidents`` poll.
        """
        by_key: Dict[Tuple, anomaly_mod.Finding] = {}
        for finding in findings:
            by_key[finding.key] = finding
        opened: List[Incident] = []
        resolved: List[Incident] = []
        with self._lock:
            # keys that went quiet lose their pending open streak
            for key in [k for k in self._streaks if k not in by_key]:
                del self._streaks[key]
            for key, finding in by_key.items():
                incident = self._open.get(key)
                if incident is not None:
                    incident.updated_ts = now
                    incident.value = finding.value
                    incident.reason = finding.reason
                    incident.updates += 1
                    incident.quiet_sweeps = 0
                    continue
                resolved_at = self._last_resolved.get(key)
                if (resolved_at is not None
                        and now - resolved_at < self.cooldown_seconds):
                    self._streaks.pop(key, None)
                    self.suppressed_total += 1
                    self._count_suppressed(finding.detector)
                    continue
                streak = self._streaks.get(key, 0) + 1
                if streak < self.open_after:
                    self._streaks[key] = streak
                    continue
                self._streaks.pop(key, None)
                severity = self._effective_severity(finding, now, store)
                self.opened_total += 1
                # the sequence number keeps ids (and so evidence dirs)
                # unique when one detector opens on TWO series in the
                # same sweep — same detector, same millisecond
                incident = Incident(
                    f"inc_{_safe_id_part(finding.detector)}"
                    f"_{int(now * 1000)}_{self.opened_total}",
                    finding, severity, now,
                )
                self._open[key] = incident
                opened.append(incident)
            # open incidents not re-asserted this sweep edge toward
            # resolution
            for key, incident in list(self._open.items()):
                if key in by_key:
                    continue
                incident.quiet_sweeps += 1
                if incident.quiet_sweeps >= self.resolve_after:
                    incident.state = "resolved"
                    incident.resolved_ts = now
                    del self._open[key]
                    self._last_resolved[key] = now
                    self.resolved_total += 1
                    self._recent.append(incident)
                    del self._recent[:-self.recent_limit]
                    resolved.append(incident)
            self._publish_open_gauge()
        for incident in opened:
            self._finish_open(incident, now, store)
        for incident in resolved:
            _write_incident_json(incident)
            _log.info(
                "incident resolved", incident_id=incident.id,
                detector=incident.detector,
                duration_seconds=now - incident.opened_ts,
                updates=incident.updates,
            )
        return opened

    # -- lifecycle internals (outside the lock) -----------------------------

    def _finish_open(self, incident: Incident, now: float,
                     store) -> None:
        try:
            self._reg().counter(
                "sparkml_obs_incidents_total",
                "auto-detected incidents opened, by detector and "
                "severity", ("detector", "severity"),
            ).inc(detector=incident.detector,
                  severity=incident.severity)
        except Exception:
            pass  # incident accounting must never kill the sweep
        _capture_evidence(incident, now, store, self)
        # ERROR: the pointer to the evidence bundle must survive any
        # production log-level threshold, exactly like a flight dump.
        _log.error(
            "incident opened", incident_id=incident.id,
            detector=incident.detector, severity=incident.severity,
            kind=incident.kind, labels=incident.labels,
            value=incident.value, baseline=incident.baseline,
            reason=incident.reason,
            evidence=incident.evidence.get("dir"),
        )

    def _effective_severity(self, finding: anomaly_mod.Finding,
                            now: float, store) -> str:
        """The detector's severity, escalated by the live 5m SLO burn
        (the SRE ladder: burn ≥ 14.4 pages critical no matter which
        detector noticed first)."""
        severity = finding.severity
        if store is None:
            return severity
        try:
            burn = 0.0
            for series in store.range_query(
                    "sparkml_slo_burn_rate", {"window": "5m"},
                    120.0, now=now):
                if series["points"]:
                    burn = max(burn, series["points"][-1][1])
            escalated = severity_for_burn(burn)
            if (escalated is not None
                    and _SEVERITY_RANK.get(escalated, 0)
                    > _SEVERITY_RANK.get(severity, 0)):
                return escalated
        except Exception:
            pass  # severity escalation is best-effort
        return severity

    def _count_suppressed(self, detector: str) -> None:
        try:
            self._reg().counter(
                "sparkml_obs_incidents_suppressed_total",
                "incident opens suppressed by the post-resolve "
                "cooldown, by detector", ("detector",),
            ).inc(detector=detector)
        except Exception:
            pass

    def _publish_open_gauge(self) -> None:
        try:
            self._reg().gauge(
                "sparkml_obs_incidents_open",
                "currently-open auto-detected incidents",
            ).set(float(len(self._open)))
        except Exception:
            pass

    # -- introspection ------------------------------------------------------

    def open_incidents(self) -> List[Dict[str, Any]]:
        with self._lock:
            incidents = sorted(self._open.values(),
                               key=lambda i: i.opened_ts, reverse=True)
            return [i.as_dict() for i in incidents]

    def recent_incidents(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [i.as_dict() for i in reversed(self._recent)]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            open_ = sorted(self._open.values(),
                           key=lambda i: i.opened_ts, reverse=True)
            return {
                "open": [i.as_dict() for i in open_],
                "recent": [i.as_dict() for i in reversed(self._recent)],
                "opened_total": self.opened_total,
                "resolved_total": self.resolved_total,
                "suppressed_total": self.suppressed_total,
                "open_after": self.open_after,
                "resolve_after": self.resolve_after,
                "cooldown_seconds": self.cooldown_seconds,
                "evidence_root": self.evidence_root(),
            }


# -- evidence assembly --------------------------------------------------------


def _write_json(path: str, doc: Any) -> Optional[str]:
    """Atomic JSON write (tmp + rename, like flight dumps); returns the
    path or None — a failed artifact never kills the sweep."""
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, default=str)
        os.replace(tmp, path)
        return path
    except Exception:
        return None


def _write_incident_json(incident: Incident) -> None:
    directory = incident.evidence.get("dir")
    if not directory:
        return
    _write_json(os.path.join(directory, "incident.json"),
                incident.as_dict())


def _history_doc(incident: Incident, now: float, store,
                 window: float) -> Dict[str, Any]:
    return {
        "window_seconds": window,
        "implicated": {
            "metric": incident.metric,
            "labels": dict(incident.labels),
            "series": store.range_query(
                incident.metric, incident.labels or None, window,
                now=now),
        },
        "context": store.history_tail(
            prefixes=("sparkml_serve_", "sparkml_slo_",
                      "sparkml_device_", "sparkml_host_"),
            window=window, now=now),
    }


def _exemplar_trace_ids(registry: metrics_mod.MetricsRegistry,
                        limit: int) -> List[Dict[str, Any]]:
    """Slowest-request exemplars (value + trace id) from the latency
    summaries, slowest first across families."""
    exemplars: List[Dict[str, Any]] = []
    for family in registry.families():
        if family.name not in _EXEMPLAR_FAMILIES:
            continue
        if not isinstance(family, metrics_mod.Summary):
            continue
        for key, child in family._samples():
            with child.lock:
                ring = list(child.exemplars)
            labels = family._label_dict(key)
            for value, trace_id, unix_ts in ring:
                exemplars.append({
                    "metric": family.name, "labels": labels,
                    "value": value, "trace_id": trace_id,
                    "unix_ts": unix_ts,
                })
    exemplars.sort(key=lambda e: e["value"], reverse=True)
    return exemplars[:max(limit, 1)]


def _traces_doc(registry: metrics_mod.MetricsRegistry) -> Dict[str, Any]:
    exemplars = _safe(
        lambda: _exemplar_trace_ids(registry, _MAX_TRACE_TREES * 2), [])
    trees: List[Dict[str, Any]] = []
    seen: set = set()
    for ex in exemplars:
        tid = ex["trace_id"]
        if tid in seen:
            continue
        seen.add(tid)
        tree = _safe(lambda t=tid: spans_mod.assemble_trace(t))
        if tree and tree.get("span_count"):
            trees.append(tree)
        if len(trees) >= _MAX_TRACE_TREES:
            break
    if not trees:
        # no exemplars yet (cold process): fall back to the most recent
        # request traces in the span ring
        for summary in _safe(
                lambda: spans_mod.recent_traces(
                    _MAX_TRACE_TREES,
                    name_prefix=("serve:http", "serve:request")), []):
            tree = _safe(lambda s=summary: spans_mod.assemble_trace(
                s["trace_id"]))
            if tree and tree.get("span_count"):
                trees.append(tree)
    return {"exemplars": exemplars, "trees": trees}


def _maybe_profile(incident: Incident,
                   capture_seconds: float) -> Dict[str, Any]:
    """A guarded capture for latency/memory incidents: single-flight by
    construction — a second incident while one capture runs records
    ``skipped`` instead of stacking profiler overhead on a sick
    process."""
    if capture_seconds <= 0:
        return {"skipped": "disabled"}
    if incident.kind not in ("latency", "memory"):
        return {"skipped": f"kind_{incident.kind}"}
    try:
        info = profiler_mod.start_capture(
            capture_seconds, label=f"incident_{incident.detector}")
        return {"started": info}
    except profiler_mod.CaptureInFlight:
        return {"skipped": "capture_in_flight"}
    except Exception as exc:  # noqa: BLE001 - evidence is best-effort
        return {"error": f"{type(exc).__name__}: {exc}"}


def _capture_evidence(incident: Incident, now: float, store,
                      manager: IncidentManager) -> None:
    """Assemble the on-disk bundle. Every artifact is independently
    guarded: a full disk loses evidence, never the incident (errors are
    themselves recorded in the bundle index)."""
    evidence: Dict[str, Any] = {}
    try:
        directory = os.path.join(manager.evidence_root(), incident.id)
        os.makedirs(directory, exist_ok=True)
        evidence["dir"] = directory
    except Exception as exc:  # noqa: BLE001 - recorded, not raised
        incident.evidence = {
            "error": f"evidence dir failed: "
                     f"{type(exc).__name__}: {exc}",
        }
        return
    if store is not None:
        evidence["history"] = _write_json(
            os.path.join(directory, "history.json"),
            _safe(lambda: _history_doc(incident, now, store,
                                       manager.history_window), {}),
        )
    evidence["traces"] = _write_json(
        os.path.join(directory, "traces.json"),
        _safe(lambda: _traces_doc(manager._reg()), {}),
    )
    breakers = flight.run_dump_section("breaker_events")
    if breakers is not None:
        evidence["breakers"] = _write_json(
            os.path.join(directory, "breakers.json"), breakers)
    evidence["flight_dump"] = _safe(lambda: flight.dump(
        f"incident:{incident.detector}",
        extra={
            "incident_id": incident.id,
            "detector": incident.detector,
            "labels": dict(incident.labels),
            "reason": incident.reason,
        },
    ))
    evidence["profile"] = _maybe_profile(incident,
                                         manager.capture_seconds)
    incident.evidence = evidence
    _write_incident_json(incident)
    # incident bundles share the artifact GC with flight dumps and
    # profile captures — an incident storm must not fill the disk
    from spark_rapids_ml_tpu.obs import retention

    _safe(lambda: retention.maybe_gc("incident"))


# -- the engine ---------------------------------------------------------------


class IncidentEngine:
    """Detector sweep + incident manager, hooked into the sampler.

    ``sweep(now)`` evaluates every detector against the store and feeds
    the manager; ``install(sampler)`` registers it as a post-sweep hook
    so detection runs on the EXISTING sampler thread at the sampling
    cadence, right after fresh samples land. The sweep's wall-clock
    cost is visible in
    ``sparkml_obs_overhead_seconds_total{component="anomaly"}``.
    """

    def __init__(
        self,
        store: Optional[tsdb_mod.TimeSeriesStore] = None,
        detectors: Optional[List[anomaly_mod.Detector]] = None,
        manager: Optional[IncidentManager] = None,
        registry: Optional[metrics_mod.MetricsRegistry] = None,
    ):
        self._store = store
        self.detectors: List[anomaly_mod.Detector] = (
            list(detectors) if detectors is not None
            else anomaly_mod.builtin_detectors()
        )
        self.manager = manager if manager is not None else (
            IncidentManager(registry=registry))
        self._registry = registry
        self._sweeps = 0
        # flat-0 gauge so dashboards see the series before the first
        # incident, not an absent metric
        self.manager._publish_open_gauge()

    def _reg(self) -> metrics_mod.MetricsRegistry:
        return (self._registry if self._registry is not None
                else metrics_mod.get_registry())

    def store(self) -> tsdb_mod.TimeSeriesStore:
        return (self._store if self._store is not None
                else tsdb_mod.get_tsdb())

    @property
    def sweeps(self) -> int:
        return self._sweeps

    def sweep(self, now: Optional[float] = None) -> List[Incident]:
        """One detection pass; returns incidents opened by it."""
        t0 = time.perf_counter()
        store = self.store()
        ts = store.clock() if now is None else now
        findings: List[anomaly_mod.Finding] = []
        for detector in self.detectors:
            try:
                findings.extend(detector.evaluate(store, ts))
            except Exception:
                self._count_detector_error(detector)
        opened = self.manager.observe(findings, ts, store=store)
        self._sweeps += 1
        try:
            self._reg().counter(
                "sparkml_obs_overhead_seconds_total",
                "wall-clock the observability layer spends watching "
                "(sampler sweeps, device monitor, profiler "
                "bookkeeping)", ("component",),
            ).inc(time.perf_counter() - t0, component="anomaly")
        except Exception:
            pass  # overhead accounting must never break detection
        return opened

    def install(self, sampler: tsdb_mod.MetricsSampler) -> None:
        """Run detection after every sampler sweep (idempotent — bound
        methods of one engine compare equal, so re-installing on server
        restarts never doubles the cadence). The INSTALLED engine also
        owns the ``incidents`` flight-dump section — registering it
        here, not in the constructor, keeps a hand-built side engine
        (examples, tests) from silently replacing the live server's
        section and from being pinned forever by the registry's strong
        reference."""
        sampler.register_post_sweep(self._post_sweep)
        flight.register_dump_section("incidents", self._dump_section)

    def uninstall(self, sampler: tsdb_mod.MetricsSampler) -> None:
        sampler.unregister_post_sweep(self._post_sweep)
        flight.unregister_dump_section("incidents")

    def _post_sweep(self, ts: float) -> None:
        self.sweep(now=ts)

    def _count_detector_error(self, detector) -> None:
        try:
            self._reg().counter(
                "sparkml_obs_detector_errors_total",
                "anomaly detectors that raised during a sweep",
                ("detector",),
            ).inc(detector=getattr(detector, "name", "detector"))
        except Exception:
            pass

    def _dump_section(self) -> Dict[str, Any]:
        # every flight dump names the incidents that were already open
        # when it was taken — a wedge diagnostic starts from them
        return {
            "open": self.manager.open_incidents(),
            "opened_total": self.manager.opened_total,
            "resolved_total": self.manager.resolved_total,
        }

    def snapshot(self) -> Dict[str, Any]:
        """The ``GET /debug/incidents`` document."""
        doc = self.manager.snapshot()
        doc["sweeps"] = self._sweeps
        doc["detectors"] = [d.describe() for d in self.detectors]
        return doc

    def digest(self, recent_limit: int = 8) -> Dict[str, Any]:
        """Compact open/recent digests for the fleet export
        (``obs.federation``): lifecycle fields only, no evidence
        bundles — an export is a poll payload, not an archive."""
        fields = ("id", "detector", "kind", "severity", "metric",
                  "labels", "state", "opened_ts", "resolved_ts",
                  "value", "reason")
        snap = self.manager.snapshot()
        return {
            "open": [{k: inc.get(k) for k in fields}
                     for inc in snap["open"]],
            "recent": [{k: inc.get(k) for k in fields}
                       for inc in snap["recent"][:max(recent_limit, 0)]],
            "opened_total": snap["opened_total"],
            "resolved_total": snap["resolved_total"],
        }


# -- the process-wide engine --------------------------------------------------

_lock = threading.Lock()
_engine: Optional[IncidentEngine] = None


def get_incident_engine() -> IncidentEngine:
    """The process-wide engine ``serve.server`` installs on the
    sampler."""
    global _engine
    with _lock:
        if _engine is None:
            _engine = IncidentEngine()
        return _engine


def reset_incident_engine() -> None:
    """Drop the process-wide engine (tests). Unhooks it from the
    current sampler and the flight-dump section."""
    global _engine
    with _lock:
        engine = _engine
        _engine = None
    if engine is not None:
        _safe(lambda: engine.uninstall(tsdb_mod.get_sampler()))
        flight.unregister_dump_section("incidents")


__all__ = [
    "CAPTURE_ENV",
    "COOLDOWN_ENV",
    "ENABLED_ENV",
    "Incident",
    "IncidentEngine",
    "IncidentManager",
    "OPEN_AFTER_ENV",
    "RESOLVE_AFTER_ENV",
    "enabled",
    "get_incident_engine",
    "incidents_dir",
    "reset_incident_engine",
]
