"""Flight recorder: diagnostic dumps for hangs, wedges, and crashes.

The r04/r05 outages ("backend init exceeded 60.0s (device tunnel wedged?)")
left nothing but a ``fallback_reason`` string — no stacks, no spans, no
metrics, nothing to attribute the hang with. This module makes every wedge
produce an artifact:

* ``dump(reason, ...)`` writes one JSON file to
  ``SPARK_RAPIDS_ML_TPU_DUMP_DIR`` (default: ``<tmp>/sparkml_dumps``)
  containing all-thread stack traces, the currently-open spans, the last-N
  completed span ring, a metrics-registry snapshot, the cached device
  health verdict (never a fresh probe — probing inside a hang diagnostic
  could itself hang), and process/env context;
* ``deadline(label, budget_seconds)`` is the watchdog: a single daemon
  thread arms a deadline per in-flight phase/fit; the budget expiring (or
  an unhandled exception crossing the context) triggers a dump.
  ``fit_instrumentation`` arms it around every instrumented fit
  (budget: ``SPARK_RAPIDS_ML_TPU_FIT_BUDGET_SECONDS``, default 900), so a
  wedged fit produces a flight dump instead of a silent hang.

Dumping is cheap, never raises into the caller, and a deadline fires at
most once per armed context.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import tempfile
import threading
import time
import traceback
from typing import Any, Dict, Optional

DUMP_DIR_ENV = "SPARK_RAPIDS_ML_TPU_DUMP_DIR"
FIT_BUDGET_ENV = "SPARK_RAPIDS_ML_TPU_FIT_BUDGET_SECONDS"
TRANSFORM_BUDGET_ENV = "SPARK_RAPIDS_ML_TPU_TRANSFORM_BUDGET_SECONDS"
_DEFAULT_FIT_BUDGET = 900.0
# Serving calls are expected to be fast, but the first call through a cold
# model pays the full XLA compile (tens of seconds at scale) — the default
# budget must cover that, not just the steady-state per-batch latency.
_DEFAULT_TRANSFORM_BUDGET = 120.0
_SPAN_RING_TAIL = 128


def dump_dir() -> str:
    return (os.environ.get(DUMP_DIR_ENV)
            or os.path.join(tempfile.gettempdir(), "sparkml_dumps"))


def fit_budget_seconds() -> float:
    try:
        budget = float(os.environ.get(FIT_BUDGET_ENV, _DEFAULT_FIT_BUDGET))
    except ValueError:
        return _DEFAULT_FIT_BUDGET
    return budget if budget > 0 else float("inf")


def transform_budget_seconds() -> float:
    """Watchdog budget for one instrumented transform/predict call
    (``SPARK_RAPIDS_ML_TPU_TRANSFORM_BUDGET_SECONDS``; <= 0 disarms)."""
    try:
        budget = float(os.environ.get(TRANSFORM_BUDGET_ENV,
                                      _DEFAULT_TRANSFORM_BUDGET))
    except ValueError:
        return _DEFAULT_TRANSFORM_BUDGET
    return budget if budget > 0 else float("inf")


def _utcnow() -> str:
    from spark_rapids_ml_tpu.obs.spans import utcnow_iso

    return utcnow_iso()


def _logger():
    from spark_rapids_ml_tpu.obs.logging import get_logger

    return get_logger("obs.flight")


def _thread_stacks() -> Dict[str, Any]:
    """Every live thread's current stack, formatted."""
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = {}
    for ident, frame in sys._current_frames().items():
        label = f"{names.get(ident, 'unknown')}-{ident}"
        stacks[label] = traceback.format_stack(frame)
    return stacks


def _safe(fn, default=None):
    try:
        return fn()
    except Exception:
        return default


# Pluggable dump sections: subsystems outside obs/ (e.g. the serving
# tier's circuit breakers) register a callable whose result is embedded
# in every dump, right after the in-flight trace table — without flight
# having to import them (no obs → serve layering inversion). Section
# functions must be cheap and must never block on the thing being
# diagnosed.
_dump_sections: Dict[str, Any] = {}
_dump_sections_lock = threading.Lock()


def register_dump_section(name: str, fn) -> None:
    """Embed ``fn()``'s result in every future dump under ``name``
    (idempotent — re-registering replaces)."""
    with _dump_sections_lock:
        _dump_sections[name] = fn


def unregister_dump_section(name: str) -> None:
    with _dump_sections_lock:
        _dump_sections.pop(name, None)


def run_dump_section(name: str):
    """Evaluate ONE registered section outside a full dump (None when
    unregistered or the section raised). The incident engine uses this
    to put breaker state into an evidence bundle without an
    obs → serve import."""
    with _dump_sections_lock:
        fn = _dump_sections.get(name)
    if fn is None:
        return None
    return _safe(fn)


def build_dump(reason: str, extra: Optional[Dict[str, Any]] = None
               ) -> Dict[str, Any]:
    """The dump document (separated from I/O so tests can inspect it)."""
    from spark_rapids_ml_tpu.obs import spans as spans_mod

    doc: Dict[str, Any] = {
        "reason": reason,
        "dumped_utc": _utcnow(),
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "thread_stacks": _safe(_thread_stacks, {}),
        "open_spans": _safe(
            lambda: [dict(s) for s in spans_mod.active_spans()], []
        ),
        # The serving tier's in-flight request table: a watchdog dump
        # names WHICH requests (trace ids, models, elapsed) were on the
        # device when the process wedged, not just which threads.
        "active_traces": _safe(_active_traces, []),
    }
    # Registered sections land right here, next to the trace table
    # (breaker events, and whatever future subsystems plug in).
    with _dump_sections_lock:
        sections = list(_dump_sections.items())
    for name, fn in sections:
        doc[name] = _safe(fn)
    doc.update({
        "span_ring_tail": _safe(
            lambda: [
                {"name": e.name, "dur_us": e.dur_us,
                 "trace_id": e.trace_id, "tid": e.tid}
                for e in spans_mod.get_recorder().events()[-_SPAN_RING_TAIL:]
            ],
            [],
        ),
        "metrics": _safe(
            lambda: __import__(
                "spark_rapids_ml_tpu.obs.metrics", fromlist=["get_registry"]
            ).get_registry().snapshot(),
            {},
        ),
        # Cached verdict only: a fresh probe inside a hang diagnostic could
        # itself hang on the wedged backend.
        "device_health_cached": _safe(_cached_health),
        "compile_log_tail": _safe(_compile_tail, []),
        "env": {
            k: v for k, v in os.environ.items()
            if k.startswith(("JAX_", "XLA_", "TPU", "SPARK_RAPIDS_ML_TPU_",
                             "TPUML_"))
        },
    })
    if extra:
        doc["extra"] = extra
    return doc


def _active_traces():
    from spark_rapids_ml_tpu.obs import tracectx

    return tracectx.inflight_requests()


def _cached_health():
    from spark_rapids_ml_tpu.obs import report as report_mod

    return report_mod._health_cache  # cached dict or None; NEVER probes


def _compile_tail():
    from spark_rapids_ml_tpu.obs.xprof import compile_log

    return [ev.as_dict() for ev in compile_log()[-32:]]


def dump(reason: str, extra: Optional[Dict[str, Any]] = None
         ) -> Optional[str]:
    """Write a flight dump; returns the path (None when even writing the
    dump failed — the recorder never raises into a dying caller)."""
    try:
        directory = dump_dir()
        os.makedirs(directory, exist_ok=True)
        safe_reason = "".join(
            c if (c.isalnum() or c in "-_") else "_" for c in reason
        )[:80]
        path = os.path.join(
            directory,
            f"flightdump_{safe_reason}_{int(time.time() * 1000)}"
            f"_{os.getpid()}.json",
        )
        doc = build_dump(reason, extra=extra)
        # atomic publish: consumers watching the dump dir (tests, ops
        # tooling) must never observe a half-written JSON document
        tmp_path = path + ".tmp"
        with open(tmp_path, "w") as f:
            json.dump(doc, f, indent=1, default=str)
        os.replace(tmp_path, path)
        # structured stderr line (obs.logging), not a bare print — a
        # dump notice must be shippable/parseable like every other log.
        # error, the highest level the gate knows: a dump IS an incident
        # artifact, and the pointer to it must survive ANY production
        # log-level threshold (at warning it would vanish under
        # SPARK_RAPIDS_ML_TPU_LOG_LEVEL=error).
        _logger().error("flight dump written", reason=reason,
                        path=path)
        try:
            from spark_rapids_ml_tpu.obs.metrics import get_registry

            get_registry().counter(
                "sparkml_flight_dumps_total", "flight-recorder dumps",
                ("reason",),
            ).inc(reason=reason.split(":", 1)[0])
        except Exception:
            pass
        # shared artifact GC: dumps, profiles, and incident bundles all
        # land under the dump dir — a dump storm must not fill the disk
        try:
            from spark_rapids_ml_tpu.obs import retention

            retention.maybe_gc("flight")
        except Exception:
            pass
        return path
    except Exception:
        return None


# -- the watchdog ----------------------------------------------------------


class _Armed:
    __slots__ = ("label", "deadline", "info", "fired", "on_expire")

    def __init__(self, label: str, deadline: float, info: Dict[str, Any],
                 on_expire=None):
        self.label = label
        self.deadline = deadline
        self.info = info
        self.fired = False
        self.on_expire = on_expire


class Watchdog:
    """One daemon thread monitoring every armed deadline in the process."""

    def __init__(self, poll_floor: float = 0.05):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._armed: Dict[int, _Armed] = {}
        self._next_id = 0
        self._thread: Optional[threading.Thread] = None
        self._poll_floor = poll_floor

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="sparkml-flight-watchdog", daemon=True
            )
            self._thread.start()

    def arm(self, label: str, budget_seconds: float,
            info: Optional[Dict[str, Any]] = None,
            on_expire=None) -> int:
        """Arm one deadline. ``on_expire`` (optional) runs on the
        watchdog thread when the budget blows, BEFORE the dump — the
        hook the serving tier uses to fail a wedged worker's requests
        fast. It must be quick, non-blocking, and is exception-guarded
        (a broken callback never kills the watchdog)."""
        with self._cond:
            handle = self._next_id
            self._next_id += 1
            self._armed[handle] = _Armed(
                label, time.monotonic() + budget_seconds, dict(info or {}),
                on_expire=on_expire,
            )
            self._ensure_thread()
            self._cond.notify()
        return handle

    def disarm(self, handle: int) -> None:
        with self._cond:
            self._armed.pop(handle, None)
            self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                now = time.monotonic()
                expired = [a for a in self._armed.values()
                           if not a.fired and a.deadline <= now]
                for a in expired:
                    a.fired = True
                pending = [a.deadline for a in self._armed.values()
                           if not a.fired]
                wait = (max(min(pending) - now, self._poll_floor)
                        if pending else None)
            for a in expired:
                if a.on_expire is not None:
                    _safe(a.on_expire)
                dump(
                    f"budget_exceeded:{a.label}",
                    extra={
                        "label": a.label,
                        "budget_info": a.info,
                        "overdue_at_utc": _utcnow(),
                    },
                )
            with self._cond:
                self._cond.wait(timeout=wait)


_watchdog = Watchdog()


def get_watchdog() -> Watchdog:
    return _watchdog


# Fast-fail errors (bad k, wrong shape, a refused source...) are expected
# control flow, not flight events. An exception dumps when it is a hard
# runtime/backend failure, or when the block had already been running long
# enough that its state is worth capturing.
_HARD_ERRORS = (OSError, TimeoutError, MemoryError, SystemError,
                ConnectionError)
_DUMP_AFTER_SECONDS = 5.0


def _should_dump_exception(exc: BaseException, elapsed: float) -> bool:
    if elapsed >= _DUMP_AFTER_SECONDS:
        return True
    if isinstance(exc, _HARD_ERRORS):
        return True
    name = type(exc).__name__
    return "XlaRuntimeError" in name or "Unavailable" in name


@contextlib.contextmanager
def deadline(label: str, budget_seconds: Optional[float] = None, **info):
    """Arm the watchdog around a block: the budget expiring dumps
    ``budget_exceeded:<label>``; a hard (or long-running) exception
    crossing the context dumps ``unhandled_exception:<label>`` (then
    re-raises). Budget None/inf arms nothing but still dumps on such
    exceptions."""
    budget = fit_budget_seconds() if budget_seconds is None else budget_seconds
    handle = None
    if budget and budget != float("inf"):
        handle = _watchdog.arm(label, budget, info)
    t0 = time.monotonic()
    try:
        yield
    except Exception as exc:
        elapsed = time.monotonic() - t0
        if _should_dump_exception(exc, elapsed):
            dump(
                f"unhandled_exception:{label}",
                extra={
                    "label": label,
                    "error": f"{type(exc).__name__}: {exc}",
                    "elapsed_seconds": elapsed,
                    "budget_info": dict(info),
                },
            )
        raise
    finally:
        if handle is not None:
            _watchdog.disarm(handle)
