"""Fit-path observability plane: step monitor, MFU/roofline attribution,
collective skew, and a backend-health watchdog.

The serving tier is saturated with telemetry; the fit side — the
distributed covariance/eigh paths the paper is about — ran dark. This
module is the fit half of the observability contract:

* ``FitRun`` / ``StepMonitor`` — a context distributed fits enter (PCA
  covariance passes, KMeans Lloyd iterations, logreg Newton epochs,
  streaming accumulator folds). Every step records wall time, device
  time (the same measured duration ``obs.devmon`` meters, so the two
  planes reconcile by construction), rows/sec, and convergence scalars
  as ``sparkml_fit_*`` TSDB series plus ``fit:step:*`` spans that land
  in the existing Chrome-trace export.
* MFU/roofline attribution — the cost-analysis FLOPs/bytes
  ``obs.xprof.TrackedJit`` already captures per compiled signature,
  divided by the step's measured device time against the per-device-kind
  peak tables in ``utils.platform``. Arithmetic intensity against the
  ridge point classifies each step compute-bound vs memory-bound.
  Unknown device kinds (CPU included) degrade to *absent* — never a
  made-up peak.
* Per-host skew — ``note_host_step`` collects per-host step timings from
  the ``parallel/multihost.py`` seams; ``detect_stragglers`` flags a
  host whose mean step time exceeds the fleet median by a configurable
  ratio (``SPARK_RAPIDS_ML_TPU_FITMON_STRAGGLER_RATIO``, default 1.5).
* ``BackendWatchdog`` — samples the resolved JAX platform, device
  count, and a tiny canary dispatch at bounded cadence, publishing
  ``sparkml_fit_backend_ok``. The ``fit_backend_degraded`` builtin
  ThresholdDetector (obs.anomaly) raises exactly one auto-resolving
  incident when the platform silently differs from the configured
  expectation (``SPARK_RAPIDS_ML_TPU_FITMON_EXPECT_PLATFORM``) or the
  canary wedges — the live fix for the r04 tunnel failure, which every
  bench round after discovered only post-hoc.

Surfaces: ``GET /debug/fit`` (serve/server.py), dashboard tiles, and the
``fit_report()`` rollup. Telemetry never raises into a fit; every
public entry point is exception-guarded. Clocks are injectable
(``clock: Callable = time.time`` default-reference only — rule 8 in
``scripts/check_instrumentation.py`` enforces the discipline for this
file); ``time.perf_counter`` is used for durations.

Knobs (env): SPARK_RAPIDS_ML_TPU_FITMON (default 1),
SPARK_RAPIDS_ML_TPU_FITMON_HISTORY (32 recent runs),
SPARK_RAPIDS_ML_TPU_FITMON_MAX_STEPS (256 step rows kept per run —
totals keep counting past the cap),
SPARK_RAPIDS_ML_TPU_FITMON_STRAGGLER_RATIO (1.5),
SPARK_RAPIDS_ML_TPU_FITMON_EXPECT_PLATFORM (unset = no expectation),
SPARK_RAPIDS_ML_TPU_FITMON_WATCHDOG_S (30),
SPARK_RAPIDS_ML_TPU_FITMON_CANARY_TIMEOUT_S (5),
SPARK_RAPIDS_ML_TPU_FITMON_PEAK_FLOPS / _PEAK_BW (override the
per-device-kind peak table — the extension seam for unlisted kinds).
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from spark_rapids_ml_tpu.obs.metrics import get_registry

INCIDENT_NAME = "fit_backend_degraded"
BACKEND_OK_METRIC = "sparkml_fit_backend_ok"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


# -- pure roofline/skew math (unit-testable, no jax) ------------------------


def step_mfu(flops: Optional[float], device_seconds: Optional[float],
             peak_flops: Optional[float]) -> Optional[float]:
    """FLOPs over device time over the chip peak; None when any input is
    unknown — an unknown device kind must never produce a fake MFU."""
    if not flops or not device_seconds or device_seconds <= 0:
        return None
    if not peak_flops:
        return None
    return flops / device_seconds / peak_flops


def roofline_bound(flops: Optional[float], nbytes: Optional[float],
                   peak_flops: Optional[float],
                   peak_bw: Optional[float]) -> Optional[str]:
    """``"compute"`` or ``"memory"`` from arithmetic intensity vs the
    ridge point ``peak_flops / peak_bw``; None when any side is unknown."""
    if not flops or not nbytes or not peak_flops or not peak_bw:
        return None
    intensity = flops / nbytes
    ridge = peak_flops / peak_bw
    return "compute" if intensity >= ridge else "memory"


def detect_stragglers(host_seconds: Dict[str, float],
                      ratio: float = 1.5) -> Dict[str, Any]:
    """Flag hosts whose mean step time exceeds the fleet median by
    ``ratio``. Pure: feed it synthetic timings in tests. A single-host
    fleet has no median to diverge from — never flagged."""
    hosts = {str(h): float(s) for h, s in host_seconds.items()}
    out: Dict[str, Any] = {
        "hosts": hosts, "ratio": float(ratio),
        "median_seconds": None, "stragglers": [],
    }
    if len(hosts) < 2:
        return out
    ordered = sorted(hosts.values())
    mid = len(ordered) // 2
    if len(ordered) % 2:
        median = ordered[mid]
    else:
        median = (ordered[mid - 1] + ordered[mid]) / 2.0
    out["median_seconds"] = median
    if median > 0:
        out["stragglers"] = sorted(
            h for h, s in hosts.items() if s > ratio * median
        )
    return out


def device_peaks() -> Tuple[Optional[float], Optional[float]]:
    """(peak FLOP/s, peak HBM bytes/s) for this process's device kind, or
    (None, None) when unknown (CPU included).

    ``SPARK_RAPIDS_ML_TPU_FITMON_PEAK_FLOPS`` /
    ``SPARK_RAPIDS_ML_TPU_FITMON_PEAK_BW`` override the table — the
    extension seam for device kinds the table does not list yet (and how
    CPU-only drills get a deterministic MFU to assert against)."""
    flops_env = os.environ.get("SPARK_RAPIDS_ML_TPU_FITMON_PEAK_FLOPS")
    bw_env = os.environ.get("SPARK_RAPIDS_ML_TPU_FITMON_PEAK_BW")
    if flops_env or bw_env:
        try:
            return (float(flops_env) if flops_env else None,
                    float(bw_env) if bw_env else None)
        except ValueError:
            pass  # malformed override: fall through to the table
    try:
        import jax

        from spark_rapids_ml_tpu.utils.platform import (
            PEAK_FLOPS_BF16,
            PEAK_HBM_BYTES_PER_SECOND,
        )

        device = jax.devices()[0]
        if device.platform == "cpu":
            return None, None
        kind = str(device.device_kind)
        return (PEAK_FLOPS_BF16.get(kind),
                PEAK_HBM_BYTES_PER_SECOND.get(kind))
    except Exception:
        return None, None


# -- step / run -------------------------------------------------------------


class StepMonitor:
    """One host-visible fit step (a blocked kernel pass, a streaming
    fold). ``with run.step("lloyd", rows=n) as step:`` measures wall
    time around the block; device time defaults to that measured wall
    (the step wraps the blocked dispatch) unless the driver passes a
    tighter measurement via ``set_device_seconds``. The ONE measured
    duration also feeds ``devmon.note_batch`` so fitmon and devmon
    device-seconds agree by construction."""

    __slots__ = ("_run", "name", "rows", "scalars", "_t0", "_flops0",
                 "_bytes0", "_device_seconds", "_token", "started_unix")

    def __init__(self, run: "FitRun", name: str,
                 rows: Optional[int] = None):
        self._run = run
        self.name = name
        self.rows = int(rows) if rows is not None else None
        self.scalars: Dict[str, float] = {}
        self._t0 = 0.0
        self._flops0 = 0.0
        self._bytes0 = 0.0
        self._device_seconds: Optional[float] = None
        self._token = None
        self.started_unix: Optional[float] = None

    def note(self, **scalars) -> None:
        """Record convergence scalars (n_iter, cost, grad_norm, ...)
        observed inside the step. Non-numeric values are dropped."""
        for key, value in scalars.items():
            try:
                self.scalars[key] = float(value)
            except (TypeError, ValueError):
                pass

    def set_device_seconds(self, seconds: float) -> None:
        """Override the device-time attribution for this step (a driver
        that timed the dispatch more tightly than the step block)."""
        try:
            self._device_seconds = max(float(seconds), 0.0)
        except (TypeError, ValueError):
            pass

    def __enter__(self) -> "StepMonitor":
        try:
            self._token = _current_run.set(self._run)
            self.started_unix = self._run._clock()
            with self._run._lock:
                self._flops0 = self._run.flops_total
                self._bytes0 = self._run.bytes_total
        except Exception:
            pass
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        t1 = time.perf_counter()
        try:
            self._run._finish_step(self, self._t0, t1,
                                   failed=exc_type is not None)
        except Exception:
            pass  # telemetry must never break a fit
        finally:
            if self._token is not None:
                try:
                    _current_run.reset(self._token)
                except Exception:
                    pass


class _NullStep:
    """Inert step: fitmon disabled or no active run."""

    __slots__ = ()

    def note(self, **scalars) -> None:
        pass

    def set_device_seconds(self, seconds: float) -> None:
        pass

    def __enter__(self) -> "_NullStep":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


class FitRun:
    """One distributed fit (or one streaming-training stretch) under the
    monitor: a bounded step table plus running totals, per-host skew, and
    program-level FLOPs/bytes fed by ``obs.xprof.TrackedJit``."""

    def __init__(self, monitor: "FitMonitor", run_id: str, algo: str,
                 trace_id: Optional[str] = None):
        self._monitor = monitor
        self._clock = monitor._clock
        self._lock = threading.Lock()
        self.run_id = run_id
        self.algo = algo
        self.trace_id = trace_id
        self.started_unix = self._clock()
        self.finished_unix: Optional[float] = None
        self.status = "active"
        self.steps: collections.deque = collections.deque(
            maxlen=monitor.max_steps)
        self.steps_total = 0
        self.steps_failed = 0
        self.wall_seconds_total = 0.0
        self.device_seconds_total = 0.0
        self.rows_total = 0
        self.flops_total = 0.0
        self.bytes_total = 0.0
        self.host_seconds: Dict[str, List[float]] = {}
        self.collectives: Dict[str, Dict[str, float]] = {}
        self.extra: Dict[str, Any] = {}
        self.report: Optional[Dict[str, Any]] = None

    @property
    def active(self) -> bool:
        return self.status == "active"

    # -- recording seams ---------------------------------------------------

    def step(self, name: str, rows: Optional[int] = None):
        """A context manager timing one host-visible step."""
        if not self._monitor.enabled:
            return _NULL_STEP
        return StepMonitor(self, name, rows=rows)

    def record_program(self, label: str, flops: Optional[float],
                       nbytes: Optional[float]) -> None:
        """Called by ``obs.xprof`` on every tracked-program execution
        while this run is current."""
        with self._lock:
            if flops:
                self.flops_total += float(flops)
            if nbytes:
                self.bytes_total += float(nbytes)

    def note_host_step(self, host, seconds: float) -> None:
        """One host's contribution to a step (the multihost placement /
        collective seams) — the skew/straggler input. Never raises."""
        try:
            key = str(host)
            value = max(float(seconds), 0.0)
            with self._lock:
                bucket = self.host_seconds.setdefault(key, [])
                bucket.append(value)
                if len(bucket) > 512:
                    del bucket[0]
            self._monitor._m_host_seconds.inc(
                value, algo=self.algo, host=key)
        except Exception:
            pass

    def record_collective(self, kind: str, *, nbytes: int = 0,
                          count: int = 1,
                          seconds: Optional[float] = None) -> None:
        """Comms accounting visible in ``/debug/fit`` (the FitContext in
        obs.report keeps the per-report ledger; this one is live)."""
        try:
            with self._lock:
                entry = self.collectives.setdefault(
                    kind, {"count": 0, "bytes": 0, "seconds": 0.0})
                entry["count"] += int(count)
                entry["bytes"] += int(nbytes) * int(count)
                if seconds:
                    entry["seconds"] += float(seconds)
        except Exception:
            pass

    def note(self, **kwargs) -> None:
        try:
            with self._lock:
                self.extra.update(kwargs)
        except Exception:
            pass

    # -- step completion (called by StepMonitor.__exit__) ------------------

    def _finish_step(self, step: StepMonitor, t0: float, t1: float, *,
                     failed: bool = False) -> None:
        over0 = time.perf_counter()
        wall = max(t1 - t0, 0.0)
        device = step._device_seconds if step._device_seconds is not None \
            else wall
        with self._lock:
            flops = self.flops_total - step._flops0
            nbytes = self.bytes_total - step._bytes0
            index = self.steps_total
            self.steps_total += 1
            if failed:
                self.steps_failed += 1
            self.wall_seconds_total += wall
            self.device_seconds_total += device
            if step.rows:
                self.rows_total += step.rows
        peak_flops, peak_bw = self._monitor.peaks()
        mfu = step_mfu(flops, device, peak_flops)
        bound = roofline_bound(flops, nbytes, peak_flops, peak_bw)
        rows_per_sec = (step.rows / wall
                        if step.rows and wall > 0 else None)
        record: Dict[str, Any] = {
            "index": index,
            "step": step.name,
            "started_unix": step.started_unix,
            "wall_seconds": wall,
            "device_seconds": device,
            "rows": step.rows,
            "rows_per_sec": rows_per_sec,
            "flops": flops or None,
            "bytes_accessed": nbytes or None,
            "mfu": mfu,
            "bound": bound,
            "failed": failed,
            "scalars": dict(step.scalars),
        }
        with self._lock:
            self.steps.append(record)
        self._monitor._publish_step(self, record, t0, t1)
        try:
            self._monitor._m_overhead.inc(
                time.perf_counter() - over0, component="fitmon")
        except Exception:
            pass

    # -- views -------------------------------------------------------------

    def skew(self, ratio: Optional[float] = None) -> Dict[str, Any]:
        """Per-host mean step seconds + straggler verdict."""
        with self._lock:
            means = {h: sum(v) / len(v)
                     for h, v in self.host_seconds.items() if v}
        return detect_stragglers(
            means, ratio if ratio is not None
            else self._monitor.straggler_ratio)

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            mfus = [s["mfu"] for s in self.steps if s["mfu"] is not None]
            last_scalars = (dict(self.steps[-1]["scalars"])
                            if self.steps else {})
            doc = {
                "run_id": self.run_id,
                "algo": self.algo,
                "trace_id": self.trace_id,
                "status": self.status,
                "started_unix": self.started_unix,
                "finished_unix": self.finished_unix,
                "steps": self.steps_total,
                "steps_failed": self.steps_failed,
                "wall_seconds": self.wall_seconds_total,
                "device_seconds": self.device_seconds_total,
                "rows": self.rows_total,
                "rows_per_sec": (
                    self.rows_total / self.wall_seconds_total
                    if self.rows_total and self.wall_seconds_total > 0
                    else None),
                "flops": self.flops_total or None,
                "bytes_accessed": self.bytes_total or None,
                "mfu_mean": sum(mfus) / len(mfus) if mfus else None,
                "last_scalars": last_scalars,
            }
        skew = self.skew()
        if skew["hosts"]:
            doc["stragglers"] = skew["stragglers"]
        return doc

    def as_dict(self) -> Dict[str, Any]:
        doc = self.summary()
        with self._lock:
            doc["step_table"] = list(self.steps)
            doc["collectives"] = {k: dict(v)
                                  for k, v in self.collectives.items()}
            doc["extra"] = dict(self.extra)
            if self.report is not None:
                doc["report"] = self.report
        doc["skew"] = self.skew()
        return doc


class _NullFitRun:
    """No-op run: lets seams call ``current_run().step(...)``
    unconditionally outside any monitored fit (or with fitmon off)."""

    run_id = None
    algo = "_unmonitored"
    trace_id = None
    status = "inactive"
    active = False

    def step(self, name: str, rows: Optional[int] = None) -> _NullStep:
        return _NULL_STEP

    def record_program(self, *args, **kwargs) -> None:
        pass

    def note_host_step(self, *args, **kwargs) -> None:
        pass

    def record_collective(self, *args, **kwargs) -> None:
        pass

    def note(self, **kwargs) -> None:
        pass

    def skew(self, ratio: Optional[float] = None) -> Dict[str, Any]:
        return detect_stragglers({})

    def summary(self) -> Dict[str, Any]:
        return {}

    def as_dict(self) -> Dict[str, Any]:
        return {}


_NULL_STEP = _NullStep()
_NULL_RUN = _NullFitRun()
_current_run: contextvars.ContextVar = contextvars.ContextVar(
    "sparkml_fitmon_run", default=None
)


# -- backend-health watchdog ------------------------------------------------


def _default_devices() -> List[Any]:
    import jax

    return list(jax.devices())


def _default_canary() -> None:
    """A tiny real dispatch: if the resolved backend's tunnel is wedged
    (the r04 failure), this call never returns — the bounded join below
    is what turns that hang into a verdict."""
    import jax.numpy as jnp

    jnp.zeros((8,), jnp.float32).sum().block_until_ready()


class BackendWatchdog:
    """Samples the resolved JAX backend at bounded cadence and publishes
    ``sparkml_fit_backend_ok`` (1 healthy / 0 degraded). Degraded means:
    the resolved platform differs from the configured expectation
    (``SPARK_RAPIDS_ML_TPU_FITMON_EXPECT_PLATFORM``), zero devices, the
    canary dispatch raises, or the canary wedges past its bounded join.
    The builtin ``fit_backend_degraded`` ThresholdDetector turns a 0
    reading into exactly one auto-resolving incident."""

    def __init__(self, *,
                 expected_platform: Optional[str] = None,
                 interval_s: Optional[float] = None,
                 canary_timeout_s: Optional[float] = None,
                 clock: Callable[[], float] = time.time,
                 devices_fn: Callable[[], List[Any]] = _default_devices,
                 canary_fn: Callable[[], None] = _default_canary):
        self.expected_platform = (
            expected_platform
            if expected_platform is not None
            else os.environ.get(
                "SPARK_RAPIDS_ML_TPU_FITMON_EXPECT_PLATFORM") or None)
        self.interval_s = (
            interval_s if interval_s is not None
            else _env_float("SPARK_RAPIDS_ML_TPU_FITMON_WATCHDOG_S", 30.0))
        self.canary_timeout_s = (
            canary_timeout_s if canary_timeout_s is not None
            else _env_float(
                "SPARK_RAPIDS_ML_TPU_FITMON_CANARY_TIMEOUT_S", 5.0))
        self._clock = clock
        self._devices_fn = devices_fn
        self._canary_fn = canary_fn
        self._lock = threading.Lock()
        self._last_checked: Optional[float] = None
        self._last_verdict: Optional[Dict[str, Any]] = None
        self.checks = 0
        self._m_ok = get_registry().gauge(
            BACKEND_OK_METRIC,
            "fit-backend health verdict from the fitmon watchdog "
            "(1 healthy, 0 degraded — platform mismatch, no devices, "
            "canary error, or canary wedge)", ())

    def last_verdict(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self._last_verdict) if self._last_verdict else None

    def maybe_check(self, now: Optional[float] = None
                    ) -> Optional[Dict[str, Any]]:
        """Run a check if the cadence allows; otherwise return the last
        verdict. The sampler calls this every sweep — the interval here
        is what makes the canary's cost bounded."""
        if now is None:
            now = self._clock()
        with self._lock:
            due = (self._last_checked is None
                   or now - self._last_checked >= self.interval_s)
            if not due:
                return (dict(self._last_verdict)
                        if self._last_verdict else None)
        return self.check(now)

    def check(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One unconditional health check. Never raises."""
        if now is None:
            now = self._clock()
        verdict: Dict[str, Any] = {
            "ok": True, "reason": None, "checked_unix": now,
            "platform": None, "device_kind": None, "device_count": 0,
            "expected_platform": self.expected_platform,
            "canary": "skipped", "canary_seconds": None,
        }
        try:
            devices = self._devices_fn()
        except Exception as exc:  # backend init itself broken
            devices = []
            verdict["ok"] = False
            verdict["reason"] = "backend_error"
            verdict["error"] = repr(exc)
        if devices:
            verdict["platform"] = str(devices[0].platform)
            verdict["device_kind"] = str(devices[0].device_kind)
            verdict["device_count"] = len(devices)
        elif verdict["reason"] is None:
            verdict["ok"] = False
            verdict["reason"] = "no_devices"
        if (verdict["ok"] and self.expected_platform
                and verdict["platform"] != self.expected_platform):
            verdict["ok"] = False
            verdict["reason"] = "platform_mismatch"
        if verdict["ok"] and devices:
            verdict.update(self._run_canary())
            if verdict["canary"] == "wedged":
                verdict["ok"] = False
                verdict["reason"] = "canary_wedged"
            elif verdict["canary"] == "error":
                verdict["ok"] = False
                verdict["reason"] = "canary_error"
        try:
            self._m_ok.set(1.0 if verdict["ok"] else 0.0)
        except Exception:
            pass
        with self._lock:
            self._last_checked = now
            self._last_verdict = verdict
            self.checks += 1
        return dict(verdict)

    def _run_canary(self) -> Dict[str, Any]:
        """The canary dispatch on a helper thread with a bounded join —
        a wedged device tunnel hangs the thread, not the watchdog."""
        outcome: Dict[str, Any] = {"canary": "ok", "canary_seconds": None}
        box: Dict[str, Any] = {}

        def _work() -> None:
            try:
                self._canary_fn()
                box["ok"] = True
            except Exception as exc:
                box["error"] = repr(exc)

        t0 = time.perf_counter()
        worker = threading.Thread(
            target=_work, name="fitmon-canary", daemon=True)
        try:
            worker.start()
            worker.join(self.canary_timeout_s)
        except Exception:
            outcome["canary"] = "error"
            return outcome
        outcome["canary_seconds"] = time.perf_counter() - t0
        if worker.is_alive():
            outcome["canary"] = "wedged"
        elif "error" in box:
            outcome["canary"] = "error"
            outcome["canary_error"] = box["error"]
        return outcome


# -- the monitor ------------------------------------------------------------


class FitMonitor:
    """Process-wide fit-path monitor: active runs, bounded run history,
    the device-peak cache, and the backend watchdog."""

    def __init__(self, *, enabled: Optional[bool] = None,
                 clock: Callable[[], float] = time.time,
                 peaks_fn: Callable[
                     [], Tuple[Optional[float], Optional[float]]
                 ] = device_peaks,
                 watchdog: Optional[BackendWatchdog] = None):
        if enabled is None:
            enabled = os.environ.get(
                "SPARK_RAPIDS_ML_TPU_FITMON", "1") not in ("0", "false", "")
        self.enabled = bool(enabled)
        self._clock = clock
        self._peaks_fn = peaks_fn
        self._peaks: Optional[
            Tuple[Optional[float], Optional[float]]] = None
        self._lock = threading.Lock()
        self._seq = 0
        self._active: Dict[str, FitRun] = {}
        self._recent: collections.deque = collections.deque(
            maxlen=_env_int("SPARK_RAPIDS_ML_TPU_FITMON_HISTORY", 32))
        self.max_steps = _env_int(
            "SPARK_RAPIDS_ML_TPU_FITMON_MAX_STEPS", 256)
        self.straggler_ratio = _env_float(
            "SPARK_RAPIDS_ML_TPU_FITMON_STRAGGLER_RATIO", 1.5)
        self.watchdog = watchdog if watchdog is not None \
            else BackendWatchdog(clock=clock)
        reg = get_registry()
        self._m_runs = reg.counter(
            "sparkml_fit_runs_total", "monitored fit runs", ("algo",))
        self._m_steps = reg.counter(
            "sparkml_fit_steps_total", "monitored fit steps",
            ("algo", "step"))
        self._m_step_seconds = reg.counter(
            "sparkml_fit_step_seconds_total",
            "wall-clock inside monitored fit steps", ("algo", "step"))
        self._m_device_seconds = reg.counter(
            "sparkml_fit_device_seconds_total",
            "device time attributed to monitored fit steps — the same "
            "measured duration devmon meters, so the planes reconcile",
            ("algo", "step"))
        self._m_rows = reg.counter(
            "sparkml_fit_rows_total", "rows processed by monitored steps",
            ("algo",))
        self._m_rows_per_sec = reg.gauge(
            "sparkml_fit_rows_per_sec",
            "latest per-step fit throughput", ("algo", "step"))
        self._m_mfu = reg.gauge(
            "sparkml_fit_mfu",
            "latest per-step analytic MFU (absent on unknown device "
            "kinds)", ("algo", "step"))
        self._m_convergence = reg.gauge(
            "sparkml_fit_convergence",
            "latest per-step convergence scalars (n_iter, cost, ...)",
            ("algo", "step", "scalar"))
        self._m_host_seconds = reg.counter(
            "sparkml_fit_host_step_seconds_total",
            "per-host step seconds from the multihost seams — the "
            "skew/straggler input", ("algo", "host"))
        self._m_overhead = reg.counter(
            "sparkml_obs_overhead_seconds_total",
            "wall-clock the observability layer spends watching "
            "(sampler sweeps, device monitor, profiler bookkeeping)",
            ("component",))

    # -- peaks -------------------------------------------------------------

    def peaks(self) -> Tuple[Optional[float], Optional[float]]:
        """(peak FLOP/s, peak HBM bytes/s), resolved once per process —
        the device kind cannot change under a live backend."""
        if self._peaks is None:
            try:
                self._peaks = self._peaks_fn()
            except Exception:
                self._peaks = (None, None)
        return self._peaks

    # -- run lifecycle -----------------------------------------------------

    def start_run(self, algo: str,
                  trace_id: Optional[str] = None) -> FitRun:
        with self._lock:
            self._seq += 1
            run_id = f"fit-{self._seq}"
        run = FitRun(self, run_id, algo, trace_id=trace_id)
        with self._lock:
            self._active[run_id] = run
        try:
            self._m_runs.inc(algo=algo)
        except Exception:
            pass
        return run

    def finish_run(self, run: FitRun,
                   report: Optional[Dict[str, Any]] = None) -> None:
        try:
            run.status = "done"
            run.finished_unix = self._clock()
            if report is not None:
                run.report = report
            with self._lock:
                self._active.pop(run.run_id, None)
                self._recent.appendleft(run)
        except Exception:
            pass

    def active_runs(self) -> List[FitRun]:
        with self._lock:
            return list(self._active.values())

    def recent_runs(self) -> List[FitRun]:
        with self._lock:
            return list(self._recent)

    def latest_active_run_id(self) -> Optional[str]:
        """The most recently started still-active run (what a profiler
        capture taken right now is covering)."""
        with self._lock:
            if not self._active:
                return None
            return max(self._active.values(),
                       key=lambda r: r.started_unix).run_id

    def find_run(self, run_id: str) -> Optional[FitRun]:
        with self._lock:
            run = self._active.get(run_id)
            if run is not None:
                return run
            for r in self._recent:
                if r.run_id == run_id:
                    return r
        return None

    # -- step publication (called by FitRun._finish_step) ------------------

    def _publish_step(self, run: FitRun, record: Dict[str, Any],
                      t0: float, t1: float) -> None:
        algo, step = run.algo, record["step"]
        try:
            self._m_steps.inc(algo=algo, step=step)
            self._m_step_seconds.inc(
                record["wall_seconds"], algo=algo, step=step)
            self._m_device_seconds.inc(
                record["device_seconds"], algo=algo, step=step)
            if record["rows"]:
                self._m_rows.inc(record["rows"], algo=algo)
            if record["rows_per_sec"] is not None:
                self._m_rows_per_sec.set(
                    record["rows_per_sec"], algo=algo, step=step)
            if record["mfu"] is not None:
                self._m_mfu.set(record["mfu"], algo=algo, step=step)
            for name, value in record["scalars"].items():
                self._m_convergence.set(
                    value, algo=algo, step=step, scalar=name)
        except Exception:
            pass
        # the ONE measured device duration also feeds devmon, so
        # per-fit device occupancy shows up beside serving occupancy
        # and the two planes reconcile by construction
        try:
            from spark_rapids_ml_tpu.obs import devmon

            devmon.get_device_monitor().note_batch(
                f"fit:{algo}", record["device_seconds"])
        except Exception:
            pass
        try:
            from spark_rapids_ml_tpu.obs import spans

            spans.record_event(
                f"fit:step:{algo}:{step}", t0, t1,
                trace_id=run.trace_id,
                run_id=run.run_id,
                rows=record["rows"],
                device_seconds=record["device_seconds"],
                mfu=record["mfu"],
                **record["scalars"],
            )
        except Exception:
            pass

    # -- watchdog collector (registered by obs.tsdb.start_sampling) --------

    def watchdog_collector(self) -> List[Dict[str, Any]]:
        """Sampler-sweep hook: runs the watchdog at ITS bounded cadence
        (the sampler sweeps much faster). Skips while a profiler
        start/stop transition is in flight — same contract as devmon."""
        t0 = time.perf_counter()
        try:
            from spark_rapids_ml_tpu.obs import profiler

            if profiler.jax_transition_pending():
                return []
        except Exception:
            pass
        try:
            verdict = self.watchdog.maybe_check()
        except Exception:
            return []
        try:
            self._m_overhead.inc(time.perf_counter() - t0,
                                 component="fitmon_watchdog")
        except Exception:
            pass
        return [verdict] if verdict else []

    # -- rollups -----------------------------------------------------------

    def fit_report(self) -> Dict[str, Any]:
        """Per-algo rollup over every run the monitor still remembers."""
        algos: Dict[str, Dict[str, Any]] = {}
        for run in self.active_runs() + self.recent_runs():
            s = run.summary()
            doc = algos.setdefault(run.algo, {
                "runs": 0, "active": 0, "steps": 0, "rows": 0,
                "wall_seconds": 0.0, "device_seconds": 0.0,
                "mfu_mean": None, "_mfus": [],
                "last_run": None,
            })
            doc["runs"] += 1
            if run.active:
                doc["active"] += 1
            doc["steps"] += s.get("steps", 0)
            doc["rows"] += s.get("rows", 0)
            doc["wall_seconds"] += s.get("wall_seconds", 0.0)
            doc["device_seconds"] += s.get("device_seconds", 0.0)
            if s.get("mfu_mean") is not None:
                doc["_mfus"].append(s["mfu_mean"])
            if doc["last_run"] is None:
                doc["last_run"] = s
        for doc in algos.values():
            mfus = doc.pop("_mfus")
            if mfus:
                doc["mfu_mean"] = sum(mfus) / len(mfus)
        return {"algos": algos, "enabled": self.enabled}

    def debug_doc(self) -> Dict[str, Any]:
        """The ``GET /debug/fit`` document."""
        peak_flops, peak_bw = self.peaks()
        return {
            "enabled": self.enabled,
            "active": [r.as_dict() for r in self.active_runs()],
            "recent": [r.summary() for r in self.recent_runs()],
            "rollup": self.fit_report()["algos"],
            "watchdog": self.watchdog.last_verdict(),
            "straggler_ratio": self.straggler_ratio,
            "peaks": {
                "flops_per_second": peak_flops,
                "hbm_bytes_per_second": peak_bw,
            },
        }


# -- module-level singletons / entry points ---------------------------------


_monitor: Optional[FitMonitor] = None
_monitor_lock = threading.Lock()


def get_fit_monitor() -> FitMonitor:
    global _monitor
    with _monitor_lock:
        if _monitor is None:
            _monitor = FitMonitor()
        return _monitor


def reset_fitmon() -> None:
    """Drop the cached monitor (tests that reset the registry)."""
    global _monitor
    with _monitor_lock:
        _monitor = None


def current_run():
    """The active ``FitRun`` in this context, or an inert null run —
    seams call ``current_run().step(...)`` unconditionally."""
    run = _current_run.get()
    if run is None or not run.active:
        return _NULL_RUN
    return run


@contextlib.contextmanager
def fit_run(algo: str, trace_id: Optional[str] = None):
    """Enter one monitored fit run. With fitmon disabled this yields the
    inert null run at near-zero cost. Monitor bookkeeping never raises
    into the fit."""
    monitor = None
    run = None
    try:
        monitor = get_fit_monitor()
        if monitor.enabled:
            run = monitor.start_run(algo, trace_id=trace_id)
    except Exception:
        run = None
    if run is None:
        yield _NULL_RUN
        return
    token = _current_run.set(run)
    try:
        yield run
    finally:
        try:
            _current_run.reset(token)
        except Exception:
            pass
        try:
            monitor.finish_run(run)
        except Exception:
            pass


def record_program(label: str, flops: Optional[float],
                   nbytes: Optional[float]) -> None:
    """The ``obs.xprof`` seam: attribute one tracked-program execution's
    cost-analysis FLOPs/bytes to the current run (no-op outside one)."""
    run = _current_run.get()
    if run is not None and run.active:
        run.record_program(label, flops, nbytes)


def fit_report() -> Dict[str, Any]:
    """Per-algo rollup over the monitor's remembered runs."""
    return get_fit_monitor().fit_report()


def debug_fit_doc() -> Dict[str, Any]:
    """The ``GET /debug/fit`` document (serve/server.py)."""
    return get_fit_monitor().debug_doc()


__all__ = [
    "BACKEND_OK_METRIC",
    "BackendWatchdog",
    "FitMonitor",
    "FitRun",
    "INCIDENT_NAME",
    "StepMonitor",
    "current_run",
    "debug_fit_doc",
    "detect_stragglers",
    "device_peaks",
    "fit_report",
    "fit_run",
    "get_fit_monitor",
    "record_program",
    "reset_fitmon",
    "roofline_bound",
    "step_mfu",
]
