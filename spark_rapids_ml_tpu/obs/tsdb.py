"""Embedded in-process time-series store: the metrics registry's memory.

Every telemetry surface built so far (registry, SLO engine, dashboard)
is instantaneous — a point-in-time snapshot with no history, so an
operator cannot see a burn-rate ramp, a queue-depth trend, or what
device time looked like five minutes before a flight dump. This module
adds the time dimension without adding a database:

* ``TimeSeriesStore`` — bounded per-series rings with coarse downsample
  tiers (default ``1 s × 5 m`` and ``10 s × 1 h``; env
  ``SPARK_RAPIDS_ML_TPU_OBS_HISTORY="1x300,10x3600"``). Each tier keeps
  the LAST sample per resolution bucket — exact for counters (rate and
  delta read cumulative values), the usual sampling semantics for
  gauges. Memory is fixed at construction: ``series × Σ(span/res)``
  points, full stop.
* ``range_query(name, labels, window)`` — timestamped points for every
  matching child series, served from the finest tier that covers the
  window; ``rate``/``delta``/``rate_points`` are the counter helpers
  (monotonic-decrease = process restart → treated as a reset, never a
  negative rate).
* ``MetricsSampler`` — a background thread (``tracectx.traced_thread``)
  snapshotting selected metric families into the store at a fixed
  cadence (``SPARK_RAPIDS_ML_TPU_OBS_SAMPLE_MS``, default 1000).
  Counters and gauges sample as-is; a ``Summary`` samples its
  configured quantiles (one series per quantile label) plus its
  ``_count`` as a counter; a ``Histogram`` samples ``_count``/``_sum``.
  Registered *collectors* (e.g. ``obs.devmon``) run at the top of every
  sweep so derived gauges get history too.
* **The cost of watching is itself watched**: every sweep's wall-clock
  lands in ``sparkml_obs_overhead_seconds_total{component="sampler"}``
  (a counter the sampler also samples), and
  ``scripts/obs_overhead_bench.py`` turns it into a sentinel-judgeable
  overhead fraction.

Clocks are injectable everywhere (``clock=``): tests drive 30 minutes
of samples with zero real sleeps. ``start_sampling()`` also registers a
``metrics_history`` flight-dump section, so a watchdog dump carries the
last ~5 minutes of the key serve/SLO series — the lead-up, not just the
moment of death.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from spark_rapids_ml_tpu.obs import metrics as metrics_mod

SAMPLE_MS_ENV = "SPARK_RAPIDS_ML_TPU_OBS_SAMPLE_MS"
HISTORY_ENV = "SPARK_RAPIDS_ML_TPU_OBS_HISTORY"

_DEFAULT_SAMPLE_MS = 1000.0
# (resolution_seconds, span_seconds) per tier, finest first.
DEFAULT_TIERS: Tuple[Tuple[float, float], ...] = (
    (1.0, 300.0),
    (10.0, 3600.0),
)
# Metric-name prefixes the sampler records by default: the serving tier,
# its SLOs, the HTTP front end, device/host memory, the per-model cost
# ledger, and the obs layer's own overhead series.
DEFAULT_PREFIXES: Tuple[str, ...] = (
    "sparkml_serve_",
    "sparkml_slo_",
    "sparkml_http_",
    "sparkml_device_",
    "sparkml_host_",
    "sparkml_model_",
    "sparkml_numerics_",
    "sparkml_obs_",
    "sparkml_log_",
    "sparkml_fit_",
    "sparkml_fleet_",
    "sparkml_forecast_",
)
# Families matched by a prefix above that do NOT earn a history ring:
# high-cardinality operational counters (per-model × outcome/op/event
# children) that are scraped via /metrics and rolled up by
# /debug/costs, but whose time dimension nobody queries. Every child
# here would otherwise cost a full ring ladder per (model, label)
# combination — the store's series budget is spent on the families the
# dashboard and detectors actually read over time.
SAMPLE_EXCLUDE: Tuple[str, ...] = (
    "sparkml_model_requests_total",
    "sparkml_model_rows_total",
    "sparkml_model_compile_seconds_total",
    "sparkml_model_compiles_total",
    "sparkml_model_aot_cache_total",
    "sparkml_model_ledger_mutations_total",
    "sparkml_model_reconcile_checks_total",
    "sparkml_model_last_hit_age_seconds",
)
# The series a flight dump's history tail embeds (kept tighter than the
# sampler set: a dump is read by a human mid-incident).
DUMP_PREFIXES: Tuple[str, ...] = ("sparkml_serve_", "sparkml_slo_")
DUMP_TAIL_SECONDS = 300.0
# Sized for the per-model cost ledger's worst case (OBS_MODEL_MAX
# models × their sampled families) ON TOP of the serve/SLO/device
# families — at the old 2048 a full model roster could crowd out
# late-born serve series, and the store drops NEW series at the cap.
_MAX_SERIES = 3072


def default_tiers() -> Tuple[Tuple[float, float], ...]:
    """The downsample ladder from ``SPARK_RAPIDS_ML_TPU_OBS_HISTORY``
    (``"1x300,10x3600"`` = 1 s × 5 m + 10 s × 1 h), or the default."""
    raw = os.environ.get(HISTORY_ENV, "").strip()
    if not raw:
        return DEFAULT_TIERS
    tiers: List[Tuple[float, float]] = []
    try:
        for part in raw.split(","):
            res, span = part.lower().split("x")
            res_s, span_s = float(res), float(span)
            if res_s <= 0 or span_s <= res_s:
                return DEFAULT_TIERS
            tiers.append((res_s, span_s))
    except ValueError:
        return DEFAULT_TIERS
    return tuple(sorted(tiers)) or DEFAULT_TIERS


def sample_interval_seconds() -> float:
    try:
        ms = float(os.environ.get(SAMPLE_MS_ENV, _DEFAULT_SAMPLE_MS))
    except ValueError:
        ms = _DEFAULT_SAMPLE_MS
    return max(ms, 10.0) / 1000.0


class _Tier:
    """One downsample tier of one series: a bounded ring of
    ``[bucket_start_ts, value]`` keeping the LAST sample per bucket."""

    __slots__ = ("resolution", "points")

    def __init__(self, resolution: float, span: float):
        self.resolution = float(resolution)
        capacity = int(span / resolution) + 1
        self.points: collections.deque = collections.deque(maxlen=capacity)

    def add(self, ts: float, value: float) -> None:
        bucket = (ts // self.resolution) * self.resolution
        if self.points and self.points[-1][0] == bucket:
            self.points[-1][1] = value  # last-in-bucket wins
        elif self.points and self.points[-1][0] > bucket:
            return  # clock went backwards; keep the ring monotone
        else:
            self.points.append([bucket, value])

    def query(self, start: float, end: float) -> List[List[float]]:
        return [[ts, v] for ts, v in self.points if start <= ts <= end]


class _Series:
    __slots__ = ("name", "labels", "kind", "tiers", "born")

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...],
                 kind: str, tiers: Sequence[Tuple[float, float]]):
        self.name = name
        self.labels = labels
        self.kind = kind
        self.tiers = [_Tier(res, span) for res, span in tiers]
        # first-ever record time; lets counter math distinguish "child
        # born mid-window" (its first value IS increase) from "older
        # points aged out of the ring" (it is not)
        self.born: Optional[float] = None

    def add(self, ts: float, value: float) -> None:
        if self.born is None:
            self.born = ts
        for tier in self.tiers:
            tier.add(ts, value)


def _label_key(labels: Optional[Dict[str, str]]
               ) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v))
                        for k, v in (labels or {}).items()))


def counter_increase(points: Sequence[Sequence[float]]) -> float:
    """Total increase of a cumulative counter over its sampled points,
    treating any monotonic DECREASE as a restart-from-zero reset (the
    post-reset value is itself new increase) — the Prometheus ``rate``
    reset rule, so a process restart never reads as a negative rate."""
    total = 0.0
    prev: Optional[float] = None
    for _ts, value in points:
        if prev is not None:
            total += value - prev if value >= prev else value
        prev = value
    return total


def windowed_increase(series: Dict[str, Any],
                      window_start: float) -> float:
    """Counter increase of one ``range_query`` series doc over its
    window, crediting a child BORN inside the window with its first
    sampled value — a burst that mints a new labeled child (the first
    ``outcome="error"`` of a fault storm) lands entirely between two
    samples, so the plain first-to-last increase over ``[3, 3, ...]``
    reads 0 and a detector watching the delta is blind to exactly the
    event it exists for. ``born_ts`` (first-ever record time) is how we
    tell that case from an old series whose early points merely aged
    out of the ring."""
    points = series.get("points") or []
    inc = counter_increase(points)
    born = series.get("born_ts")
    if points and born is not None and born >= window_start:
        inc += points[0][1]
    return inc


class TimeSeriesStore:
    """Bounded multi-tier history for metric series.

    One lock guards the series map and every ring: recording is a dict
    lookup plus ≤ ``len(tiers)`` deque appends, and queries copy the
    matching points out — safe under concurrent sample/query threads
    (tested 8-way). The store holds at most ``max_series`` distinct
    series; past that, NEW series are dropped and counted in
    ``sparkml_obs_tsdb_dropped_series_total`` (never silently).
    """

    def __init__(
        self,
        tiers: Optional[Sequence[Tuple[float, float]]] = None,
        clock: Callable[[], float] = time.time,
        max_series: int = _MAX_SERIES,
    ):
        self.tiers: Tuple[Tuple[float, float], ...] = tuple(
            sorted(tiers if tiers is not None else default_tiers())
        )
        if not self.tiers:
            raise ValueError("need at least one (resolution, span) tier")
        self.clock = clock
        self.max_series = int(max_series)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                           _Series] = {}
        self._dropped_keys: set = set()

    # -- recording ---------------------------------------------------------

    def record(self, name: str, labels: Optional[Dict[str, str]],
               value: float, kind: str = "gauge",
               now: Optional[float] = None) -> None:
        ts = self.clock() if now is None else now
        key = (name, _label_key(labels))
        with self._lock:
            series = self._series.get(key)
            if series is None:
                if len(self._series) >= self.max_series:
                    # count each DISTINCT dropped series once — the
                    # sampler re-offers the same over-cap series every
                    # sweep, and a per-sample count would read as a
                    # mass-drop event after a day at 1 s cadence. The
                    # dedup set is itself bounded (2× the series cap):
                    # unbounded label churn (a URL scanner minting
                    # metric children) must not leak memory through the
                    # very guard that exists to bound it — past the
                    # bound, further distinct drops go uncounted.
                    if (key not in self._dropped_keys
                            and len(self._dropped_keys)
                            < 2 * self.max_series):
                        self._dropped_keys.add(key)
                        self._count_dropped()
                    return
                series = _Series(name, key[1], kind, self.tiers)
                self._series[key] = series
            series.add(ts, float(value))

    def _count_dropped(self) -> None:
        try:
            metrics_mod.get_registry().counter(
                "sparkml_obs_tsdb_dropped_series_total",
                "new series dropped because the store hit max_series "
                "(raise max_series or narrow the sampler prefixes)",
            ).inc()
        except Exception:
            pass  # telemetry about telemetry must never raise

    # -- queries -----------------------------------------------------------

    def _tier_for(self, series: _Series, window: float) -> _Tier:
        """The finest tier whose span covers the window (else the
        coarsest)."""
        for tier, (_res, span) in zip(series.tiers, self.tiers):
            if span >= window:
                return tier
        return series.tiers[-1]

    def _matching(self, name: str, labels: Optional[Dict[str, str]]
                  ) -> List[_Series]:
        """Children of ``name`` whose labels contain every given pair
        (``labels=None`` matches all children). Caller holds the lock."""
        want = set(_label_key(labels)) if labels else None
        out = []
        for (sname, _lk), series in self._series.items():
            if sname != name:
                continue
            if want is not None and not want.issubset(set(series.labels)):
                continue
            out.append(series)
        return out

    def range_query(self, name: str,
                    labels: Optional[Dict[str, str]] = None,
                    window: float = 300.0,
                    now: Optional[float] = None) -> List[Dict[str, Any]]:
        """``[{"labels": {...}, "kind", "points": [[ts, value], ...]},
        ...]`` for every matching child over the trailing window —
        points ascending in time, served from the finest covering tier."""
        ts = self.clock() if now is None else now
        with self._lock:
            matches = [
                (dict(s.labels), s.kind, s.born,
                 self._tier_for(s, window).query(ts - window, ts))
                for s in self._matching(name, labels)
            ]
        return [
            {"labels": lbls, "kind": kind, "born_ts": born,
             "points": pts}
            for lbls, kind, born, pts in matches
        ]

    def delta(self, name: str, labels: Optional[Dict[str, str]] = None,
              window: float = 300.0, now: Optional[float] = None) -> float:
        """Total counter increase over the window, summed across matching
        children, reset-aware."""
        return sum(
            counter_increase(s["points"])
            for s in self.range_query(name, labels, window, now=now)
        )

    def rate(self, name: str, labels: Optional[Dict[str, str]] = None,
             window: float = 300.0, now: Optional[float] = None) -> float:
        """Per-second counter rate over the window, summed per series
        (Prometheus semantics: each child's increase over its OWN
        sampled span — a child that appeared mid-window contributes its
        true rate, not one diluted by the longest-lived sibling's span).
        A series with fewer than two samples contributes 0.0."""
        total = 0.0
        for s in self.range_query(name, labels, window, now=now):
            pts = s["points"]
            span = pts[-1][0] - pts[0][0] if len(pts) >= 2 else 0.0
            if span > 0:
                total += counter_increase(pts) / span
        return total

    def rate_points(self, name: str,
                    labels: Optional[Dict[str, str]] = None,
                    window: float = 300.0,
                    now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Per-interval rate series (``[[ts, per_second], ...]`` between
        consecutive samples, reset-aware) — what a request-rate
        sparkline plots from a cumulative counter."""
        out = []
        for s in self.range_query(name, labels, window, now=now):
            pts = s["points"]
            rates: List[List[float]] = []
            for prev, cur in zip(pts, pts[1:]):
                dt = cur[0] - prev[0]
                if dt <= 0:
                    continue
                inc = cur[1] - prev[1] if cur[1] >= prev[1] else cur[1]
                rates.append([cur[0], inc / dt])
            out.append({"labels": s["labels"], "kind": "rate",
                        "points": rates})
        return out

    # -- introspection -----------------------------------------------------

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted({name for name, _ in self._series})

    def series_count(self) -> int:
        with self._lock:
            return len(self._series)

    def dropped_series(self) -> int:
        """How many DISTINCT series were refused at the cap."""
        with self._lock:
            return len(self._dropped_keys)

    def history_tail(self, prefixes: Sequence[str] = DUMP_PREFIXES,
                     window: float = DUMP_TAIL_SECONDS,
                     now: Optional[float] = None,
                     max_series: int = 64) -> Dict[str, Any]:
        """The flight-dump section: recent points for every series whose
        name starts with one of ``prefixes`` (bounded — a dump must stay
        readable). Keys are ``name{k=v,...}``."""
        ts = self.clock() if now is None else now
        prefixes = tuple(prefixes)
        with self._lock:
            items = sorted(self._series.items())
        out: Dict[str, Any] = {}
        truncated = 0
        for (name, label_key), series in items:
            if not name.startswith(prefixes):
                continue
            if len(out) >= max_series:
                truncated += 1
                continue
            tier = self._tier_for(series, window)
            with self._lock:
                points = tier.query(ts - window, ts)
            if not points:
                continue
            label_str = ",".join(f"{k}={v}" for k, v in label_key)
            out[f"{name}{{{label_str}}}" if label_str else name] = {
                "kind": series.kind,
                "points": points,
            }
        if truncated:
            out["_truncated_series"] = truncated
        return out


class MetricsSampler:
    """Background sweep: registry families → store, at a fixed cadence.

    ``sample_once(now=)`` is the injectable-clock entry point tests (and
    the background thread) share; ``start()``/``stop()`` manage the
    daemon thread. Collectors registered via ``register_collector`` run
    at the top of each sweep (guarded — a broken collector never kills
    the sampler) so derived gauges (device memory, occupancy) are fresh
    in the same tick that samples them.
    """

    def __init__(
        self,
        store: Optional[TimeSeriesStore] = None,
        registry: Optional[metrics_mod.MetricsRegistry] = None,
        interval_seconds: Optional[float] = None,
        prefixes: Sequence[str] = DEFAULT_PREFIXES,
        clock: Callable[[], float] = time.time,
        exclude: Sequence[str] = SAMPLE_EXCLUDE,
    ):
        self.store = store if store is not None else TimeSeriesStore(
            clock=clock)
        self._registry = registry
        self.interval_seconds = (
            interval_seconds if interval_seconds is not None
            else sample_interval_seconds()
        )
        self.prefixes = tuple(prefixes)
        self.exclude = frozenset(exclude)
        self.clock = clock
        self._collectors: List[Callable[[], None]] = []
        self._post_hooks: List[Callable[[float], None]] = []
        self._collectors_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lifecycle = threading.Lock()  # start/stop check-then-act
        self._sweeps = 0

    def _reg(self) -> metrics_mod.MetricsRegistry:
        return (self._registry if self._registry is not None
                else metrics_mod.get_registry())

    def register_collector(self, fn: Callable[[], None]) -> None:
        with self._collectors_lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def unregister_collector(self, fn: Callable[[], None]) -> None:
        with self._collectors_lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def register_post_sweep(self, fn: Callable[[float], None]) -> None:
        """Run ``fn(sweep_timestamp)`` at the END of every sweep, after
        fresh samples landed in the store — the hook the auto-incident
        engine detects from (same thread, same injectable clock, cost
        inside the sweep's own overhead accounting). Idempotent."""
        with self._collectors_lock:
            if fn not in self._post_hooks:
                self._post_hooks.append(fn)

    def unregister_post_sweep(self, fn: Callable[[float], None]) -> None:
        with self._collectors_lock:
            if fn in self._post_hooks:
                self._post_hooks.remove(fn)

    # -- one sweep ---------------------------------------------------------

    def sample_once(self, now: Optional[float] = None) -> int:
        """Run collectors, then snapshot every selected family into the
        store at timestamp ``now`` (injectable). Returns the number of
        points recorded. The sweep's own wall-clock cost lands in
        ``sparkml_obs_overhead_seconds_total{component="sampler"}``."""
        t0 = time.perf_counter()
        ts = self.clock() if now is None else now
        with self._collectors_lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:
                self._count_collector_error(fn)
        recorded = 0
        for family in self._reg().families():
            if (not family.name.startswith(self.prefixes)
                    or family.name in self.exclude):
                continue
            try:
                recorded += self._sample_family(family, ts)
            except Exception:
                continue  # one sick family must not starve the rest
        # the sampler's own cost stops HERE: post-sweep hooks (the
        # anomaly sweep) account for themselves under their own
        # component label — timing them here too would double-count
        # every detector sweep in the overhead total and make an
        # evidence capture read as a sampler latency spike
        self._sweeps += 1
        elapsed = time.perf_counter() - t0
        self._publish_overhead(elapsed, recorded)
        with self._collectors_lock:
            post_hooks = list(self._post_hooks)
        for fn in post_hooks:
            try:
                fn(ts)
            except Exception:
                self._count_collector_error(fn)
        return recorded

    def _sample_family(self, family, ts: float) -> int:
        # reads go straight at the child objects _samples() yielded —
        # re-resolving each child through family.value(**labels) would
        # re-take the family lock and rebuild the label key per child,
        # per sweep, for nothing
        recorded = 0
        for key, child in family._samples():
            labels = family._label_dict(key)
            if isinstance(family, (metrics_mod.Counter,
                                   metrics_mod.Gauge)):
                with child.lock:
                    value = child.value
                self.store.record(family.name, labels, value,
                                  kind=family.kind, now=ts)
                recorded += 1
            elif isinstance(family, metrics_mod.Summary):
                sketch = child.sketch
                for q in family.quantiles:
                    value = sketch.quantile(q)
                    if value is None:
                        continue
                    q_labels = dict(labels)
                    q_labels["quantile"] = metrics_mod._format_value(q)
                    self.store.record(family.name, q_labels, value,
                                      kind="gauge", now=ts)
                    recorded += 1
                self.store.record(f"{family.name}_count", labels,
                                  sketch.count, kind="counter", now=ts)
                recorded += 1
            elif isinstance(family, metrics_mod.Histogram):
                with child.lock:
                    count, total = child.count, child.sum
                self.store.record(f"{family.name}_count", labels,
                                  count, kind="counter", now=ts)
                self.store.record(f"{family.name}_sum", labels,
                                  total, kind="counter", now=ts)
                recorded += 2
        return recorded

    def _publish_overhead(self, elapsed: float, recorded: int) -> None:
        try:
            reg = self._reg()
            reg.counter(
                "sparkml_obs_overhead_seconds_total",
                "wall-clock the observability layer spends watching "
                "(sampler sweeps, device monitor, profiler bookkeeping)",
                ("component",),
            ).inc(elapsed, component="sampler")
            reg.counter(
                "sparkml_obs_samples_total",
                "history points recorded by the metrics sampler",
            ).inc(recorded)
            reg.gauge(
                "sparkml_obs_sample_sweep_seconds",
                "duration of the most recent sampler sweep",
            ).set(elapsed)
        except Exception:
            pass  # overhead accounting must never break the sweep

    def _count_collector_error(self, fn) -> None:
        try:
            self._reg().counter(
                "sparkml_obs_collector_errors_total",
                "sampler collector callbacks that raised", ("collector",),
            ).inc(collector=getattr(fn, "__name__", "collector"))
        except Exception:
            pass

    # -- the background thread ---------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def sweeps(self) -> int:
        return self._sweeps

    def start(self) -> None:
        """Start the sampling thread (idempotent — two racing starts
        must not spawn two sweep loops sampling at double cadence)."""
        from spark_rapids_ml_tpu.obs import tracectx

        with self._lifecycle:
            if self.running:
                return
            self._stop.clear()
            self._thread = tracectx.traced_thread(
                self._run, name="sparkml-obs-sampler", daemon=True,
                fresh=True,
            )
            self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        with self._lifecycle:
            # set under the lock: a racing start() clearing the event
            # between set and join would orphan a live sweep loop
            self._stop.set()
            thread = self._thread
            self._thread = None
        if thread is not None:
            thread.join(timeout=timeout)

    def _run(self) -> None:
        while not self._stop.is_set():
            self.sample_once()
            self._stop.wait(self.interval_seconds)


# -- the process-wide default store/sampler ----------------------------------

_lock = threading.Lock()
_store: Optional[TimeSeriesStore] = None
_sampler: Optional[MetricsSampler] = None


def get_tsdb() -> TimeSeriesStore:
    """The process-wide history store the serving surface queries."""
    global _store
    with _lock:
        if _store is None:
            _store = TimeSeriesStore()
        return _store


def get_sampler() -> MetricsSampler:
    global _sampler
    store = get_tsdb()
    with _lock:
        if _sampler is None:
            _sampler = MetricsSampler(store)
        return _sampler


def _dump_history_tail() -> Dict[str, Any]:
    return get_tsdb().history_tail()


def start_sampling(interval_seconds: Optional[float] = None
                   ) -> MetricsSampler:
    """Start (idempotently) the process-wide history sampler.

    Wires the device monitor in as a collector and registers the
    ``metrics_history`` flight-dump section, so every dump from here on
    carries the last ~5 minutes of the key serve/SLO series."""
    sampler = get_sampler()
    if interval_seconds is not None:
        sampler.interval_seconds = interval_seconds
    try:
        from spark_rapids_ml_tpu.obs import devmon

        sampler.register_collector(devmon.get_device_monitor().sample)
    except Exception:
        pass  # no jax / no devices: plain registry history still works
    try:
        from spark_rapids_ml_tpu.obs import fitmon

        sampler.register_collector(
            fitmon.get_fit_monitor().watchdog_collector)
    except Exception:
        pass  # watchdog is advisory: registry history still works
    from spark_rapids_ml_tpu.obs import flight

    flight.register_dump_section("metrics_history", _dump_history_tail)
    sampler.start()
    return sampler


def stop_sampling() -> None:
    with _lock:
        sampler = _sampler
    if sampler is not None:
        sampler.stop()


def reset_tsdb() -> None:
    """Drop the process-wide store/sampler (tests)."""
    global _store, _sampler
    with _lock:
        sampler = _sampler
        _sampler = None
        _store = None
    if sampler is not None:
        sampler.stop()


__all__ = [
    "DEFAULT_PREFIXES",
    "DEFAULT_TIERS",
    "DUMP_PREFIXES",
    "HISTORY_ENV",
    "MetricsSampler",
    "SAMPLE_EXCLUDE",
    "SAMPLE_MS_ENV",
    "TimeSeriesStore",
    "counter_increase",
    "default_tiers",
    "get_sampler",
    "get_tsdb",
    "reset_tsdb",
    "sample_interval_seconds",
    "start_sampling",
    "stop_sampling",
    "windowed_increase",
]
