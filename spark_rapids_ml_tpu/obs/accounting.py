"""Per-model resource accounting: the cost-attribution ledger.

The ROADMAP's model-density (tiering/eviction under an HBM budget) and
predictive-autoscaling items both need one thing that did not exist:
a meter that attributes every resource a served model consumes to that
model — HBM residency, device-seconds, compile cost, traffic. This
module is that meter. It is read-side only: it never makes a placement
or eviction decision, it produces the numbers those controllers will
read.

What is metered, and at which seam:

* **HBM residency** — ``sparkml_model_hbm_bytes{model,component}``.
  Charged when the engine builds a replica from a ``ServingProgram``
  (``weight_bytes`` is computed at staging time in
  ``models/_serving.py`` — the bytes actually ``device_put``), under
  three components:

  - ``weights``   — staged weights of live (serving) replicas;
  - ``reserve``   — staged weights of reaped replicas. The engine
    deliberately RETAINS a reaped replica's program so a later
    scale-up revives it without re-staging (the zero-cold-start
    property); those bytes are still device-resident, so the ledger
    moves them ``weights → reserve`` instead of pretending they are
    free. ``evict()`` is what actually frees them (drops everything).
  - ``executables`` — serialized-executable bytes attributed from the
    AOT cache (``obs.aotcache``) during a compile-attribution window.

* **Device time** — ``sparkml_model_device_seconds_total{model}``,
  noted at the ``MicroBatcher`` completion seam (the same call site
  that feeds ``obs.devmon``) and by the sharded fan-out path. Because
  the ledger hears about device time from the same seam as devmon, it
  can be *checked, not trusted*: ``reconcile()`` compares the ledger's
  per-model totals against devmon's
  ``sparkml_serve_device_batch_seconds_total`` and publishes a drift
  ratio + verdict counter.

* **Compile cost** — ``compile_attribution(model, version)`` wraps the
  engine's warm/build sections; the OUTERMOST window captures deltas
  of ``obs.xprof.compile_stats()`` (compile-seconds, compiles) and
  ``obs.aotcache`` stats (hit/miss/bytes) and charges them to the
  model being warmed. Nested windows (warmup calling replica build)
  attribute to the outer owner exactly once.

* **Traffic vitals** — rows, requests by outcome, last-hit age and a
  decaying-average request rate (``ewma_rps``: on each request the
  accumulator decays by ``exp(-dt/tau)`` then adds the row count;
  the published rate is ``acc/tau``). Per-(tenant, priority) rollups
  are kept in the ledger snapshot only — never as metric labels — so
  request cardinality cannot leak into the metrics surface.

Every ``sparkml_model_*`` series carries a model label bounded by
``resolve_model``: the first ``MODEL_MAX`` distinct names get their own
label, later ones collapse into ``(overflow)`` (mirroring the serve
tier's ``TENANT_MAX`` guard) — a 200-model registry cannot blow up the
metrics text surface. Every ledger mutation increments
``sparkml_model_ledger_mutations_total{model,op}`` (rule 15 of
``scripts/check_instrumentation.py``: a silent ledger mutation is a
bug by construction). Only the low-cardinality families the dashboard
and detectors read over time (HBM bytes, device-seconds, ``ewma_rps``,
reconcile drift) earn TSDB history rings; the per-outcome/op/event
counters stay on ``/metrics`` and in ``/debug/costs`` rollups
(``obs.tsdb.SAMPLE_EXCLUDE`` — the store's series budget is finite).

Knobs (env):

* ``SPARK_RAPIDS_ML_TPU_OBS_ACCOUNTING`` — ``0`` disables the ledger
  (every mutation becomes a cheap guard-and-return; default on).
* ``SPARK_RAPIDS_ML_TPU_OBS_MODEL_MAX`` — distinct model labels before
  ``(overflow)`` (default 64).
* ``SPARK_RAPIDS_ML_TPU_OBS_ACCOUNTING_TAU`` — EWMA time constant for
  ``ewma_rps``, seconds (default 60).
* ``SPARK_RAPIDS_ML_TPU_OBS_RECONCILE_TOL`` — relative drift between
  ledger and devmon device-seconds tolerated per model (default 0.05).
* ``SPARK_RAPIDS_ML_TPU_OBS_RECONCILE_MIN_SECONDS`` — models with less
  devmon busy-time than this are skipped by reconciliation (a 2 ms
  total makes any ratio meaningless; default 0.05 s).

Surfaces: ``GET /debug/costs`` (``costs_document()`` — per-model
rollups, per-replica breakdown, a ranked cold-model report, and the
reconciliation verdict), the dashboard's per-model tiles (via the
TSDB sampler: ``publish()`` is registered as a collector so gauges are
refreshed and every series gets history), and the autoscale snapshot
(per-model resident bytes — the meter predictive scaling reads).
"""

from __future__ import annotations

import contextlib
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from spark_rapids_ml_tpu.obs.metrics import get_registry

ACCOUNTING_ENV = "SPARK_RAPIDS_ML_TPU_OBS_ACCOUNTING"
MODEL_MAX_ENV = "SPARK_RAPIDS_ML_TPU_OBS_MODEL_MAX"
TAU_ENV = "SPARK_RAPIDS_ML_TPU_OBS_ACCOUNTING_TAU"
RECONCILE_TOL_ENV = "SPARK_RAPIDS_ML_TPU_OBS_RECONCILE_TOL"
RECONCILE_MIN_ENV = "SPARK_RAPIDS_ML_TPU_OBS_RECONCILE_MIN_SECONDS"

OVERFLOW_MODEL = "(overflow)"
DEFAULT_MODEL_MAX = 64
DEFAULT_TAU_SECONDS = 60.0
DEFAULT_RECONCILE_TOL = 0.05
DEFAULT_RECONCILE_MIN_SECONDS = 0.05

# HBM residency components (the only values the component label takes).
COMPONENT_WEIGHTS = "weights"
COMPONENT_RESERVE = "reserve"
COMPONENT_EXECUTABLES = "executables"

# per-(tenant, priority) rollups kept in the snapshot; bounded so a
# hostile tenant mix cannot grow the ledger without bound (tenant ids
# reaching here are already TENANT_MAX-bounded by serve.admission, this
# is defense in depth)
_MAX_TENANT_ROWS = 128


def _env_flag(name: str, default: bool = True) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("0", "false", "no", "off", "")


def _env_float(name: str, default: float) -> float:
    try:
        value = float(os.environ.get(name, "").strip() or default)
    except (TypeError, ValueError):
        return default
    return value if value > 0 else default


def _env_int(name: str, default: int) -> int:
    try:
        value = int(os.environ.get(name, "").strip() or default)
    except (TypeError, ValueError):
        return default
    return value if value > 0 else default


class _ModelVitals:
    """Traffic + cost accumulators for one resolved model label."""

    __slots__ = ("rows", "requests", "device_seconds", "compile_seconds",
                 "compiles", "aot_hit", "aot_miss", "signatures",
                 "last_hit", "ewma_acc", "ewma_ts", "tenants")

    def __init__(self):
        self.rows = 0
        self.requests: Dict[str, int] = {}
        self.device_seconds = 0.0
        self.compile_seconds = 0.0
        self.compiles = 0
        self.aot_hit = 0
        self.aot_miss = 0
        self.signatures = 0
        self.last_hit: Optional[float] = None   # ledger-clock timestamp
        self.ewma_acc = 0.0
        self.ewma_ts: Optional[float] = None
        # (tenant, priority) -> {"rows": n, "requests": n}
        self.tenants: Dict[Tuple[str, str], Dict[str, int]] = {}


class ResourceLedger:
    """Process-wide per-model resource ledger (see module docstring).

    Thread-safe; the hot-path entry points (``note_request``,
    ``note_batch_seconds``) never raise — accounting is telemetry, not
    control flow. Memory mutations (charge/release/retire/revive) DO
    raise on caller bugs (negative bytes, unknown component): those run
    on the engine's build/scale paths where a silent mis-charge would
    corrupt the very numbers the tiering controller will trust.
    """

    def __init__(self, clock=time.monotonic,
                 enabled: Optional[bool] = None):
        self._clock = clock
        self.enabled = (_env_flag(ACCOUNTING_ENV, True)
                        if enabled is None else bool(enabled))
        self.model_max = _env_int(MODEL_MAX_ENV, DEFAULT_MODEL_MAX)
        self.tau = _env_float(TAU_ENV, DEFAULT_TAU_SECONDS)
        self.reconcile_tol = _env_float(
            RECONCILE_TOL_ENV, DEFAULT_RECONCILE_TOL)
        self.reconcile_min_seconds = _env_float(
            RECONCILE_MIN_ENV, DEFAULT_RECONCILE_MIN_SECONDS)
        self._lock = threading.RLock()
        # (model, version, replica, component) -> bytes
        self._mem: Dict[Tuple[str, str, str, str], int] = {}
        self._vitals: Dict[str, _ModelVitals] = {}
        self._known_models: set = set()
        # compile-attribution window state (outermost-only capture)
        self._attr_lock = threading.RLock()
        self._attr_depth = 0
        self._attr_owner: Optional[Tuple[str, int]] = None
        self._attr_before: Optional[Dict[str, float]] = None
        self._declare_metrics()

    def _declare_metrics(self) -> None:
        reg = get_registry()
        self._m_rows = reg.counter(
            "sparkml_model_rows_total",
            "rows served per model", ("model",))
        self._m_requests = reg.counter(
            "sparkml_model_requests_total",
            "requests per model by outcome", ("model", "outcome"))
        self._m_device_seconds = reg.counter(
            "sparkml_model_device_seconds_total",
            "device wall-clock attributed per model at the batcher "
            "completion seam (reconciled against devmon)", ("model",))
        self._m_compile_seconds = reg.counter(
            "sparkml_model_compile_seconds_total",
            "compile wall-clock attributed per model during warm/build "
            "windows", ("model",))
        self._m_compiles = reg.counter(
            "sparkml_model_compiles_total",
            "compilations attributed per model", ("model",))
        self._m_aot = reg.counter(
            "sparkml_model_aot_cache_total",
            "AOT executable-cache events attributed per model",
            ("model", "event"))
        self._m_mutations = reg.counter(
            "sparkml_model_ledger_mutations_total",
            "ledger mutations by operation (audit trail: every "
            "charge/release/retire/revive/note lands here)",
            ("model", "op"))
        self._m_reconcile_checks = reg.counter(
            "sparkml_model_reconcile_checks_total",
            "ledger-vs-devmon reconciliation verdicts", ("verdict",))
        self._m_hbm = reg.gauge(
            "sparkml_model_hbm_bytes",
            "accounted HBM residency per model by component "
            "(weights=live replicas, reserve=reaped-but-retained "
            "programs, executables=serialized AOT entries)",
            ("model", "component"))
        self._m_ewma = reg.gauge(
            "sparkml_model_ewma_rps",
            "decaying-average rows/second per model (tau="
            "SPARK_RAPIDS_ML_TPU_OBS_ACCOUNTING_TAU)", ("model",))
        self._m_age = reg.gauge(
            "sparkml_model_last_hit_age_seconds",
            "seconds since a model last served a request "
            "(-1 = never hit)", ("model",))
        self._m_drift = reg.gauge(
            "sparkml_model_reconcile_drift_ratio",
            "relative drift between ledger and devmon device-seconds "
            "per model", ("model",))

    # -- model-label cardinality guard -------------------------------------

    def resolve_model(self, name: str) -> str:
        """Bound the model label: the first ``model_max`` distinct names
        keep their own label, later ones collapse to ``(overflow)``.
        Mirrors ``serve.admission``'s tenant guard."""
        name = str(name) if name else "(unknown)"
        with self._lock:
            if name in self._known_models:
                return name
            if len(self._known_models) < self.model_max:
                self._known_models.add(name)
                return name
            return OVERFLOW_MODEL

    def _vitals_for(self, label: str) -> _ModelVitals:
        # caller holds self._lock
        vitals = self._vitals.get(label)
        if vitals is None:
            vitals = self._vitals[label] = _ModelVitals()
        return vitals

    # -- HBM residency ------------------------------------------------------

    def charge_memory(self, model: str, version: Any, replica: str,
                      component: str, nbytes: int) -> None:
        """Account ``nbytes`` of device residency to one replica of
        ``model@version``. Re-charging the same key overwrites (a
        rebuilt replica re-states its footprint, it does not stack)."""
        if not self.enabled:
            return
        if component not in (COMPONENT_WEIGHTS, COMPONENT_RESERVE,
                             COMPONENT_EXECUTABLES):
            raise ValueError(f"unknown residency component {component!r}")
        nbytes = int(nbytes)
        if nbytes < 0:
            raise ValueError("residency bytes cannot be negative")
        label = self.resolve_model(model)
        key = (label, str(version), str(replica), component)
        with self._lock:
            self._mem[key] = nbytes
            self._publish_hbm_locked(label)
        self._m_mutations.inc(model=label, op="charge")

    def release_memory(self, model: str, version: Any = None,
                       replica: Optional[str] = None,
                       component: Optional[str] = None) -> int:
        """Drop accounted residency; None fields are wildcards (release
        every version / replica / component of the model). Returns the
        bytes released. This is the eviction path — reap uses
        ``retire_replica`` instead, which keeps the bytes visible under
        ``reserve``."""
        if not self.enabled:
            return 0
        label = self.resolve_model(model)
        version_s = None if version is None else str(version)
        replica_s = None if replica is None else str(replica)
        released = 0
        with self._lock:
            for key in [k for k in self._mem if k[0] == label]:
                if version_s is not None and key[1] != version_s:
                    continue
                if replica_s is not None and key[2] != replica_s:
                    continue
                if component is not None and key[3] != component:
                    continue
                released += self._mem.pop(key)
            self._publish_hbm_locked(label)
        self._m_mutations.inc(model=label, op="release")
        return released

    def retire_replica(self, model: str, version: Any,
                       replica: str) -> int:
        """Move one reaped replica's ``weights`` bytes to ``reserve``:
        the engine retains the staged program for cheap revival, so the
        bytes are still device-resident — they just stop counting as
        live serving capacity. Returns the bytes moved. Idempotent."""
        if not self.enabled:
            return 0
        label = self.resolve_model(model)
        src = (label, str(version), str(replica), COMPONENT_WEIGHTS)
        dst = (label, str(version), str(replica), COMPONENT_RESERVE)
        with self._lock:
            moved = self._mem.pop(src, 0)
            if moved:
                self._mem[dst] = self._mem.get(dst, 0) + moved
            self._publish_hbm_locked(label)
        self._m_mutations.inc(model=label, op="retire")
        return moved

    def revive_replica(self, model: str, version: Any,
                       replica: str) -> int:
        """Reverse of ``retire_replica``: a scale-up revived the reaped
        replica, its bytes count as live ``weights`` again. Idempotent
        (a replica that was never reaped moves nothing)."""
        if not self.enabled:
            return 0
        label = self.resolve_model(model)
        src = (label, str(version), str(replica), COMPONENT_RESERVE)
        dst = (label, str(version), str(replica), COMPONENT_WEIGHTS)
        with self._lock:
            moved = self._mem.pop(src, 0)
            if moved:
                self._mem[dst] = self._mem.get(dst, 0) + moved
            self._publish_hbm_locked(label)
        self._m_mutations.inc(model=label, op="revive")
        return moved

    def _publish_hbm_locked(self, label: str) -> None:
        # caller holds self._lock; restate the model's per-component
        # gauge from the map (gauges are absolute, not deltas)
        totals = {COMPONENT_WEIGHTS: 0, COMPONENT_RESERVE: 0,
                  COMPONENT_EXECUTABLES: 0}
        for key, nbytes in self._mem.items():
            if key[0] == label:
                totals[key[3]] += nbytes
        for component, nbytes in totals.items():
            self._m_hbm.set(nbytes, model=label, component=component)

    def memory_bytes(self, model: Optional[str] = None,
                     component: Optional[str] = None) -> Dict[str, int]:
        """Accounted resident bytes per model (summed over versions,
        replicas and — unless ``component`` is given — components).
        The per-model number predictive autoscaling / tiering reads."""
        out: Dict[str, int] = {}
        with self._lock:
            for key, nbytes in self._mem.items():
                if model is not None and key[0] != model:
                    continue
                if component is not None and key[3] != component:
                    continue
                out[key[0]] = out.get(key[0], 0) + nbytes
        return out

    # -- traffic vitals (hot path — never raises) ---------------------------

    def note_request(self, model: str, version: Any, tenant: str,
                     priority: str, rows: int, outcome: str) -> None:
        """Record one request's vitals. Called from the serve hot path:
        guards first, never raises."""
        if not self.enabled:
            return
        try:
            label = self.resolve_model(model)
            rows = max(int(rows), 0)
            now = self._clock()
            with self._lock:
                vitals = self._vitals_for(label)
                vitals.requests[outcome] = (
                    vitals.requests.get(outcome, 0) + 1)
                if outcome == "ok":
                    vitals.rows += rows
                    # decaying rate accumulator: decay by the elapsed
                    # gap, then add this request's rows
                    if vitals.ewma_ts is not None:
                        dt = max(now - vitals.ewma_ts, 0.0)
                        vitals.ewma_acc *= math.exp(-dt / self.tau)
                    vitals.ewma_acc += rows
                    vitals.ewma_ts = now
                    vitals.last_hit = now
                tkey = (str(tenant), str(priority))
                trow = vitals.tenants.get(tkey)
                if trow is None and len(vitals.tenants) < _MAX_TENANT_ROWS:
                    trow = vitals.tenants[tkey] = {"rows": 0,
                                                   "requests": 0}
                if trow is not None:
                    trow["requests"] += 1
                    if outcome == "ok":
                        trow["rows"] += rows
            self._m_requests.inc(model=label, outcome=outcome)
            if outcome == "ok" and rows:
                self._m_rows.inc(rows, model=label)
            self._m_mutations.inc(model=label, op="note_request")
        except Exception:
            pass  # vitals must never fail a request

    def note_batch_seconds(self, model: str, seconds: float,
                           device: Optional[str] = None) -> None:
        """Attribute one coalesced batch's device time to the model.
        Same seam (and same never-raises contract) as
        ``devmon.note_batch`` — reconcile() checks the two agree."""
        if not self.enabled:
            return
        try:
            label = self.resolve_model(model)
            seconds = max(float(seconds), 0.0)
            with self._lock:
                self._vitals_for(label).device_seconds += seconds
            self._m_device_seconds.inc(seconds, model=label)
            self._m_mutations.inc(model=label, op="note_batch")
        except Exception:
            pass  # attribution must never fail a batch

    # -- compile / cache attribution ---------------------------------------

    def _attribution_totals(self) -> Dict[str, float]:
        """Current process-wide compile + AOT-cache totals (the deltas
        of which a compile_attribution window charges to its owner)."""
        totals = {"compile_seconds": 0.0, "compiles": 0.0,
                  "aot_hit": 0.0, "aot_miss": 0.0, "aot_bytes": 0.0}
        try:
            from spark_rapids_ml_tpu.obs import xprof

            for stats in xprof.compile_stats().values():
                totals["compile_seconds"] += float(
                    stats.get("compile_seconds", 0.0))
                totals["compiles"] += float(stats.get("compiles", 0))
        except Exception:
            pass
        try:
            from spark_rapids_ml_tpu.obs import aotcache

            cache = aotcache.get_executable_cache()
            if cache is not None:
                stats = cache.stats()
                totals["aot_hit"] = float(stats.get("hit", 0))
                totals["aot_miss"] = float(stats.get("miss", 0))
                totals["aot_bytes"] = float(stats.get("bytes", 0))
        except Exception:
            pass
        return totals

    @contextlib.contextmanager
    def compile_attribution(self, model: str, version: Any):
        """Attribute compile-seconds / compilations / AOT-cache events
        that happen inside this window to ``model@version``. Reentrant:
        only the OUTERMOST window captures deltas (warmup wrapping the
        replica build must not double-charge). Windows from different
        threads serialize — concurrent windows could not tell whose
        compile was whose, and warm/build is a cold path where a short
        wait is cheaper than a mis-charge."""
        if not self.enabled:
            yield
            return
        with self._attr_lock:
            self._attr_depth += 1
            outermost = self._attr_depth == 1
            if outermost:
                self._attr_owner = (model, version)
                self._attr_before = self._attribution_totals()
            try:
                yield
            finally:
                self._attr_depth -= 1
                if outermost:
                    before = self._attr_before or {}
                    self._attr_before = None
                    owner, self._attr_owner = self._attr_owner, None
                    try:
                        self._charge_attribution(owner, before)
                    except Exception:
                        pass  # attribution is telemetry

    def _charge_attribution(self, owner, before: Dict[str, float]):
        after = self._attribution_totals()
        model, version = owner
        label = self.resolve_model(model)
        d_seconds = max(after["compile_seconds"]
                        - before.get("compile_seconds", 0.0), 0.0)
        d_compiles = max(after["compiles"] - before.get("compiles", 0), 0)
        d_hit = max(after["aot_hit"] - before.get("aot_hit", 0), 0)
        d_miss = max(after["aot_miss"] - before.get("aot_miss", 0), 0)
        d_bytes = max(after["aot_bytes"] - before.get("aot_bytes", 0), 0)
        with self._lock:
            vitals = self._vitals_for(label)
            vitals.compile_seconds += d_seconds
            vitals.compiles += int(d_compiles)
            vitals.aot_hit += int(d_hit)
            vitals.aot_miss += int(d_miss)
        if d_seconds:
            self._m_compile_seconds.inc(d_seconds, model=label)
        if d_compiles:
            self._m_compiles.inc(d_compiles, model=label)
        if d_hit:
            self._m_aot.inc(d_hit, model=label, event="hit")
        if d_miss:
            self._m_aot.inc(d_miss, model=label, event="miss")
        if d_bytes:
            # serialized-executable residency: charge under a synthetic
            # replica key so evict() of the version releases it
            self.charge_memory(model, version, "(aot)",
                               COMPONENT_EXECUTABLES, int(d_bytes))
        self._m_mutations.inc(model=label, op="compile_attribution")

    # -- reconciliation (checked, not trusted) ------------------------------

    def reconcile(self) -> Dict[str, Any]:
        """Compare the ledger's per-model device-seconds against what
        devmon measured at the same seam
        (``sparkml_serve_device_batch_seconds_total``). Publishes a
        per-model drift-ratio gauge and a verdict counter; returns the
        full comparison. Models below ``reconcile_min_seconds`` of
        devmon busy-time are skipped (ratios over microseconds are
        noise, not evidence)."""
        devmon_by_model: Dict[str, float] = {}
        try:
            family = get_registry().counter(
                "sparkml_serve_device_batch_seconds_total",
                "device wall-clock attributed to coalesced serve "
                "batches — rate() of this series is per-device "
                "occupancy", ("model", "device"))
            for key, child in family._samples():
                labels = family._label_dict(key)
                raw = labels.get("model", "(unknown)")
                with self._lock:
                    label = (raw if raw in self._known_models
                             else OVERFLOW_MODEL)
                with child.lock:
                    value = child.value
                devmon_by_model[label] = (
                    devmon_by_model.get(label, 0.0) + value)
        except Exception:
            pass
        with self._lock:
            ledger_by_model = {label: vitals.device_seconds
                               for label, vitals in self._vitals.items()
                               if vitals.device_seconds > 0}
        models: Dict[str, Any] = {}
        worst = 0.0
        checked = 0
        for label in sorted(set(devmon_by_model) | set(ledger_by_model)):
            devmon_s = devmon_by_model.get(label, 0.0)
            ledger_s = ledger_by_model.get(label, 0.0)
            if max(devmon_s, ledger_s) < self.reconcile_min_seconds:
                models[label] = {"ledger_seconds": ledger_s,
                                 "devmon_seconds": devmon_s,
                                 "skipped": True}
                continue
            drift = (abs(ledger_s - devmon_s)
                     / max(devmon_s, ledger_s, 1e-9))
            self._m_drift.set(drift, model=label)
            models[label] = {"ledger_seconds": ledger_s,
                             "devmon_seconds": devmon_s,
                             "drift_ratio": drift}
            worst = max(worst, drift)
            checked += 1
        verdict = "ok" if worst <= self.reconcile_tol else "drift"
        self._m_reconcile_checks.inc(verdict=verdict)
        self._m_mutations.inc(model="(all)", op="reconcile")
        return {"verdict": verdict, "worst_drift_ratio": worst,
                "tolerance": self.reconcile_tol,
                "models_checked": checked, "models": models}

    # -- surfaces -----------------------------------------------------------

    def publish(self) -> None:
        """Refresh the time-derived gauges (last-hit age, EWMA decay).
        Registered as a TSDB sampler collector so every sweep both
        updates the gauges and records their history."""
        if not self.enabled:
            return
        now = self._clock()
        with self._lock:
            for label, vitals in self._vitals.items():
                self._m_ewma.set(self._ewma_rps_locked(vitals, now),
                                 model=label)
                age = (-1.0 if vitals.last_hit is None
                       else max(now - vitals.last_hit, 0.0))
                self._m_age.set(age, model=label)

    def _ewma_rps_locked(self, vitals: _ModelVitals, now: float) -> float:
        if vitals.ewma_ts is None:
            return 0.0
        dt = max(now - vitals.ewma_ts, 0.0)
        return (vitals.ewma_acc * math.exp(-dt / self.tau)) / self.tau

    def costs_document(self) -> Dict[str, Any]:
        """The ``/debug/costs`` payload: per-model rollups, per-replica
        residency breakdown, the ranked cold-model report (the exact
        input a tiering controller evicts by), and the reconciliation
        verdict."""
        models = self._model_rollups()
        cold = self._cold_report(models)
        return {"models": models, "cold_report": cold,
                "reconcile": self.reconcile()}

    def cold_report(self) -> List[Dict[str, Any]]:
        """The ranked cold-model report alone — the ONE source of truth
        the tiering controller's eviction scorer reads, identical row
        for row to ``costs_document()["cold_report"]`` (and therefore to
        ``GET /debug/costs``)."""
        return self._cold_report(self._model_rollups())

    def _model_rollups(self) -> Dict[str, Any]:
        """Per-model rollups (residency components, replicas, traffic
        vitals) shared by ``costs_document`` and ``cold_report``."""
        now = self._clock()
        with self._lock:
            labels = sorted(set(self._vitals)
                            | {key[0] for key in self._mem})
            models: Dict[str, Any] = {}
            for label in labels:
                vitals = self._vitals.get(label) or _ModelVitals()
                components = {COMPONENT_WEIGHTS: 0, COMPONENT_RESERVE: 0,
                              COMPONENT_EXECUTABLES: 0}
                replicas: Dict[str, Dict[str, int]] = {}
                for key, nbytes in self._mem.items():
                    if key[0] != label:
                        continue
                    components[key[3]] += nbytes
                    rep = replicas.setdefault(
                        f"{key[2]}@v{key[1]}", {})
                    rep[key[3]] = rep.get(key[3], 0) + nbytes
                models[label] = {
                    "hbm_bytes": components,
                    "hbm_total_bytes": sum(components.values()),
                    "replicas": replicas,
                    "device_seconds": vitals.device_seconds,
                    "rows": vitals.rows,
                    "requests": dict(vitals.requests),
                    "compile_seconds": vitals.compile_seconds,
                    "compiles": vitals.compiles,
                    "aot_cache": {"hit": vitals.aot_hit,
                                  "miss": vitals.aot_miss},
                    "ewma_rps": self._ewma_rps_locked(vitals, now),
                    "last_hit_age_seconds": (
                        -1.0 if vitals.last_hit is None
                        else max(now - vitals.last_hit, 0.0)),
                    "tenants": {
                        f"{tenant}|{priority}": dict(row)
                        for (tenant, priority), row
                        in sorted(vitals.tenants.items())},
                }
        return models

    @staticmethod
    def _cold_report(models: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Rank resident models coldest-first: cost held on device vs
        traffic served. ``cold_score = resident_bytes * (age + 1) /
        (ewma_rps + 1)`` — a model holding HBM while serving nothing
        sorts to the top; a hot model sorts to the bottom."""
        report = []
        for label, doc in models.items():
            resident = doc["hbm_total_bytes"]
            if resident <= 0:
                continue
            age = doc["last_hit_age_seconds"]
            age = 1e6 if age < 0 else age  # never-hit is maximally cold
            rps = doc["ewma_rps"]
            report.append({
                "model": label,
                "resident_bytes": resident,
                "ewma_rps": rps,
                "last_hit_age_seconds": doc["last_hit_age_seconds"],
                "cold_score": resident * (age + 1.0) / (rps + 1.0),
            })
        report.sort(key=lambda row: row["cold_score"], reverse=True)
        return report

    def snapshot(self) -> Dict[str, Any]:
        """Cheap introspection for tests / debug dumps."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "model_max": self.model_max,
                "known_models": sorted(self._known_models),
                "memory": {" ".join(key): nbytes
                           for key, nbytes in sorted(self._mem.items())},
            }


_ledger: Optional[ResourceLedger] = None
_ledger_lock = threading.Lock()


def get_ledger() -> ResourceLedger:
    global _ledger
    with _ledger_lock:
        if _ledger is None:
            _ledger = ResourceLedger()
        return _ledger


def reset_ledger() -> None:
    """Drop the cached ledger (tests that reset the registry)."""
    global _ledger
    with _ledger_lock:
        _ledger = None


__all__ = [
    "ResourceLedger",
    "get_ledger",
    "reset_ledger",
    "OVERFLOW_MODEL",
    "COMPONENT_WEIGHTS",
    "COMPONENT_RESERVE",
    "COMPONENT_EXECUTABLES",
]
