"""Retention sweeper for on-disk observability artifacts.

Three writers land artifacts under ``SPARK_RAPIDS_ML_TPU_DUMP_DIR``:
flight dumps (``flightdump_*.json`` files), profile captures
(``profiles/<id>/`` directories), and incident evidence bundles
(``incidents/<id>/`` directories). Before this module they accumulated
unboundedly — an incident storm (the exact situation that produces the
most artifacts) could fill the disk and take the serving tier down with
its own diagnostics.

``maybe_gc(kind)`` is the shared hook every writer calls after landing
an artifact: per artifact kind it enforces a **count cap** and a **byte
cap** (env-tunable), deleting **oldest first** until both hold. Every
removal is counted in ``sparkml_obs_artifacts_gc_total{kind}`` — GC is
itself observable, never silent. A per-kind minimum sweep interval
keeps a dump storm from paying a directory scan per dump.

Knobs:

* ``SPARK_RAPIDS_ML_TPU_OBS_ARTIFACT_MAX_COUNT`` — newest N artifacts
  kept per kind (default 200; <= 0 disables the count cap);
* ``SPARK_RAPIDS_ML_TPU_OBS_ARTIFACT_MAX_MB`` — byte budget per kind
  (default 512 MB; <= 0 disables the byte cap).

The sweeper never raises into a writer: a GC failure mid-incident is
worse than a full disk tomorrow.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

MAX_COUNT_ENV = "SPARK_RAPIDS_ML_TPU_OBS_ARTIFACT_MAX_COUNT"
MAX_MB_ENV = "SPARK_RAPIDS_ML_TPU_OBS_ARTIFACT_MAX_MB"

_DEFAULT_MAX_COUNT = 200
_DEFAULT_MAX_MB = 512.0
# a storm of writers shares one scan per kind per interval
_MIN_SWEEP_INTERVAL_S = 20.0

_last_sweep: Dict[str, float] = {}
_lock = threading.Lock()


def max_count() -> int:
    try:
        return int(float(os.environ.get(MAX_COUNT_ENV,
                                        _DEFAULT_MAX_COUNT)))
    except ValueError:
        return _DEFAULT_MAX_COUNT


def max_bytes() -> float:
    try:
        mb = float(os.environ.get(MAX_MB_ENV, _DEFAULT_MAX_MB))
    except ValueError:
        mb = _DEFAULT_MAX_MB
    return mb * 1024 * 1024


def _kind_root(kind: str) -> Tuple[Optional[str], bool]:
    """(root directory, entries-are-directories) for one artifact
    kind. Function-level imports: flight/profiler both call into this
    module, and a module-level import back at them would cycle."""
    if kind == "flight":
        from spark_rapids_ml_tpu.obs import flight

        return flight.dump_dir(), False
    if kind == "profile":
        from spark_rapids_ml_tpu.obs import profiler

        return profiler.profile_dir(), True
    if kind == "incident":
        from spark_rapids_ml_tpu.obs import incidents

        return incidents.incidents_dir(), True
    return None, False


def _entry_size(path: str, is_dir: bool) -> int:
    if not is_dir:
        try:
            return os.path.getsize(path)
        except OSError:
            return 0
    total = 0
    for root, _dirs, files in os.walk(path):
        for fname in files:
            try:
                total += os.path.getsize(os.path.join(root, fname))
            except OSError:
                continue
    return total


def _list_entries(root: str, dirs: bool) -> List[Dict[str, Any]]:
    """Artifacts under ``root`` as ``{path, mtime, bytes}``, oldest
    first. Files mode keeps only ``flightdump_*.json`` (never touch a
    ``.tmp`` mid-rename or anything another subsystem parked there);
    dirs mode takes every subdirectory."""
    entries: List[Dict[str, Any]] = []
    try:
        names = os.listdir(root)
    except OSError:
        return entries
    for name in names:
        path = os.path.join(root, name)
        is_dir = os.path.isdir(path)
        if dirs != is_dir:
            continue
        if not dirs and not (name.startswith("flightdump_")
                             and name.endswith(".json")):
            continue
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            continue
        entries.append({
            "path": path,
            "mtime": mtime,
            "bytes": _entry_size(path, is_dir),
        })
    entries.sort(key=lambda e: e["mtime"])
    return entries


def _remove(path: str, is_dir: bool) -> bool:
    try:
        if is_dir:
            shutil.rmtree(path, ignore_errors=True)
            return not os.path.exists(path)
        os.remove(path)
        return True
    except OSError:
        return False


def _count_removed(kind: str, n: int) -> None:
    if n <= 0:
        return
    try:
        from spark_rapids_ml_tpu.obs.metrics import get_registry

        get_registry().counter(
            "sparkml_obs_artifacts_gc_total",
            "on-disk observability artifacts removed by the retention "
            "sweeper (oldest-first past the count/byte caps)",
            ("kind",),
        ).inc(n, kind=kind)
    except Exception:
        pass  # GC accounting must never raise into a writer


def sweep_kind(kind: str,
               *,
               root: Optional[str] = None,
               dirs: Optional[bool] = None,
               keep_count: Optional[int] = None,
               keep_bytes: Optional[float] = None) -> int:
    """Enforce the caps for one kind NOW; returns how many artifacts
    were removed. The explicit-parameter form is what tests drive."""
    default_root, default_dirs = _kind_root(kind)
    root = root if root is not None else default_root
    dirs = dirs if dirs is not None else default_dirs
    if not root or not os.path.isdir(root):
        return 0
    cap_count = keep_count if keep_count is not None else max_count()
    cap_bytes = keep_bytes if keep_bytes is not None else max_bytes()
    entries = _list_entries(root, dirs)
    total_bytes = sum(e["bytes"] for e in entries)
    removed = 0
    # the artifact just written is the newest — the caps always leave
    # at least it in place
    while entries[:-1] and (
        (cap_count > 0 and len(entries) > cap_count)
        or (cap_bytes > 0 and total_bytes > cap_bytes)
    ):
        victim = entries.pop(0)
        if _remove(victim["path"], dirs):
            removed += 1
            total_bytes -= victim["bytes"]
        else:
            total_bytes -= victim["bytes"]  # unremovable: stop retrying
    _count_removed(kind, removed)
    return removed


def maybe_gc(kind: str, force: bool = False) -> int:
    """The writer-side hook: sweep ``kind`` unless one ran within the
    last ``_MIN_SWEEP_INTERVAL_S`` (a dump storm shares one scan).
    Never raises."""
    try:
        now = time.monotonic()
        with _lock:
            last = _last_sweep.get(kind, 0.0)
            if not force and now - last < _MIN_SWEEP_INTERVAL_S:
                return 0
            _last_sweep[kind] = now
        return sweep_kind(kind)
    except Exception:
        return 0


def gc_all(force: bool = False) -> Dict[str, int]:
    """Sweep every kind (ops tooling / tests)."""
    return {kind: maybe_gc(kind, force=force)
            for kind in ("flight", "profile", "incident")}


__all__ = [
    "MAX_COUNT_ENV",
    "MAX_MB_ENV",
    "gc_all",
    "max_bytes",
    "max_count",
    "maybe_gc",
    "sweep_kind",
]
