"""Compile/recompile/cost telemetry for jitted entry points.

``tracked_jit`` is a drop-in replacement for ``jax.jit`` (same kwargs) that
makes XLA compilation a first-class observable instead of an invisible tax:

* **lowering + compile wall-clock** per distinct signature, measured by
  driving the AOT path explicitly (``fn.lower(...).compile()``) so the
  numbers are the real jaxpr-trace/MLIR-lower and backend-compile costs,
  not first-call-minus-steady-state guesswork;
* **a recompile counter** keyed by the abstract signature — pytree
  structure, (shape, dtype, weak-type, sharding) of every array leaf, and
  the static argument values — with a loud "recompile storm" warning when
  one function accumulates more distinct signatures than
  ``SPARK_RAPIDS_ML_TPU_RECOMPILE_STORM`` (default 8): the classic symptom
  of un-padded batch tails or a static arg that should be dynamic;
* **HLO ``cost_analysis`` FLOPs / bytes-accessed and compiled memory
  sizes** per signature, so every executed call can attribute *analytic*
  FLOPs to the active fit (``FitReport.analytic_flops`` /
  ``flops_by_phase`` → per-phase analytic MFU) instead of the bench-only
  ``2·rows·cols²`` estimate.

Execution goes through the cached compiled executable, so tracking adds no
extra compiles: signature miss → one lower+compile (exactly what ``jax.jit``
would have paid) + cost analysis; signature hit → call the cached
executable. Tracer inputs (the wrapped function invoked inside another
traced computation) bypass tracking entirely and defer to the plain jitted
function. Any AOT-path surprise falls back to the plain jitted call for
that signature — telemetry must never break a kernel.
"""

from __future__ import annotations

import inspect
import os
import threading
import time
import warnings
import weakref
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

STORM_ENV = "SPARK_RAPIDS_ML_TPU_RECOMPILE_STORM"
_DEFAULT_STORM_THRESHOLD = 8


def storm_threshold() -> int:
    try:
        return int(os.environ.get(STORM_ENV, _DEFAULT_STORM_THRESHOLD))
    except ValueError:
        return _DEFAULT_STORM_THRESHOLD


@dataclass
class CompileEvent:
    """One observed compilation of one tracked function signature."""

    label: str
    key: Tuple
    lowering_seconds: float
    compile_seconds: float
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    memory: Dict[str, int] = field(default_factory=dict)
    recompile: bool = False
    fallback: bool = False

    def as_dict(self) -> Dict[str, Any]:
        return {
            "label": self.label,
            "lowering_seconds": self.lowering_seconds,
            "compile_seconds": self.compile_seconds,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "memory": dict(self.memory),
            "recompile": self.recompile,
            "fallback": self.fallback,
        }


class _CacheEntry:
    __slots__ = ("compiled", "flops", "bytes_accessed", "memory", "fallback")

    def __init__(self, compiled=None, flops=None, bytes_accessed=None,
                 memory=None, fallback=False):
        self.compiled = compiled
        self.flops = flops
        self.bytes_accessed = bytes_accessed
        self.memory = memory or {}
        self.fallback = fallback


# Global compile log (bounded) + per-label aggregate, for tests, dumps and
# `compile_stats()`.
_log_lock = threading.Lock()
_compile_log: list = []
_COMPILE_LOG_CAP = 512


def _log_event(event: CompileEvent) -> None:
    with _log_lock:
        _compile_log.append(event)
        if len(_compile_log) > _COMPILE_LOG_CAP:
            del _compile_log[: len(_compile_log) - _COMPILE_LOG_CAP]


def compile_log():
    """The recent ``CompileEvent`` history (newest last)."""
    with _log_lock:
        return list(_compile_log)


def compile_stats() -> Dict[str, Dict[str, Any]]:
    """Aggregate per-label compile accounting across all tracked functions:
    ``{label: {compiles, recompiles, compile_seconds, flops, signatures}}``
    (``signatures`` counts DISTINCT signatures seen in the log window)."""
    out: Dict[str, Dict[str, Any]] = {}
    seen_keys: Dict[str, set] = {}
    for ev in compile_log():
        agg = out.setdefault(ev.label, {
            "compiles": 0, "recompiles": 0, "compile_seconds": 0.0,
            "flops": 0.0, "signatures": 0,
        })
        agg["compiles"] += 1
        agg["recompiles"] += int(ev.recompile)
        agg["compile_seconds"] += ev.lowering_seconds + ev.compile_seconds
        if ev.flops:
            agg["flops"] += ev.flops
        keys = seen_keys.setdefault(ev.label, set())
        try:
            keys.add(ev.key)
        except TypeError:
            keys.add(repr(ev.key))
        agg["signatures"] = len(keys)
    return out


def signature_count(label_prefix: str) -> int:
    """Distinct compiled signatures across tracked functions whose label
    starts with ``label_prefix`` — the warmup-ladder assertion helper for
    the serving tier, where the precision × bucket ladder registers one
    label per variant (``pca_transform_serve``, ``pca_transform_bf16``,
    ...) and one signature per bucket under each."""
    return sum(
        stats["signatures"]
        for label, stats in compile_stats().items()
        if label.startswith(label_prefix)
    )


def reset_compile_log() -> None:
    with _log_lock:
        _compile_log.clear()


# Live TrackedJit instances (weak: module-level kernels pin themselves
# through their module; runtime-built programs must stay collectable).
# clear_all_signature_caches() is the warm-restart rehearsal switch: it
# makes every tracked function forget its in-memory executables, so the
# next call exercises the persistent disk cache exactly like a freshly
# restarted process would.
_instances: "weakref.WeakSet[TrackedJit]" = weakref.WeakSet()


def clear_all_signature_caches() -> None:
    """Drop every live tracked function's in-memory signature cache
    (the persistent disk cache, if configured, is untouched). Used by
    the warm-restart integration test and the cold-start bench to
    simulate a process restart in-process."""
    for inst in list(_instances):
        inst.clear_cache()


def _leaf_sig(x) -> Tuple:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        # Shardings are hashable with value equality — used directly in the
        # key (repr() would stringify the whole mesh on every hot call).
        sharding = getattr(x, "sharding", None)
        if sharding is not None:
            try:
                hash(sharding)
            except TypeError:
                sharding = repr(sharding)
        return (
            "arr",
            tuple(int(s) for s in shape),
            str(dtype),
            bool(getattr(x, "weak_type", False)),
            sharding,
        )
    if isinstance(x, (bool, int, float, complex)):
        # value-independent: jit traces python scalars as (weak) 0-d arrays,
        # so a changed value is NOT a recompile
        return ("py", type(x).__name__)
    if x is None:
        return ("none",)
    return ("obj", type(x).__name__)


def _hashable(value) -> Any:
    try:
        hash(value)
        return value
    except TypeError:
        return repr(value)


def _cost_fields(compiled) -> Tuple[Optional[float], Optional[float]]:
    """(flops, bytes_accessed) from ``Compiled.cost_analysis()`` — which
    returns a list-of-dicts on some backends, a dict on others, and may
    report -1 for unknowns."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return None, None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return None, None

    def _pick(name):
        v = cost.get(name)
        if v is None or v < 0:
            return None
        return float(v)

    return _pick("flops"), _pick("bytes accessed")


def _memory_fields(compiled) -> Dict[str, int]:
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    out = {}
    for name in ("generated_code_size_in_bytes", "argument_size_in_bytes",
                 "output_size_in_bytes", "temp_size_in_bytes",
                 "alias_size_in_bytes"):
        v = getattr(mem, name, None)
        if v is not None:
            out[name] = int(v)
    return out


class TrackedJit:
    """The ``tracked_jit`` wrapper object. Callable like the jitted fn;
    exposes ``stats()`` for introspection."""

    def __init__(self, fn, *, label: Optional[str] = None,
                 storm_threshold: Optional[int] = None, **jit_kwargs):
        import jax

        self._fn = fn
        self.label = label or getattr(fn, "__qualname__", None) or getattr(
            fn, "__name__", "jit_fn"
        )
        self._jitted = jax.jit(fn, **jit_kwargs)
        # Signature-less callables (shard_map wrappers, *args shims) run in
        # "generic" mode: no canonicalization, statics located by name only.
        try:
            self._signature = inspect.signature(fn)
            self._params = list(self._signature.parameters.values())
            if any(p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
                   for p in self._params):
                self._signature = None
                self._params = []
        except (ValueError, TypeError):
            self._signature = None
            self._params = []
        self._storm_threshold = storm_threshold
        self._storm_warned = False
        self._lock = threading.Lock()
        # Serializes first-compile per instance so concurrent first calls
        # with one signature cannot double-compile / double-count.
        self._compile_lock = threading.Lock()
        self._cache: Dict[Any, _CacheEntry] = {}

        static_names = set()
        names = jit_kwargs.get("static_argnames") or ()
        if isinstance(names, str):
            names = (names,)
        static_names.update(names)
        for i in jit_kwargs.get("static_argnums") or ():
            if 0 <= i < len(self._params):
                static_names.add(self._params[i].name)
        self._static_names = frozenset(static_names)
        self._static_positions = frozenset(
            i for i, p in enumerate(self._params)
            if p.name in self._static_names
        )
        # functools.wraps surface so @tracked_jit looks like the function
        for attr in ("__name__", "__qualname__", "__doc__", "__module__"):
            try:
                setattr(self, attr, getattr(fn, attr))
            except (AttributeError, TypeError):
                pass
        self.__wrapped__ = fn
        _instances.add(self)

    # -- introspection -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "label": self.label,
                "signatures": len(self._cache),
                "fallbacks": sum(1 for e in self._cache.values()
                                 if e.fallback),
            }

    def clear_cache(self) -> None:
        with self._lock:
            self._cache.clear()
            self._storm_warned = False

    # AOT passthroughs so call sites that reach for the raw jit still work.
    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def prime(self, *args, **kwargs) -> bool:
        """Ensure the signature for these (abstract) arguments is
        compiled — via the persistent executable cache when configured,
        else a fresh AOT compile — WITHOUT executing the program.

        The warm-restart replay path: executing a zero batch per bucket
        just to reach the compiler wastes restart time (and on a real
        chip, device time); priming loads/compiles the executable and
        returns. Returns False when the signature had to fall back to
        the plain jitted path (it will compile lazily on first call)."""
        import jax

        if any(isinstance(leaf, jax.core.Tracer)
               for leaf in jax.tree_util.tree_leaves((args, kwargs))):
            return False
        try:
            cargs, ckwargs = self._canonicalize(args, kwargs)
            key = self._signature_key(cargs, ckwargs)
        except Exception:
            return False
        with self._lock:
            entry = self._cache.get(key)
        if entry is None:
            with self._compile_lock:
                with self._lock:
                    entry = self._cache.get(key)
                if entry is None:
                    entry = self._compile_entry(key, cargs, ckwargs)
                    with self._lock:
                        self._cache[key] = entry
                        n_signatures = len(self._cache)
                    self._maybe_warn_storm(n_signatures)
        return not entry.fallback and entry.compiled is not None

    # -- the call path -----------------------------------------------------

    def _canonicalize(self, args, kwargs):
        # Normalize positional-vs-keyword passing of the same parameter so
        # both spellings share one signature key. Defaults are NOT applied:
        # jit never sees unpassed parameters (their defaults resolve inside
        # the traced function — they may be non-array values like solver
        # strings), so neither may we.
        if self._signature is None:
            return args, dict(kwargs)
        bound = self._signature.bind(*args, **kwargs)
        return bound.args, bound.kwargs

    def _split_dynamic(self, cargs, ckwargs):
        dyn_args = tuple(a for i, a in enumerate(cargs)
                         if i not in self._static_positions)
        dyn_kwargs = {k: v for k, v in ckwargs.items()
                      if k not in self._static_names}
        return dyn_args, dyn_kwargs

    def _signature_key(self, cargs, ckwargs):
        import jax

        dyn_args, dyn_kwargs = self._split_dynamic(cargs, ckwargs)
        leaves, treedef = jax.tree_util.tree_flatten((dyn_args, dyn_kwargs))
        statics = tuple(
            (self._params[i].name, _hashable(cargs[i]))
            for i in sorted(self._static_positions) if i < len(cargs)
        ) + tuple(
            (k, _hashable(v)) for k, v in sorted(ckwargs.items())
            if k in self._static_names
        )
        return (treedef, tuple(_leaf_sig(x) for x in leaves), statics)

    def _maybe_warn_storm(self, n_signatures: int) -> None:
        threshold = (self._storm_threshold if self._storm_threshold
                     is not None else storm_threshold())
        if n_signatures >= threshold and not self._storm_warned:
            self._storm_warned = True
            warnings.warn(
                f"recompile storm: {self.label} has compiled "
                f"{n_signatures} distinct signatures (threshold "
                f"{threshold}). Usual causes: un-padded batch tails "
                f"(pad + mask to a fixed shape) or a static argument that "
                f"changes per call. Set {STORM_ENV} to tune.",
                RuntimeWarning,
                stacklevel=3,
            )
            try:
                from spark_rapids_ml_tpu.obs.metrics import get_registry

                get_registry().counter(
                    "sparkml_recompile_storms_total",
                    "tracked functions crossing the recompile-storm "
                    "threshold", ("fn",),
                ).inc(fn=self.label)
            except Exception:
                pass

    def _record_compile(self, event: CompileEvent) -> None:
        _log_event(event)
        try:
            from spark_rapids_ml_tpu.obs.metrics import get_registry
            from spark_rapids_ml_tpu.obs.report import current_fit

            reg = get_registry()
            reg.counter(
                "sparkml_xla_compiles_total",
                "XLA compilations of tracked jitted functions", ("fn",),
            ).inc(fn=self.label)
            if event.recompile:
                reg.counter(
                    "sparkml_xla_recompiles_total",
                    "re-compilations (new signature after the first)",
                    ("fn",),
                ).inc(fn=self.label)
            reg.histogram(
                "sparkml_xla_compile_seconds",
                "lowering+backend-compile wall-clock", ("fn",),
            ).observe(event.lowering_seconds + event.compile_seconds,
                      fn=self.label)
            current_fit().record_compile(
                self.label,
                event.lowering_seconds + event.compile_seconds,
                recompile=event.recompile,
            )
            from spark_rapids_ml_tpu.obs.serving import current_transform

            current_transform().record_compile(
                self.label,
                event.lowering_seconds + event.compile_seconds,
                recompile=event.recompile,
            )
        except Exception:
            pass  # telemetry must never break a kernel

    def _record_execution(self, entry: _CacheEntry) -> None:
        try:
            from spark_rapids_ml_tpu.obs.report import current_fit

            current_fit().record_program(
                self.label, entry.flops, entry.bytes_accessed
            )
            from spark_rapids_ml_tpu.obs.serving import current_transform

            current_transform().record_program(
                self.label, entry.flops, entry.bytes_accessed
            )
            from spark_rapids_ml_tpu.obs import fitmon

            fitmon.record_program(
                self.label, entry.flops, entry.bytes_accessed
            )
        except Exception:
            pass

    def _persistent_cache(self):
        """The process's persistent executable cache, or None. Resolved
        per compile (not per call — the miss path already pays a full
        XLA compile, the hit path one small file read): a cache the
        operator enables mid-process must start serving hits."""
        try:
            from spark_rapids_ml_tpu.obs.aotcache import (
                get_executable_cache,
            )

            return get_executable_cache()
        except Exception:
            return None  # cache plumbing must never break a kernel

    def _compile_entry(self, key, cargs, ckwargs) -> _CacheEntry:
        recompile = bool(self._cache)
        # The persistent executable cache (obs/aotcache.py): a disk hit
        # skips lower+compile entirely — no CompileEvent is recorded, so
        # signature_count() stays at 0 across a warm restart (the
        # zero-fresh-compiles assertion the cold-start bench makes).
        cache = self._persistent_cache()
        if cache is not None:
            loaded = cache.load(self.label, key)
            if loaded is not None and loaded.compiled is not None:
                return _CacheEntry(
                    compiled=loaded.compiled, flops=loaded.flops,
                    bytes_accessed=loaded.bytes_accessed,
                    memory=loaded.memory,
                )
        t0 = time.perf_counter()
        try:
            lowered = self._jitted.lower(*cargs, **ckwargs)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            t2 = time.perf_counter()
        except Exception:
            # AOT path unavailable for this signature (exotic pytree,
            # backend quirk): fall back to the plain jitted call forever
            # for this key, timing its first call as the compile cost.
            t1 = time.perf_counter()
            entry = _CacheEntry(fallback=True)
            self._record_compile(CompileEvent(
                label=self.label, key=key,
                lowering_seconds=t1 - t0, compile_seconds=0.0,
                recompile=recompile, fallback=True,
            ))
            return entry
        flops, nbytes = _cost_fields(compiled)
        memory = _memory_fields(compiled)
        entry = _CacheEntry(compiled=compiled, flops=flops,
                            bytes_accessed=nbytes, memory=memory)
        self._record_compile(CompileEvent(
            label=self.label, key=key,
            lowering_seconds=t1 - t0, compile_seconds=t2 - t1,
            flops=flops, bytes_accessed=nbytes, memory=memory,
            recompile=recompile,
        ))
        if cache is not None:
            # store failures are counted inside the cache and ignored:
            # the in-memory entry above is already good
            cache.store(self.label, key, compiled, flops=flops,
                        bytes_accessed=nbytes, memory=memory,
                        compile_seconds=(t1 - t0) + (t2 - t1))
        return entry

    def __call__(self, *args, **kwargs):
        import jax

        # Inside another trace (vmap/jit/scan): stay out of the way.
        if any(isinstance(leaf, jax.core.Tracer)
               for leaf in jax.tree_util.tree_leaves((args, kwargs))):
            return self._jitted(*args, **kwargs)
        try:
            cargs, ckwargs = self._canonicalize(args, kwargs)
            key = self._signature_key(cargs, ckwargs)
        except Exception:
            return self._jitted(*args, **kwargs)

        with self._lock:
            entry = self._cache.get(key)
        if entry is None:
            with self._compile_lock:
                with self._lock:
                    entry = self._cache.get(key)
                if entry is None:
                    entry = self._compile_entry(key, cargs, ckwargs)
                    with self._lock:
                        self._cache[key] = entry
                        n_signatures = len(self._cache)
                    self._maybe_warn_storm(n_signatures)

        self._record_execution(entry)
        if entry.fallback or entry.compiled is None:
            return self._jitted(*cargs, **ckwargs)
        dyn_args, dyn_kwargs = self._split_dynamic(cargs, ckwargs)
        try:
            return entry.compiled(*dyn_args, **dyn_kwargs)
        except Exception:
            # Executable/argument mismatch we failed to predict (e.g. a
            # sharding nuance outside the signature key): permanently fall
            # back to the plain jitted path for this signature.
            with self._lock:
                entry.fallback = True
            return self._jitted(*cargs, **ckwargs)


def tracked_jit(fn=None, *, label: Optional[str] = None,
                storm_threshold: Optional[int] = None, **jit_kwargs):
    """``jax.jit`` with compile/recompile/cost telemetry (see module doc).

    Usable bare (``@tracked_jit``), with jit kwargs
    (``@tracked_jit(static_argnames=("k",), donate_argnums=(0,))``), or via
    ``partial`` exactly like ``jax.jit``.
    """
    if fn is None:
        return lambda f: TrackedJit(f, label=label,
                                    storm_threshold=storm_threshold,
                                    **jit_kwargs)
    return TrackedJit(fn, label=label, storm_threshold=storm_threshold,
                      **jit_kwargs)


def track_compiles(fn, **jit_kwargs) -> TrackedJit:
    """Imperative form of ``tracked_jit`` for call sites that build their
    jitted function at runtime (``track_compiles(f, static_argnames=...)``)."""
    if isinstance(fn, TrackedJit):
        return fn
    return TrackedJit(fn, **jit_kwargs)


def peak_flops_per_second() -> Optional[float]:
    """This process's per-chip peak dense FLOP/s (bf16), or None when the
    device kind has no published number (CPU included) — the denominator
    for every analytic-MFU figure."""
    try:
        import jax

        from spark_rapids_ml_tpu.utils.platform import PEAK_FLOPS_BF16

        device = jax.devices()[0]
        if device.platform == "cpu":
            return None
        return PEAK_FLOPS_BF16.get(str(device.device_kind))
    except Exception:
        return None


def analytic_mfu(flops: Optional[float],
                 seconds: Optional[float]) -> Optional[float]:
    """Analytic MFU: HLO cost-analysis FLOPs over wall-clock over the
    chip's peak. None when any input (or the peak) is unknown."""
    if not flops or not seconds or seconds <= 0:
        return None
    peak = peak_flops_per_second()
    if not peak:
        return None
    return flops / seconds / peak
