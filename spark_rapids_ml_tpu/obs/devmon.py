"""Per-device fleet monitor: memory gauges + batch-time attribution.

The ROADMAP's multi-chip serving item needs "per-device occupancy/queue
metrics in the existing obs/ registry" before the mesh PR lands, and the
hot-path latency item needs device-time evidence to attribute wins. This
module publishes both, per ``jax.devices()`` entry:

* ``sample()`` — ``memory_stats()`` in-use / limit / peak gauges labeled
  by device id (``sparkml_device_mem_bytes_in_use{device,source}`` etc.,
  ``source="pjrt"``). Backends without PJRT stats (CPU) fall back to the
  host RSS reader in ``obs.memory`` (``source="host_rss"``) — a host
  number is never mistaken for an HBM number. Registered as a sampler
  collector by ``obs.tsdb.start_sampling``, so every gauge gets history.
* ``note_batch(model, seconds)`` — per-device batch-time attribution,
  wired from ``serve/batching.py``: every coalesced batch's execute time
  lands in ``sparkml_serve_device_batch_seconds_total{model,device}``
  (+ a batches counter), so per-chip occupancy is
  ``rate(batch_seconds)`` straight out of the history store —
  ``occupancy(window)`` computes exactly that. Never raises into the
  batcher: attribution is telemetry, not control flow.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from spark_rapids_ml_tpu.obs import memory as memory_mod
from spark_rapids_ml_tpu.obs.metrics import get_registry


def _devices() -> List[Any]:
    try:
        import jax

        return list(jax.devices())
    except Exception:
        return []


def _profiler_transition_pending() -> bool:
    try:
        from spark_rapids_ml_tpu.obs import profiler

        return profiler.jax_transition_pending()
    except Exception:
        return False


class DeviceMonitor:
    """One process-wide monitor over the local device fleet."""

    def __init__(self, devices_fn=_devices):
        self._devices_fn = devices_fn
        self._lock = threading.Lock()
        self._default_device: Optional[str] = None
        # last sample() reading per device label — the placement tier's
        # memory-pressure input (serve/placement.py) reads it without
        # re-polling PJRT on the request path
        self._last_sample: Dict[str, Dict[str, Any]] = {}
        reg = get_registry()
        self._m_in_use = reg.gauge(
            "sparkml_device_mem_bytes_in_use",
            "per-device bytes in use (PJRT memory_stats; host RSS on "
            "backends without device stats)", ("device", "source"),
        )
        self._m_limit = reg.gauge(
            "sparkml_device_mem_bytes_limit",
            "per-device memory limit (PJRT memory_stats)",
            ("device", "source"),
        )
        self._m_peak = reg.gauge(
            "sparkml_device_mem_peak_bytes",
            "per-device peak bytes in use (PJRT high-watermark; host RSS "
            "peak on backends without device stats)", ("device", "source"),
        )
        self._m_batch_seconds = reg.counter(
            "sparkml_serve_device_batch_seconds_total",
            "device wall-clock attributed to coalesced serve batches — "
            "rate() of this series is per-device occupancy",
            ("model", "device"),
        )
        self._m_batches = reg.counter(
            "sparkml_serve_device_batches_total",
            "coalesced serve batches attributed per device",
            ("model", "device"),
        )
        self._m_overhead = reg.counter(
            "sparkml_obs_overhead_seconds_total",
            "wall-clock the observability layer spends watching "
            "(sampler sweeps, device monitor, profiler bookkeeping)",
            ("component",),
        )

    # -- memory gauges -----------------------------------------------------

    def sample(self) -> List[Dict[str, Any]]:
        """Publish the fleet's memory gauges; returns what was read.

        One entry per device: PJRT stats when the backend has them, the
        process RSS (tagged ``host_rss``) otherwise — a CPU fleet still
        shows a concrete, visibly host-sourced number per device."""
        t0 = time.perf_counter()
        out: List[Dict[str, Any]] = []
        if _profiler_transition_pending():
            # PJRT polls (memory_stats) stall jax.profiler.start_trace
            # on some backends; skip this sweep only while start/stop
            # is actually in flight — gauges keep updating through the
            # capture window itself (a 5-minute capture must not hide
            # the very memory ramp the operator is profiling).
            return out
        rss: Optional[int] = None
        peak_rss: Optional[int] = None
        for device in self._devices_fn():
            label = str(device)
            stats = memory_mod.device_memory_stats(device)
            if stats is not None:
                in_use = int(stats.get("bytes_in_use", 0))
                peak = int(stats.get("peak_bytes_in_use", in_use))
                entry: Dict[str, Any] = {
                    "device": label, "source": "pjrt",
                    "bytes_in_use": in_use, "peak_bytes_in_use": peak,
                }
                self._m_in_use.set(in_use, device=label, source="pjrt")
                self._m_peak.set(peak, device=label, source="pjrt")
                if "bytes_limit" in stats:
                    limit = int(stats["bytes_limit"])
                    entry["bytes_limit"] = limit
                    self._m_limit.set(limit, device=label, source="pjrt")
            else:
                # in_use must be CURRENT RSS (goes down on free — a
                # spike and a leak look different in the history),
                # peak is the lifetime watermark; ru_maxrss only when
                # /proc is unavailable (then in_use IS the watermark).
                if rss is None:
                    peak_rss = memory_mod.host_peak_rss_bytes() or 0
                    rss = (memory_mod.host_current_rss_bytes()
                           or peak_rss)
                entry = {
                    "device": label, "source": "host_rss",
                    "bytes_in_use": rss, "peak_bytes_in_use": peak_rss,
                }
                self._m_in_use.set(rss, device=label, source="host_rss")
                self._m_peak.set(peak_rss, device=label,
                                 source="host_rss")
            out.append(entry)
        with self._lock:
            for entry in out:
                self._last_sample[entry["device"]] = entry
        try:
            self._m_overhead.inc(time.perf_counter() - t0,
                                 component="devmon")
        except Exception:
            pass
        return out

    def last_sample(self, device: str) -> Optional[Dict[str, Any]]:
        """The most recent ``sample()`` reading for one device label
        (None before any sweep has run)."""
        with self._lock:
            return self._last_sample.get(device)

    def memory_pressure(self, device: str) -> Optional[float]:
        """in-use / limit for one device from the last sample, or None
        when unknowable — no sample yet, no limit reported, or the
        reading is host RSS (a process-wide number is not a per-device
        verdict; the placement tier must not drain every replica at
        once off one host gauge)."""
        entry = self.last_sample(device)
        if entry is None or entry.get("source") != "pjrt":
            return None
        limit = entry.get("bytes_limit")
        if not limit:
            return None
        return float(entry.get("bytes_in_use", 0)) / float(limit)

    # -- batch-time attribution --------------------------------------------

    def default_device_label(self) -> str:
        """The device the single-replica batcher runs on (cached). The
        mesh-serving PR passes an explicit device per dispatch; until
        then every batch attributes to the process default device."""
        with self._lock:
            if self._default_device is None:
                try:
                    devices = self._devices_fn()
                except Exception:
                    devices = []
                self._default_device = (str(devices[0]) if devices
                                        else "unknown")
            return self._default_device

    def note_batch(self, model: str, seconds: float,
                   device: Optional[str] = None) -> None:
        """Attribute one coalesced batch's device time. NEVER raises —
        this is called from the batcher's hot path."""
        try:
            label = device or self.default_device_label()
            self._m_batch_seconds.inc(max(float(seconds), 0.0),
                                      model=model, device=label)
            self._m_batches.inc(model=model, device=label)
        except Exception:
            pass  # attribution must never fail a batch

    def occupancy(self, window: float = 60.0) -> Dict[str, float]:
        """Per-device busy fraction over the trailing window, computed
        as ``rate(sparkml_serve_device_batch_seconds_total)`` from the
        history store (empty dict before any sampling)."""
        from spark_rapids_ml_tpu.obs import tsdb

        store = tsdb.get_tsdb()
        out: Dict[str, float] = {}
        for series in store.rate_points(
            "sparkml_serve_device_batch_seconds_total", window=window,
        ):
            device = series["labels"].get("device", "unknown")
            points = series["points"]
            if not points:
                continue
            mean = sum(v for _ts, v in points) / len(points)
            out[device] = out.get(device, 0.0) + mean
        return out


_monitor: Optional[DeviceMonitor] = None
_monitor_lock = threading.Lock()


def get_device_monitor() -> DeviceMonitor:
    global _monitor
    with _monitor_lock:
        if _monitor is None:
            _monitor = DeviceMonitor()
        return _monitor


def reset_device_monitor() -> None:
    """Drop the cached monitor (tests that reset the registry)."""
    global _monitor
    with _monitor_lock:
        _monitor = None


__all__ = [
    "DeviceMonitor",
    "get_device_monitor",
    "reset_device_monitor",
]
