"""Thread-safe metrics registry: counters, gauges, histograms with labels.

The reference plugin has NO metrics at all — its only signals are Spark
``Logging`` lines and NVTX ranges (SURVEY.md §3.4–3.5, §5). This registry is
the missing accounting layer the tuning papers lean on (Alchemist's
per-collective cost model, arxiv 1805.11800; the TPU distributed linear
algebra accounting in arxiv 2112.09017): every fit increments a small set of
well-known series (``sparkml_fits_total``, ``sparkml_fit_seconds``,
``sparkml_collective_bytes_total``, …) that can be scraped as Prometheus
text or embedded as a JSON snapshot in bench records.

Design constraints:

* stdlib only (no ``prometheus_client`` dependency — the container may not
  have it, and the exposition format is four lines of spec);
* thread-safe — Spark-style executors fit from worker threads;
* labels are kwargs at observation time; each label-set gets its own child
  series, exactly Prometheus' data model.
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Latency-oriented default buckets (seconds): sub-ms compile-cache hits up
# to multi-minute full-scale fits.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    60.0, 120.0, 300.0,
)


def _escape_label_value(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _format_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Metric:
    """Base: one named family holding one child per label-value tuple."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str, labelnames: Tuple[str, ...]):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"metric {self.name} expects labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}"
            )
        return tuple(str(labels[ln]) for ln in self.labelnames)

    def _child(self, labels: Dict[str, str]):
        key = self._key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                self._children[key] = child
            return child

    def _new_child(self):  # pragma: no cover - overridden
        raise NotImplementedError

    def _samples(self) -> Iterable[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return list(self._children.items())

    def _label_dict(self, key: Tuple[str, ...]) -> Dict[str, str]:
        return dict(zip(self.labelnames, key))


class Counter(_Metric):
    """Monotonically increasing count (``.inc(amount, **labels)``)."""

    kind = "counter"

    class _Child:
        __slots__ = ("value", "lock")

        def __init__(self):
            self.value = 0.0
            self.lock = threading.Lock()

    def _new_child(self):
        return Counter._Child()

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        child = self._child(labels)
        with child.lock:
            child.value += amount

    def value(self, **labels) -> float:
        child = self._child(labels)
        with child.lock:
            return child.value

    def total(self) -> float:
        """Sum across every labeled child — the family-wide count,
        without walking a full registry snapshot."""
        total = 0.0
        for _key, child in self._samples():
            with child.lock:
                total += child.value
        return total


class Gauge(_Metric):
    """Point-in-time value (``.set(v, **labels)`` / ``.inc``/``.dec``)."""

    kind = "gauge"

    class _Child:
        __slots__ = ("value", "lock")

        def __init__(self):
            self.value = 0.0
            self.lock = threading.Lock()

    def _new_child(self):
        return Gauge._Child()

    def set(self, value: float, **labels) -> None:
        child = self._child(labels)
        with child.lock:
            child.value = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        child = self._child(labels)
        with child.lock:
            child.value += amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        child = self._child(labels)
        with child.lock:
            return child.value


class Histogram(_Metric):
    """Cumulative-bucket histogram (``.observe(v, **labels)``)."""

    kind = "histogram"

    class _Child:
        __slots__ = ("counts", "sum", "count", "lock")

        def __init__(self, n_buckets: int):
            self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
            self.sum = 0.0
            self.count = 0
            self.lock = threading.Lock()

    def __init__(
        self,
        name: str,
        help_text: str,
        labelnames: Tuple[str, ...] = (),
        buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
    ):
        super().__init__(name, help_text, labelnames)
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bounds

    def _new_child(self):
        return Histogram._Child(len(self.buckets))

    def observe(self, value: float, **labels) -> None:
        child = self._child(labels)
        with child.lock:
            child.sum += float(value)
            child.count += 1
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    child.counts[i] += 1
                    break

    def snapshot_child(self, **labels) -> Dict[str, object]:
        child = self._child(labels)
        with child.lock:
            cumulative = {}
            running = 0
            for bound, c in zip(self.buckets, child.counts):
                running += c
                cumulative[_format_value(bound)] = running
            cumulative["+Inf"] = child.count
            return {
                "count": child.count,
                "sum": child.sum,
                "buckets": cumulative,
            }


class Summary(_Metric):
    """Quantile summary backed by a mergeable streaming sketch.

    Where ``Histogram`` answers with fixed-bucket counts, ``Summary``
    answers with true quantiles at a documented relative error
    (``obs.quantiles.QuantileSketch``, DDSketch-style): ``observe`` is
    O(1), ``quantile(q)`` is exact-rank over log buckets. The Prometheus
    exposition emits ``name{quantile="0.5"}``-style lines (summary type)
    alongside whatever ``_bucket`` series the histograms export.

    ``observe(value, trace_id=...)`` additionally files a **trace-id
    exemplar**: each child keeps the ``EXEMPLAR_CAPACITY`` slowest
    observations with their trace ids, so "the p99 got worse" comes with
    the exact requests to go look at. Exemplars appear in ``snapshot()``
    and as ``# exemplar: <name>{labels} trace_id="..."`` comment lines
    in the text exposition (comments, because the endpoint advertises
    text format 0.0.4 — inline OpenMetrics ``# {...}`` annotations would
    abort a 0.0.4 scrape).
    """

    kind = "summary"
    DEFAULT_QUANTILES: Tuple[float, ...] = (0.5, 0.95, 0.99)
    EXEMPLAR_CAPACITY = 5

    class _Child:
        __slots__ = ("sketch", "exemplars", "lock")

        def __init__(self, alpha: float, max_bins: int):
            from spark_rapids_ml_tpu.obs.quantiles import QuantileSketch

            self.sketch = QuantileSketch(alpha=alpha, max_bins=max_bins)
            # slowest-N ring: [(value, trace_id, unix_ts)] kept sorted
            # ascending so [0] is the cheapest candidate to evict
            self.exemplars: List[Tuple[float, str, float]] = []
            self.lock = threading.Lock()

    def __init__(
        self,
        name: str,
        help_text: str,
        labelnames: Tuple[str, ...] = (),
        alpha: float = 0.01,
        max_bins: int = 4096,
        quantiles: Tuple[float, ...] = DEFAULT_QUANTILES,
    ):
        super().__init__(name, help_text, labelnames)
        self.alpha = float(alpha)
        self.max_bins = int(max_bins)
        self.quantiles = tuple(float(q) for q in quantiles)

    def _new_child(self):
        return Summary._Child(self.alpha, self.max_bins)

    def observe(self, value: float, trace_id: Optional[str] = None,
                **labels) -> None:
        child = self._child(labels)
        child.sketch.observe(value)
        if trace_id:
            self._note_exemplar(child, float(value), str(trace_id))

    def _note_exemplar(self, child: "_Child", value: float,
                       trace_id: str) -> None:
        with child.lock:
            ring = child.exemplars
            if len(ring) >= self.EXEMPLAR_CAPACITY and value <= ring[0][0]:
                return  # faster than every kept exemplar — not slowest-N
            ring.append((value, trace_id, time.time()))
            ring.sort(key=lambda e: e[0])
            if len(ring) > self.EXEMPLAR_CAPACITY:
                del ring[0]

    def exemplars(self, **labels) -> List[Dict[str, object]]:
        """The slowest-N exemplars for one label set, slowest first."""
        child = self._child(labels)
        with child.lock:
            ring = list(child.exemplars)
        return [
            {"value": v, "trace_id": tid, "unix_ts": ts}
            for v, tid, ts in reversed(ring)
        ]

    def quantile(self, q: float, **labels):
        return self._child(labels).sketch.quantile(q)

    def sketch(self, **labels):
        """The underlying ``QuantileSketch`` for one label set (merge it,
        serialize it, embed it in a bench record)."""
        return self._child(labels).sketch

    def sketch_states(self) -> List[Tuple[Dict[str, str], Dict[str, object]]]:
        """Every child's serialized sketch state as
        ``[(labels, state), ...]`` — the fleet-export transport
        (``obs.federation``): states merge losslessly across hosts
        where already-computed percentiles could only be averaged."""
        return [
            (self._label_dict(key), child.sketch.to_dict())
            for key, child in self._samples()
        ]

    def snapshot_child(self, **labels) -> Dict[str, object]:
        sketch = self._child(labels).sketch
        return {
            "count": sketch.count,
            "sum": sketch.sum,
            "alpha": self.alpha,
            "quantiles": {
                _format_value(q): sketch.quantile(q) for q in self.quantiles
            },
            "exemplars": self.exemplars(**labels),
        }


class MetricsRegistry:
    """Process-wide metric family registry.

    ``counter``/``gauge``/``histogram`` are get-or-create: repeated calls
    with the same name return the SAME family (so call sites never need to
    coordinate), but a name re-registered as a different kind or label set
    is a programming error and raises.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help_text, labelnames, **kwargs):
        labelnames = tuple(labelnames)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or (
                    existing.labelnames != labelnames
                ):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind} with labels {existing.labelnames}"
                    )
                return existing
            metric = cls(name, help_text, labelnames, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name, help_text="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help_text, labelnames)

    def gauge(self, name, help_text="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labelnames)

    def histogram(
        self, name, help_text="", labelnames=(), buckets=DEFAULT_BUCKETS
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help_text, labelnames, buckets=buckets
        )

    def summary(
        self, name, help_text="", labelnames=(), alpha=0.01,
        max_bins=4096, quantiles=Summary.DEFAULT_QUANTILES,
    ) -> Summary:
        return self._get_or_create(
            Summary, name, help_text, labelnames, alpha=alpha,
            max_bins=max_bins, quantiles=quantiles,
        )

    def reset(self) -> None:
        """Drop every family (tests / fresh bench windows)."""
        with self._lock:
            self._metrics.clear()

    def families(self):
        with self._lock:
            return list(self._metrics.values())

    # -- exposition --------------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        """JSON-safe snapshot of every series (embedded in bench records)."""
        out: Dict[str, object] = {}
        for metric in self.families():
            samples = []
            for key, _child in metric._samples():
                labels = metric._label_dict(key)
                if isinstance(metric, (Histogram, Summary)):
                    samples.append(
                        {"labels": labels,
                         **metric.snapshot_child(**labels)}
                    )
                else:
                    samples.append(
                        {"labels": labels, "value": metric.value(**labels)}
                    )
            out[metric.name] = {
                "type": metric.kind,
                "help": metric.help,
                "samples": samples,
            }
        return out

    def to_json(self, **dumps_kwargs) -> str:
        return json.dumps(self.snapshot(), **dumps_kwargs)

    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines = []
        for metric in self.families():
            if metric.help:
                lines.append(f"# HELP {metric.name} {metric.help}")
            lines.append(f"# TYPE {metric.name} {metric.kind}")
            for key, _child in metric._samples():
                labels = metric._label_dict(key)
                label_str = ",".join(
                    f'{k}="{_escape_label_value(v)}"'
                    for k, v in labels.items()
                )
                if isinstance(metric, Histogram):
                    snap = metric.snapshot_child(**labels)
                    for le, cum in snap["buckets"].items():
                        bl = (label_str + "," if label_str else "") + \
                            f'le="{le}"'
                        lines.append(
                            f"{metric.name}_bucket{{{bl}}} {cum}"
                        )
                    suffix = f"{{{label_str}}}" if label_str else ""
                    lines.append(
                        f"{metric.name}_sum{suffix} "
                        f"{_format_value(snap['sum'])}"
                    )
                    lines.append(
                        f"{metric.name}_count{suffix} {snap['count']}"
                    )
                elif isinstance(metric, Summary):
                    snap = metric.snapshot_child(**labels)
                    emitted = []
                    for q, value in snap["quantiles"].items():
                        if value is None:
                            continue
                        ql = (label_str + "," if label_str else "") + \
                            f'quantile="{q}"'
                        emitted.append(
                            f"{metric.name}{{{ql}}} {_format_value(value)}"
                        )
                    lines.extend(emitted)
                    exemplars = snap.get("exemplars") or []
                    if emitted and exemplars:
                        # The slowest observation's trace id — "p99 got
                        # worse" names the request to go look at. Emitted
                        # as a COMMENT line: inline `# {...}` exemplar
                        # annotations are only legal in the OpenMetrics
                        # exposition, and this endpoint advertises text
                        # format 0.0.4, whose parser would abort the
                        # whole scrape on one. Comments pass every 0.0.4
                        # parser untouched.
                        ex = exemplars[0]
                        suffix = f"{{{label_str}}}" if label_str else ""
                        lines.append(
                            f"# exemplar: {metric.name}{suffix} "
                            f'trace_id='
                            f'"{_escape_label_value(ex["trace_id"])}" '
                            f'{_format_value(ex["value"])} '
                            f'{ex["unix_ts"]:.3f}'
                        )
                    suffix = f"{{{label_str}}}" if label_str else ""
                    lines.append(
                        f"{metric.name}_sum{suffix} "
                        f"{_format_value(snap['sum'])}"
                    )
                    lines.append(
                        f"{metric.name}_count{suffix} {snap['count']}"
                    )
                else:
                    suffix = f"{{{label_str}}}" if label_str else ""
                    lines.append(
                        f"{metric.name}{suffix} "
                        f"{_format_value(metric.value(**labels))}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry every instrumented fit writes to."""
    return _default_registry


def start_prometheus_server(
    port: int = 0,
    addr: str = "127.0.0.1",
    registry: Optional[MetricsRegistry] = None,
):
    """Serve ``GET /metrics`` on a daemon thread; returns the HTTPServer.

    The scrape-endpoint helper for long-lived serving processes: bind port 0
    for an ephemeral port (``server.server_address[1]``), call
    ``server.shutdown()`` to stop. Registry defaults to the process-wide one.
    """
    import http.server
    import socketserver

    reg = registry or get_registry()

    class _Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - http.server API
            if self.path.split("?")[0] not in ("/metrics", "/"):
                self.send_error(404)
                return
            body = reg.prometheus_text().encode("utf-8")
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # silence per-request stderr noise
            pass

    class _Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
        daemon_threads = True
        allow_reuse_address = True

    server = _Server((addr, port), _Handler)
    thread = threading.Thread(
        target=server.serve_forever, name="sparkml-metrics", daemon=True
    )
    thread.start()
    return server
