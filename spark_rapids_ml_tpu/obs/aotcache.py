"""Persistent XLA executable cache: zero-cold-start serving.

Every serve replica used to pay a full XLA recompile of its bucket ×
precision ladder on process start — the one latency the pipeline work
(PR 9–13) cannot hide, and the reason replica count could not safely
move under load. This module persists the executables ``tracked_jit``
(``obs/xprof.py``) compiles through its AOT lower+compile path, keyed on
the exact signature it already computes, so a fresh process warms a
model's full ladder from disk in **milliseconds** instead of seconds:

* **key** — the content digest covers the tracked label plus the full
  abstract signature (pytree structure, per-leaf shape/dtype/weak-type/
  sharding, static argument values). The **environment fingerprint**
  (jax/jaxlib version, backend platform + platform version, device
  kind, the ``SPARK_RAPIDS_ML_TPU_SERVE_PRECISION`` posture, x64 mode)
  is stored in the entry header and checked at load: a jaxlib bump, a
  different chip, or a changed precision env var is an **invalidation**
  (counted, stale file dropped), never a silently-wrong executable.
  Serving weights are *runtime arguments* of every serving program
  (``models/_serving.py`` stages them as operands, not closures), so a
  cached executable is weight-independent by construction — new model
  versions reuse it.
* **write** — atomic tmp + ``os.replace``; a crash mid-write leaves no
  half-entry. Size is bounded (``..._CACHE_MAX_BYTES``) with
  oldest-mtime LRU eviction (hits ``os.utime`` their entry).
* **read** — corruption-tolerant: a truncated file, bad magic, foreign
  pickle, or a deserialization failure is a MISS plus a
  ``sparkml_serve_cache_errors_total{reason}`` increment — never an
  exception on the serving path.
* **observability** — ``sparkml_serve_cache_total{event}`` counts
  hit / miss / store / evict / invalidate; every hit/miss/store/evict
  decision files a ``serve:cache`` audit event (rule 14 of
  ``scripts/check_instrumentation.py`` rejects a cache decision path
  that is neither counted nor audit-spanned).

The cache is OFF unless ``SPARK_RAPIDS_ML_TPU_SERVE_CACHE_DIR`` points
somewhere (or ``configure_executable_cache`` is called): fit-side and
test processes keep the exact pre-cache behavior by default.

Entry format (one file per signature)::

    SMLAOTC1 | u32 header_len | header JSON | pickle(payload, trees)

where the header carries the environment fingerprint plus the compile
metadata (flops / bytes_accessed / memory sizes from the original
``cost_analysis``) so a cache hit keeps feeding analytic-MFU accounting
without re-running the analysis.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

ENV_PREFIX = "SPARK_RAPIDS_ML_TPU_SERVE_"
CACHE_DIR_ENV = ENV_PREFIX + "CACHE_DIR"
CACHE_MAX_BYTES_ENV = ENV_PREFIX + "CACHE_MAX_BYTES"
PRECISION_ENV = ENV_PREFIX + "PRECISION"

_MAGIC = b"SMLAOTC1"
_HEADER_STRUCT = struct.Struct("<I")
_DEFAULT_MAX_BYTES = 512 * 1024 * 1024

# header fields that must match the live process for an entry to be
# servable; a mismatch is an INVALIDATION (the honest-key satellite:
# a jaxlib bump / device-kind change / precision flip MUST miss)
_FINGERPRINT_KEYS = ("jax", "jaxlib", "platform", "platform_version",
                     "device_kind", "precision", "x64")


def _env_max_bytes() -> int:
    try:
        return int(float(os.environ.get(CACHE_MAX_BYTES_ENV,
                                        _DEFAULT_MAX_BYTES)))
    except ValueError:
        return _DEFAULT_MAX_BYTES


def environment_fingerprint() -> Dict[str, str]:
    """The live process's compile environment: everything that changes
    what an XLA executable MEANS without changing the abstract call
    signature. Stored in every entry header and compared at load."""
    fp: Dict[str, str] = {}
    try:
        import jax
        import jaxlib

        fp["jax"] = str(jax.__version__)
        fp["jaxlib"] = str(jaxlib.__version__)
        fp["x64"] = str(bool(jax.config.jax_enable_x64))
        try:
            # explicit submodule import: attribute access alone raises
            # until something else imported it, which would make the
            # fingerprint depend on IMPORT ORDER (observed live: the
            # same process computed platform_version '' before a fit
            # and 'cpu' after one — every warm restart invalidated)
            from jax.extend import backend as jax_backend

            backend = jax_backend.get_backend()
            fp["platform"] = str(backend.platform)
            fp["platform_version"] = str(
                getattr(backend, "platform_version", ""))
        except Exception:
            fp["platform"] = str(jax.default_backend())
            fp["platform_version"] = ""
        try:
            fp["device_kind"] = str(jax.devices()[0].device_kind)
        except Exception:
            fp["device_kind"] = ""
    except Exception:
        # a jax-less probe still produces a fingerprint; the entries it
        # writes can never load anyway (no backend to deserialize into)
        fp.setdefault("jax", "")
        fp.setdefault("jaxlib", "")
    fp["precision"] = os.environ.get(PRECISION_ENV, "native")
    return fp


def _canonical(obj: Any) -> str:
    """A stable textual form of one signature component. Primitives
    spell themselves; containers recurse; everything else (PyTreeDef,
    Sharding, dtype objects) uses its repr — stable within one
    jax/jaxlib version, which the fingerprint pins anyway."""
    if isinstance(obj, (str, bytes, int, float, bool)) or obj is None:
        return repr(obj)
    if isinstance(obj, (tuple, list)):
        return "(" + ",".join(_canonical(v) for v in obj) + ")"
    if isinstance(obj, dict):
        items = sorted((repr(k), _canonical(v)) for k, v in obj.items())
        return "{" + ",".join(f"{k}:{v}" for k, v in items) + "}"
    if isinstance(obj, frozenset):
        return "fs(" + ",".join(sorted(_canonical(v) for v in obj)) + ")"
    return repr(obj)


def signature_digest(label: str, signature_key: Any) -> str:
    """The entry filename digest: blake2b over (label, canonical
    signature). The environment fingerprint deliberately stays OUT of
    the digest and in the header — so a fingerprint mismatch is an
    observable *invalidation* of a found entry, not an invisible miss."""
    text = f"{label}\x00{_canonical(signature_key)}"
    return hashlib.blake2b(text.encode(), digest_size=20).hexdigest()


def _sanitize(label: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in label)[:64] or "fn"


class CachedExecutable:
    """One loaded entry: the deserialized compiled executable plus the
    compile metadata its header carried."""

    __slots__ = ("compiled", "flops", "bytes_accessed", "memory")

    def __init__(self, compiled, flops, bytes_accessed, memory):
        self.compiled = compiled
        self.flops = flops
        self.bytes_accessed = bytes_accessed
        self.memory = memory or {}


class ExecutableCache:
    """Disk-backed persistent compilation cache (see module doc).

    Thread-safe: loads are lock-free file reads; stores/evictions
    serialize on an instance lock (atomic replace keeps readers safe
    either way). ``fingerprint`` is injectable for the key-matrix
    tests."""

    def __init__(self, path: str, *,
                 max_bytes: Optional[int] = None,
                 fingerprint: Optional[Dict[str, str]] = None):
        self.path = os.path.abspath(path)
        self.max_bytes = int(max_bytes if max_bytes is not None
                             else _env_max_bytes())
        self._fingerprint = fingerprint
        # two locks: _lock guards the local counter tally (taken inside
        # _count/_count_error), _evict_lock serializes eviction sweeps.
        # They must be distinct — an eviction failure counts an error,
        # and counting under the eviction lock would self-deadlock.
        self._lock = threading.Lock()
        self._evict_lock = threading.Lock()
        self._local = {"hit": 0, "miss": 0, "store": 0, "evict": 0,
                       "evict_forced": 0, "invalidate": 0, "error": 0}
        # tiering protection hook (set_protect): predicate over entry
        # labels marking executables a COLD-but-registered model still
        # needs, plus the byte floor their population never drops below
        self._protect_fn: Optional[Callable[[str], bool]] = None
        self._protect_floor = 0
        os.makedirs(self.path, exist_ok=True)

    # -- plumbing ----------------------------------------------------------

    def fingerprint(self) -> Dict[str, str]:
        if self._fingerprint is None:
            self._fingerprint = environment_fingerprint()
        return self._fingerprint

    def _entry_path(self, label: str, digest: str) -> str:
        return os.path.join(self.path, f"{_sanitize(label)}-{digest}.aotx")

    def _count(self, event: str) -> None:
        with self._lock:
            self._local[event] = self._local.get(event, 0) + 1
        try:
            from spark_rapids_ml_tpu.obs.metrics import get_registry

            get_registry().counter(
                "sparkml_serve_cache_total",
                "persistent executable-cache decisions "
                "(hit/miss/store/evict/invalidate)", ("event",),
            ).inc(event=event)
        except Exception:
            # telemetry must never break the serving path; the local
            # tally above still records the decision for stats()
            with self._lock:
                self._local["error"] = self._local.get("error", 0) + 1

    def _count_error(self, reason: str) -> None:
        with self._lock:
            self._local["error"] = self._local.get("error", 0) + 1
        try:
            from spark_rapids_ml_tpu.obs.metrics import get_registry

            get_registry().counter(
                "sparkml_serve_cache_errors_total",
                "persistent executable-cache load/store failures by "
                "reason (a bad entry is a MISS, never a crash)",
                ("reason",),
            ).inc(reason=reason)
        except Exception:
            with self._lock:
                self._local["error"] = self._local.get("error", 0) + 1

    def _audit(self, event: str, label: str, t0: float, **attrs) -> None:
        """The ``serve:cache`` audit trail (rule 14): every cache
        decision lands in the span ring with its label and outcome."""
        try:
            from spark_rapids_ml_tpu.obs import spans as spans_mod

            spans_mod.record_event(
                f"serve:cache:{event}", t0, time.perf_counter(),
                label=label, **attrs)
        except Exception:
            self._count_error("audit")

    # -- the read path -----------------------------------------------------

    def load(self, label: str,
             signature_key: Any) -> Optional[CachedExecutable]:
        """The cached executable for (label, signature), or None (MISS).
        Corruption-tolerant: every failure mode degrades to a counted
        miss."""
        t0 = time.perf_counter()
        digest = signature_digest(label, signature_key)
        path = self._entry_path(label, digest)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            self._count("miss")
            self._audit("miss", label, t0, digest=digest)
            return None
        except OSError:
            self._count_error("io_read")
            self._count("miss")
            self._audit("miss", label, t0, digest=digest, error="io_read")
            return None
        header = self._parse_header(blob, label, t0, digest, path)
        if header is None:
            return None
        stale = {
            k: (header.get("fingerprint", {}).get(k), v)
            for k, v in self.fingerprint().items()
            if k in _FINGERPRINT_KEYS
            and header.get("fingerprint", {}).get(k) != v
        }
        if stale:
            # honest invalidation: the entry was compiled under a
            # different jaxlib/platform/device-kind/precision world —
            # drop it so the slot recompiles fresh
            self._count("invalidate")
            self._count("miss")
            self._audit("invalidate", label, t0, digest=digest,
                        stale_keys=sorted(stale))
            self._remove(path, count_evict=False)
            return None
        try:
            offset = len(_MAGIC) + _HEADER_STRUCT.size + header["_len"]
            payload, in_tree, out_tree = pickle.loads(blob[offset:])
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )

            compiled = deserialize_and_load(payload, in_tree, out_tree)
        except Exception as exc:
            self._count_error(f"deserialize_{type(exc).__name__}"[:40])
            self._count("miss")
            self._audit("miss", label, t0, digest=digest,
                        error=type(exc).__name__)
            self._remove(path, count_evict=False)
            return None
        try:
            os.utime(path)  # LRU touch
        except OSError:
            self._count_error("utime")
        self._count("hit")
        self._audit("hit", label, t0, digest=digest,
                    bytes=len(blob))
        return CachedExecutable(
            compiled,
            header.get("flops"),
            header.get("bytes_accessed"),
            header.get("memory") or {},
        )

    def _parse_header(self, blob: bytes, label: str, t0: float,
                      digest: str, path: str) -> Optional[Dict[str, Any]]:
        if len(blob) < len(_MAGIC) + _HEADER_STRUCT.size:
            self._count_error("truncated")
            self._count("miss")
            self._audit("miss", label, t0, digest=digest,
                        error="truncated")
            self._remove(path, count_evict=False)
            return None
        if blob[:len(_MAGIC)] != _MAGIC:
            self._count_error("bad_magic")
            self._count("miss")
            self._audit("miss", label, t0, digest=digest,
                        error="bad_magic")
            self._remove(path, count_evict=False)
            return None
        (hlen,) = _HEADER_STRUCT.unpack(
            blob[len(_MAGIC):len(_MAGIC) + _HEADER_STRUCT.size])
        start = len(_MAGIC) + _HEADER_STRUCT.size
        if len(blob) < start + hlen:
            self._count_error("truncated")
            self._count("miss")
            self._audit("miss", label, t0, digest=digest,
                        error="truncated")
            self._remove(path, count_evict=False)
            return None
        try:
            header = json.loads(blob[start:start + hlen])
        except ValueError:
            self._count_error("bad_header")
            self._count("miss")
            self._audit("miss", label, t0, digest=digest,
                        error="bad_header")
            self._remove(path, count_evict=False)
            return None
        header["_len"] = hlen
        return header

    # -- the write path ----------------------------------------------------

    def store(self, label: str, signature_key: Any, compiled, *,
              flops: Optional[float] = None,
              bytes_accessed: Optional[float] = None,
              memory: Optional[Dict[str, int]] = None,
              compile_seconds: Optional[float] = None) -> bool:
        """Persist one compiled executable (atomic write-then-rename;
        bounded by LRU eviction). Returns whether it landed; a failure
        (unserializable backend, disk trouble) is counted and ignored —
        the in-memory path is always intact."""
        t0 = time.perf_counter()
        digest = signature_digest(label, signature_key)
        path = self._entry_path(label, digest)
        try:
            from jax.experimental.serialize_executable import serialize

            payload, in_tree, out_tree = serialize(compiled)
            body = pickle.dumps((payload, in_tree, out_tree),
                                protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as exc:
            self._count_error(f"serialize_{type(exc).__name__}"[:40])
            return False
        header = json.dumps({
            "label": label,
            "fingerprint": self.fingerprint(),
            "flops": flops,
            "bytes_accessed": bytes_accessed,
            "memory": dict(memory or {}),
            "compile_seconds": compile_seconds,
        }).encode()
        blob = (_MAGIC + _HEADER_STRUCT.pack(len(header)) + header + body)
        tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except OSError:
            self._count_error("io_write")
            try:
                os.unlink(tmp)
            except OSError:
                self._count_error("io_cleanup")
            return False
        self._count("store")
        self._audit("store", label, t0, digest=digest, bytes=len(blob))
        self._evict_to_cap()
        return True

    def _remove(self, path: str, *, count_evict: bool) -> None:
        try:
            os.unlink(path)
        except OSError:
            return
        if count_evict:
            self._count("evict")

    def set_protect(self, predicate: Optional[Callable[[str], bool]],
                    floor_bytes: int = 0) -> None:
        """Install the tiering protection hook. ``predicate`` receives
        each entry's (sanitized) label and marks executables that a
        COLD-but-registered model still depends on for its fast
        reactivation: protected entries are evicted LAST, and only while
        the protected population would stay at or above ``floor_bytes``
        — every such eviction is FORCED (counted as ``evict_forced``).
        ``predicate=None`` clears the hook."""
        with self._evict_lock:
            self._protect_fn = predicate
            self._protect_floor = max(int(floor_bytes), 0)

    @staticmethod
    def _entry_label(path: str) -> str:
        """The sanitized label portion of an entry filename
        (``{label}-{digest}.aotx`` — the digest never contains '-')."""
        return os.path.basename(path)[:-len(".aotx")].rsplit("-", 1)[0]

    def _evict_to_cap(self) -> None:
        """Oldest-mtime LRU eviction down to ``max_bytes`` (hits touch
        their entry's mtime), in two passes: unprotected entries first;
        then, only if still over cap, protected entries — stopping at
        the protected floor, each deletion counted as a forced eviction
        (``set_protect``). Serialized on the eviction lock so racing
        stores don't double-delete."""
        if self.max_bytes <= 0:
            return
        t0 = time.perf_counter()
        with self._evict_lock:
            protect = self._protect_fn
            floor = self._protect_floor
            try:
                entries = []
                total = 0
                with os.scandir(self.path) as it:
                    for e in it:
                        if not e.name.endswith(".aotx"):
                            continue
                        st = e.stat()
                        entries.append((st.st_mtime, st.st_size, e.path))
                        total += st.st_size
            except OSError:
                self._count_error("io_scan")
                return
            plain, shielded = [], []
            shielded_total = 0
            for row in sorted(entries):
                keep = False
                if protect is not None:
                    try:
                        keep = bool(protect(self._entry_label(row[2])))
                    except Exception:
                        self._count_error("protect")
                if keep:
                    shielded.append(row)
                    shielded_total += row[1]
                else:
                    plain.append(row)
            evicted, forced = [], []
            for _mtime, size, path in plain:
                if total <= self.max_bytes:
                    break
                try:
                    os.unlink(path)
                except OSError:
                    continue
                total -= size
                evicted.append(os.path.basename(path))
            for _mtime, size, path in shielded:
                if total <= self.max_bytes:
                    break
                if shielded_total - size < floor:
                    # the floor wins over the cap: a COLD model's
                    # reactivation path outranks disk pressure
                    break
                try:
                    os.unlink(path)
                except OSError:
                    continue
                total -= size
                shielded_total -= size
                forced.append(os.path.basename(path))
        for name in evicted:
            self._count("evict")
            self._audit("evict", name, t0)
        for name in forced:
            self._count("evict")
            self._count("evict_forced")
            self._audit("evict", name, t0, forced=True)

    # -- introspection -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        entries = 0
        total = 0
        try:
            with os.scandir(self.path) as it:
                for e in it:
                    if e.name.endswith(".aotx"):
                        entries += 1
                        total += e.stat().st_size
        except OSError:
            self._count_error("io_scan")
        with self._lock:
            counters = dict(self._local)
        return {
            "path": self.path,
            "max_bytes": self.max_bytes,
            "entries": entries,
            "bytes": total,
            **counters,
        }


# -- the process-global cache handle -----------------------------------------

_global_lock = threading.Lock()
_global_cache: Optional[ExecutableCache] = None
_global_config: Optional[Tuple] = None
_configured: Optional[Tuple[Optional[str], Optional[int]]] = None


def configure_executable_cache(path: Optional[str], *,
                               max_bytes: Optional[int] = None) -> None:
    """Programmatic override of the env-var configuration (tests, the
    cold-start bench). ``path=None`` restores env-driven resolution."""
    global _configured, _global_cache, _global_config
    with _global_lock:
        _configured = (path, max_bytes) if path else None
        _global_cache = None
        _global_config = None


def get_executable_cache() -> Optional[ExecutableCache]:
    """The process cache, or None when disabled. Re-resolves when the
    governing env vars change (the precision env is part of the entry
    fingerprint, so a flipped posture must rebuild the handle)."""
    global _global_cache, _global_config
    if _configured is not None:
        path, max_bytes = _configured
        key = ("cfg", path, max_bytes,
               os.environ.get(PRECISION_ENV, "native"))
    else:
        path = os.environ.get(CACHE_DIR_ENV, "").strip() or None
        max_bytes = None
        key = ("env", path, _env_max_bytes(),
               os.environ.get(PRECISION_ENV, "native"))
    if path is None:
        return None
    with _global_lock:
        if _global_cache is None or _global_config != key:
            _global_cache = ExecutableCache(path, max_bytes=max_bytes)
            _global_config = key
        return _global_cache


__all__ = [
    "CACHE_DIR_ENV",
    "CACHE_MAX_BYTES_ENV",
    "CachedExecutable",
    "ExecutableCache",
    "configure_executable_cache",
    "environment_fingerprint",
    "get_executable_cache",
    "signature_digest",
]
