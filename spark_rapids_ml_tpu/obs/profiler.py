"""Guarded on-demand device profiling: ``POST /debug/profile`` backend.

The hot-path latency work needs device-timeline evidence ("where did the
batch's 4 ms go?") that metrics cannot give. This module turns one HTTP
request into a bounded capture:

* ``start_capture(seconds)`` drives ``jax.profiler.start_trace`` /
  ``stop_trace`` into the profile dir
  (``SPARK_RAPIDS_ML_TPU_OBS_PROFILE_DIR``, default
  ``<dump_dir>/profiles``) — **single-flight** (a second start while
  one is running raises ``CaptureInFlight``), auto-stopped by a timer
  thread after ``seconds`` (clamped to ``MAX_SECONDS``), and works on
  CPU backends too;
* the jax profiler start/stop runs on its **own helper thread with a
  bounded join**: on some runtimes ``start_trace`` stalls for tens of
  seconds (or indefinitely) while other threads are mid-computation or
  polling PJRT (measured on this container's CPU backend under live
  serve traffic), and an ops endpoint must never inherit that stall.
  A capture whose helper misses the join grace completes anyway
  (``outcome="jax_wedged"``); the helper cleans up after itself when
  the backend unblocks (start → sees the stop event → stop → exit),
  and while it is still draining, new captures skip the jax trace
  (``jax_enabled=false``) instead of stacking a second ``start_trace``
  behind it. Every capture still lands a loadable artifact, because
* every capture ALSO exports the span-ring as a Chrome-trace JSON into
  the same directory (loadable in Perfetto / ``chrome://tracing``)
  regardless of the native profiler's mood;
* the capture itself is observable: an ``obs:profile`` span covering
  the window, ``sparkml_obs_profile_captures_total{outcome}`` counts
  (``started`` / ``completed`` / ``jax_unavailable`` / ``jax_wedged``),
  and the bookkeeping cost lands in
  ``sparkml_obs_overhead_seconds_total{component="profiler"}``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from spark_rapids_ml_tpu.obs import flight
from spark_rapids_ml_tpu.obs.logging import get_logger
from spark_rapids_ml_tpu.obs.metrics import get_registry

PROFILE_DIR_ENV = "SPARK_RAPIDS_ML_TPU_OBS_PROFILE_DIR"
MAX_SECONDS = 300.0
_DEFAULT_SECONDS = 5.0
# How long past the capture window the jax helper thread gets to come
# back before the backend is declared wedged.
_JAX_JOIN_GRACE = 2.0

_log = get_logger("obs.profiler")


class CaptureInFlight(RuntimeError):
    """A profile capture is already running — captures are single-flight
    (two overlapping ``start_trace`` calls would corrupt the dump, and a
    scrape loop must not be able to stack profiler overhead)."""


def profile_dir() -> str:
    return (os.environ.get(PROFILE_DIR_ENV)
            or os.path.join(flight.dump_dir(), "profiles"))


def _captures_counter():
    return get_registry().counter(
        "sparkml_obs_profile_captures_total",
        "on-demand profiler captures by outcome", ("outcome",),
    )


def _overhead_counter():
    return get_registry().counter(
        "sparkml_obs_overhead_seconds_total",
        "wall-clock the observability layer spends watching "
        "(sampler sweeps, device monitor, profiler bookkeeping)",
        ("component",),
    )


class _Capture:
    __slots__ = ("id", "path", "seconds", "t0_perf", "started_unix",
                 "stop_event", "thread", "jax_thread", "jax_started",
                 "jax_result", "fit_run_id")

    def __init__(self, cid: str, path: str, seconds: float):
        self.id = cid
        self.path = path
        self.seconds = seconds
        self.t0_perf = time.perf_counter()
        self.started_unix = time.time()
        self.stop_event = threading.Event()
        self.thread: Optional[threading.Thread] = None
        self.jax_thread: Optional[threading.Thread] = None
        self.jax_started = threading.Event()
        self.jax_result: Optional[str] = None
        # the FitRun active when the capture was armed (the monitor's
        # latest, NOT the contextvar — captures run on worker threads)
        self.fit_run_id: Optional[str] = None


_lock = threading.Lock()
_active: Optional[_Capture] = None
_last: Optional[Dict[str, Any]] = None
# The most recent jax helper thread. While it is still alive (wedged in
# start/stop_trace behind a busy backend), new captures skip the jax
# trace — two overlapping start_trace calls would corrupt the session —
# and re-arm automatically once it drains and cleans up after itself.
_jax_helper: Optional[threading.Thread] = None


def jax_profiler_busy() -> bool:
    """A previous capture's jax helper is still wedged in the backend
    (new captures serve span-ring artifacts until it drains)."""
    with _lock:
        helper = _jax_helper
    return helper is not None and helper.is_alive()


def jax_transition_pending() -> bool:
    """True only while a ``start_trace``/``stop_trace`` call is actually
    in flight. The window between them — trace running, helper parked in
    its ``stop_event`` wait — is NOT a transition: PJRT polls are safe
    then, so a long capture must not blind the device monitor for its
    whole duration."""
    with _lock:
        cap = _active
        helper = _jax_helper
    cap_thread = cap.jax_thread if cap is not None else None
    if cap_thread is not None and cap_thread.is_alive():
        if not cap.jax_started.is_set():
            return True  # start_trace in flight
        if cap.jax_result is None and (
                cap.stop_event.is_set()
                or time.perf_counter() - cap.t0_perf >= cap.seconds):
            return True  # stop_trace in flight (or about to be)
    if (helper is not None and helper is not cap_thread
            and helper.is_alive()):
        # an orphaned helper from an earlier capture is by definition
        # stuck inside start/stop_trace
        return True
    return False


def reset_jax_profiler_state() -> None:
    """Forget the tracked helper thread (tests)."""
    global _jax_helper
    with _lock:
        _jax_helper = None


def capture_active() -> Optional[Dict[str, Any]]:
    """The in-flight capture's info, or None."""
    with _lock:
        cap = _active
    if cap is None:
        return None
    return {
        "id": cap.id,
        "path": cap.path,
        "seconds": cap.seconds,
        "elapsed_seconds": time.perf_counter() - cap.t0_perf,
        "jax_trace": cap.jax_started.is_set(),
        "fit_run_id": cap.fit_run_id,
    }


def last_capture() -> Optional[Dict[str, Any]]:
    """The most recent completed capture's result document."""
    with _lock:
        return dict(_last) if _last else None


def start_capture(seconds: float = _DEFAULT_SECONDS,
                  label: str = "ondemand") -> Dict[str, Any]:
    """Begin a single-flight capture; auto-stops after ``seconds``.

    Returns the capture info immediately (a worker thread finishes it);
    raises ``CaptureInFlight`` when one is already running. ``seconds``
    is clamped to ``(0, MAX_SECONDS]`` — an unbounded capture armed over
    HTTP would be a denial-of-service knob pointed at the dump disk."""
    global _active, _jax_helper
    seconds = min(max(float(seconds), 0.05), MAX_SECONDS)
    safe_label = "".join(
        c if (c.isalnum() or c in "-_") else "_" for c in str(label)
    )[:40] or "ondemand"
    cid = f"{safe_label}_{int(time.time() * 1000)}_{os.getpid()}"
    path = os.path.join(profile_dir(), cid)
    with _lock:
        if _active is not None:
            raise CaptureInFlight(
                f"profile capture {_active.id!r} is already running "
                f"({_active.seconds:g}s window) — retry after it lands"
            )
        cap = _Capture(cid, path, seconds)
        _active = cap
        jax_enabled = _jax_helper is None or not _jax_helper.is_alive()
    try:
        from spark_rapids_ml_tpu.obs import fitmon

        cap.fit_run_id = fitmon.get_fit_monitor().latest_active_run_id()
    except Exception:
        cap.fit_run_id = None
    try:
        os.makedirs(path, exist_ok=True)
        from spark_rapids_ml_tpu.obs import tracectx

        if jax_enabled:
            # start AND stop live on one helper thread: if start_trace
            # wedges, a later unwedge sees the stop event already set
            # and cleans up after itself; the capture path never waits
            # on it past the join grace.
            cap.jax_thread = tracectx.traced_thread(
                _jax_worker, name=f"sparkml-profile-jax-{cid}",
                daemon=True, fresh=True, args=(cap,),
            )
            cap.jax_thread.start()
            with _lock:
                _jax_helper = cap.jax_thread
        cap.thread = tracectx.traced_thread(
            _run_capture, name=f"sparkml-profile-{cid}", daemon=True,
            fresh=True, args=(cap,),
        )
        cap.thread.start()
    except Exception:
        # A failed start (unwritable dir, thread spawn failure) must
        # not brick the endpoint: release the single-flight slot and
        # end any helper that already launched, then surface the error.
        cap.stop_event.set()
        with _lock:
            if _active is cap:
                _active = None
        _captures_counter().inc(outcome="start_failed")
        raise
    _captures_counter().inc(outcome="started")
    _log.info("profile capture started", capture_id=cid, path=path,
              seconds=seconds, jax_enabled=jax_enabled)
    return {
        "id": cid,
        "path": path,
        "seconds": seconds,
        "jax_enabled": jax_enabled,
        "fit_run_id": cap.fit_run_id,
    }


def stop_capture() -> Optional[Dict[str, Any]]:
    """End the in-flight capture early (no-op when none is running);
    blocks until its artifacts are written and returns the result."""
    with _lock:
        cap = _active
    if cap is None:
        return last_capture()
    cap.stop_event.set()
    thread = cap.thread
    if thread is not None:
        thread.join(timeout=10.0)
    return last_capture()


def wait(timeout: Optional[float] = None) -> Optional[Dict[str, Any]]:
    """Block until the in-flight capture (if any) lands AND its jax
    helper thread drains; returns the last capture result. Call before
    process exit in tests/short-lived tools — an abandoned helper stuck
    inside the profiler C++ at interpreter teardown can crash it."""
    with _lock:
        cap = _active
        helper = _jax_helper
    if cap is not None and cap.thread is not None:
        cap.thread.join(timeout=timeout)
    if helper is not None and helper.is_alive():
        helper.join(timeout=timeout)
    return last_capture()


def _jax_worker(cap: _Capture) -> None:
    """start_trace → wait out the window → stop_trace, all on one
    thread. Any step may block forever on a wedged backend; the capture
    worker only ever joins this thread with a bounded timeout."""
    try:
        import jax

        jax.profiler.start_trace(cap.path)
    except Exception as exc:
        cap.jax_result = "unavailable"
        _log.warning("jax profiler unavailable; span-ring capture only",
                     error=f"{type(exc).__name__}: {exc}")
        return
    cap.jax_started.set()
    cap.stop_event.wait(cap.seconds)
    try:
        jax.profiler.stop_trace()
        cap.jax_result = "ok"
    except Exception as exc:
        cap.jax_result = "stop_failed"
        _log.warning("jax profiler stop_trace failed",
                     error=f"{type(exc).__name__}: {exc}")


def _run_capture(cap: _Capture) -> None:
    cap.stop_event.wait(cap.seconds)
    jax_outcome = "skipped_busy"
    if cap.jax_thread is not None:
        cap.stop_event.set()  # early-stop: release the helper's wait
        cap.jax_thread.join(timeout=_JAX_JOIN_GRACE)
        if cap.jax_thread.is_alive():
            # start_trace (or stop_trace) has not come back — the known
            # stall when other threads are mid-computation. The capture
            # completes with span-ring artifacts; the helper cleans up
            # when the backend unblocks, and until then new captures
            # skip the jax trace instead of stacking behind it.
            jax_outcome = "jax_wedged"
            _captures_counter().inc(outcome="jax_wedged")
            _log.warning(
                "jax profiler wedged (start/stop_trace did not return "
                "within the join grace); capture lands span-ring only",
                capture_id=cap.id)
        elif cap.jax_result == "unavailable":
            jax_outcome = "jax_unavailable"
            _captures_counter().inc(outcome="jax_unavailable")
        else:
            jax_outcome = cap.jax_result or "ok"
    _finish(cap, jax_outcome)


def _artifacts(path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for root, _dirs, files in os.walk(path):
        for fname in sorted(files):
            fpath = os.path.join(root, fname)
            try:
                size = os.path.getsize(fpath)
            except OSError:
                continue
            out.append({"path": fpath, "bytes": size})
    return out


def _finish(cap: _Capture, jax_outcome: str) -> None:
    global _active, _last
    t_finish = time.perf_counter()
    # The span-ring view of the same window: always written, so every
    # capture yields at least one loadable (Perfetto/chrome://tracing)
    # artifact even without a native profiler backend.
    spans_path: Optional[str] = os.path.join(
        cap.path, f"spans_{cap.id}.json")
    try:
        from spark_rapids_ml_tpu.obs import spans as spans_mod

        spans_mod.get_recorder().export_chrome_trace(spans_path)
    except Exception as exc:
        _log.warning("span-ring export failed",
                     error=f"{type(exc).__name__}: {exc}")
        spans_path = None
    t1 = time.perf_counter()
    try:
        from spark_rapids_ml_tpu.obs import spans as spans_mod

        spans_mod.record_event(
            "obs:profile", cap.t0_perf, t1,
            capture_id=cap.id, seconds=cap.seconds,
            jax_outcome=jax_outcome,
        )
    except Exception:
        pass
    result = {
        "id": cap.id,
        "path": cap.path,
        "seconds": cap.seconds,
        "elapsed_seconds": t1 - cap.t0_perf,
        # honest only on "ok": a failed/wedged stop_trace typically never
        # flushed the buffer, so there is no loadable jax artifact
        "jax_trace": jax_outcome == "ok",
        "jax_outcome": jax_outcome,
        "spans_trace": spans_path,
        "artifacts": _artifacts(cap.path),
        "finished_unix": time.time(),
        "fit_run_id": cap.fit_run_id,
    }
    with _lock:
        _last = result
        _active = None
    _captures_counter().inc(outcome="completed")
    try:
        from spark_rapids_ml_tpu.obs import retention

        retention.maybe_gc("profile")
    except Exception:
        pass  # GC is best-effort; the capture already landed
    try:
        _overhead_counter().inc(time.perf_counter() - t_finish,
                                component="profiler")
    except Exception:
        pass
    _log.info("profile capture completed", capture_id=cap.id,
              path=cap.path, artifacts=len(result["artifacts"]),
              jax_outcome=jax_outcome)


__all__ = [
    "CaptureInFlight",
    "MAX_SECONDS",
    "PROFILE_DIR_ENV",
    "capture_active",
    "jax_profiler_busy",
    "jax_transition_pending",
    "last_capture",
    "profile_dir",
    "reset_jax_profiler_state",
    "start_capture",
    "stop_capture",
    "wait",
]
