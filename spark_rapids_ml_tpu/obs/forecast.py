"""Short-horizon trend forecasting over the TSDB rings, and the
predictive input it feeds the serve autoscaler.

The reactive autoscaler (``serve.autoscale``) scales when queue wait has
ALREADY breached its threshold — by then requests have eaten the wait.
The ROADMAP asks for the other half: *scale before the queue builds,
not after*. This module produces that signal from history the process
already keeps:

* ``HoltState`` — incremental Holt double exponential smoothing
  (level + trend) over irregularly-spaced samples. The trend is kept in
  units-per-second so ``project(horizon_s)`` is just
  ``level + trend * horizon_s``. Every update first makes a one-step
  prediction for the incoming sample and records ``|value - predicted|``
  — a CONTINUOUS backtest, so the forecast's own error is a published
  metric, not a claim.
* ``Forecaster`` — reads configured target series out of a
  ``TimeSeriesStore`` once per sampler sweep (``install()`` hooks
  ``register_post_sweep`` — no new thread), feeds the Holt state, and
  publishes per-horizon projection gauges
  (``sparkml_forecast_queue_wait_ms{horizon}``,
  ``sparkml_forecast_rps{horizon}``) plus the backtest error gauge
  (``sparkml_forecast_abs_err{signal}``). Default targets: queue wait
  from the ``sparkml_serve_queue_wait_seconds`` gauge the serve stack
  republishes every sweep, and request rate from the
  ``sparkml_serve_requests_total`` counter's windowed rate.
* ``PredictiveAutoscaler`` — the ``AutoscaleController`` predictive
  input. Consulted when the reactive path HOLDs, it fires when the
  projected queue wait at ``horizon`` would breach the SAME
  ``up_queue_wait_s`` threshold the reactive path uses. It runs
  SHADOW-MODE first: by default a would-scale tick only counts
  ``sparkml_serve_autoscale_total{decision="predictive_shadow"}`` and
  records a span — actuation (``controller.predictive_scale_up``)
  requires ``SPARK_RAPIDS_ML_TPU_AUTOSCALE_PREDICTIVE=1``. Operators
  watch the shadow counter against real traffic before trusting the
  forecast with replicas.

Every poll/feed outcome and every shadow/actuate decision increments a
counter in the SAME function that took it (``check_instrumentation``
rule 18). Clocks are injectable and this module never reads the wall
clock directly (rule 8): timestamps flow from the sampler's sweep
``now`` or the constructor-injected ``clock``.

Knobs (env): SPARK_RAPIDS_ML_TPU_FORECAST (default 1),
SPARK_RAPIDS_ML_TPU_FORECAST_ALPHA (0.4) / _BETA (0.2) — Holt
smoothing factors, SPARK_RAPIDS_ML_TPU_FORECAST_HORIZONS_S ("30,120"),
SPARK_RAPIDS_ML_TPU_FORECAST_WINDOW_S (30 — rate/read window),
SPARK_RAPIDS_ML_TPU_AUTOSCALE_PREDICTIVE (default 0 = shadow only),
SPARK_RAPIDS_ML_TPU_AUTOSCALE_PREDICTIVE_HORIZON_S (60).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from spark_rapids_ml_tpu.obs import metrics as metrics_mod
from spark_rapids_ml_tpu.obs import spans as spans_mod
from spark_rapids_ml_tpu.obs import tsdb as tsdb_mod
from spark_rapids_ml_tpu.obs.logging import get_logger

ENABLED_ENV = "SPARK_RAPIDS_ML_TPU_FORECAST"
ALPHA_ENV = "SPARK_RAPIDS_ML_TPU_FORECAST_ALPHA"
BETA_ENV = "SPARK_RAPIDS_ML_TPU_FORECAST_BETA"
HORIZONS_ENV = "SPARK_RAPIDS_ML_TPU_FORECAST_HORIZONS_S"
WINDOW_ENV = "SPARK_RAPIDS_ML_TPU_FORECAST_WINDOW_S"
PREDICTIVE_ENV = "SPARK_RAPIDS_ML_TPU_AUTOSCALE_PREDICTIVE"
PREDICTIVE_HORIZON_ENV = "SPARK_RAPIDS_ML_TPU_AUTOSCALE_PREDICTIVE_HORIZON_S"

_DEFAULT_ALPHA = 0.4
_DEFAULT_BETA = 0.2
_DEFAULT_HORIZONS = (30.0, 120.0)
_DEFAULT_WINDOW_S = 30.0
_DEFAULT_PREDICTIVE_HORIZON_S = 60.0

# The gauge serve.server republishes from the engine's live overload
# signals every sweep — the forecaster's queue-wait input series.
QUEUE_WAIT_SERIES = "sparkml_serve_queue_wait_seconds"

_log = get_logger("obs.forecast")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def enabled() -> bool:
    """The forecaster's kill switch (default on)."""
    return os.environ.get(ENABLED_ENV, "1").strip().lower() not in (
        "0", "false", "off", "no")


def predictive_actuation_enabled() -> bool:
    """Shadow→actuate gate: predictive scale-ups only touch replicas
    when this is explicitly switched on."""
    return os.environ.get(PREDICTIVE_ENV, "0").strip().lower() in (
        "1", "true", "on", "yes")


def horizons_from_env() -> Tuple[float, ...]:
    raw = os.environ.get(HORIZONS_ENV, "")
    if not raw.strip():
        return _DEFAULT_HORIZONS
    out: List[float] = []
    for part in raw.split(","):
        try:
            h = float(part)
        except ValueError:
            continue
        if h > 0:
            out.append(h)
    return tuple(out) or _DEFAULT_HORIZONS


def horizon_label(horizon_s: float) -> str:
    """``30s`` / ``120s`` — the ``{horizon=}`` label value."""
    if float(horizon_s).is_integer():
        return f"{int(horizon_s)}s"
    return f"{horizon_s:g}s"


class HoltState:
    """Incremental Holt level+trend smoothing with one-step backtest.

    The update recurrence over an irregular gap ``dt = ts - last_ts``:

        predicted = level + trend * dt          # one-step forecast
        err       = |value - predicted|         # backtest residual
        level'    = alpha * value + (1 - alpha) * predicted
        trend'    = beta * (level' - level) / dt + (1 - beta) * trend

    An exact linear ramp is a fixed point (trend converges to the slope,
    err → 0) and a flat series keeps trend at exactly 0 — both are
    hand-computable test fixtures. Not thread-safe on its own; the
    owning ``Forecaster`` serialises updates.
    """

    __slots__ = ("alpha", "beta", "level", "trend", "last_ts",
                 "updates", "abs_err_sum", "abs_value_sum", "err_count",
                 "last_err")

    def __init__(self, alpha: float = _DEFAULT_ALPHA,
                 beta: float = _DEFAULT_BETA):
        if not (0.0 < alpha <= 1.0) or not (0.0 <= beta <= 1.0):
            raise ValueError(
                f"alpha must be in (0, 1], beta in [0, 1]; "
                f"got alpha={alpha} beta={beta}")
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.level: Optional[float] = None
        self.trend = 0.0  # units per second
        self.last_ts: Optional[float] = None
        self.updates = 0
        self.abs_err_sum = 0.0
        self.abs_value_sum = 0.0
        self.err_count = 0
        self.last_err: Optional[float] = None

    def update(self, ts: float, value: float) -> Optional[float]:
        """Feed one sample; returns the backtest residual (None for the
        seed sample or a non-advancing timestamp)."""
        value = float(value)
        if self.level is None:
            self.level = value
            self.last_ts = float(ts)
            self.updates += 1
            return None
        dt = float(ts) - self.last_ts
        if dt <= 0:
            return None
        predicted = self.level + self.trend * dt
        err = abs(value - predicted)
        self.abs_err_sum += err
        self.abs_value_sum += abs(value)
        self.err_count += 1
        self.last_err = err
        new_level = self.alpha * value + (1.0 - self.alpha) * predicted
        self.trend = (self.beta * (new_level - self.level) / dt
                      + (1.0 - self.beta) * self.trend)
        self.level = new_level
        self.last_ts = float(ts)
        self.updates += 1
        return err

    def project(self, horizon_s: float) -> Optional[float]:
        """Pure projection ``level + trend * horizon`` (None while
        unseeded). Never negative-projects below zero for the serve
        signals this module forecasts — wait and rate are both
        non-negative quantities."""
        if self.level is None:
            return None
        return max(0.0, self.level + self.trend * float(horizon_s))

    def abs_err_mean(self) -> Optional[float]:
        if self.err_count == 0:
            return None
        return self.abs_err_sum / self.err_count

    def rel_err_mean(self) -> Optional[float]:
        """Mean |residual| over mean |value| — the scale-free backtest
        number the load-harness fleet gate judges."""
        if self.err_count == 0 or self.abs_value_sum <= 0:
            return None
        return self.abs_err_sum / self.abs_value_sum

    def as_dict(self) -> Dict[str, Any]:
        return {
            "level": self.level,
            "trend_per_s": self.trend,
            "last_ts": self.last_ts,
            "updates": self.updates,
            "backtest": {
                "samples": self.err_count,
                "abs_err_mean": self.abs_err_mean(),
                "rel_err_mean": self.rel_err_mean(),
                "last_abs_err": self.last_err,
            },
        }


class ForecastTarget:
    """One forecasted signal: where to read it and how to interpret it.

    ``mode="gauge"`` feeds the latest sample (at ITS timestamp — the
    backtest is honest about when the value was observed);
    ``mode="rate"`` feeds the counter's windowed per-second rate at the
    tick timestamp. ``scale`` converts stored units to published units
    (seconds → ms for queue wait).
    """

    __slots__ = ("signal", "series", "labels", "mode", "scale")

    def __init__(self, signal: str, series: str, *,
                 labels: Optional[Dict[str, str]] = None,
                 mode: str = "gauge", scale: float = 1.0):
        if mode not in ("gauge", "rate"):
            raise ValueError(f"mode must be gauge|rate, got {mode!r}")
        self.signal = signal
        self.series = series
        self.labels = dict(labels) if labels else None
        self.mode = mode
        self.scale = float(scale)


def default_targets() -> List[ForecastTarget]:
    """The two signals the ISSUE names: queue wait (ms) and request
    rate (rps)."""
    return [
        ForecastTarget("queue_wait_ms", QUEUE_WAIT_SERIES,
                       mode="gauge", scale=1000.0),
        ForecastTarget("rps", "sparkml_serve_requests_total",
                       mode="rate", scale=1.0),
    ]


class Forecaster:
    """Per-sweep Holt forecasting over TSDB series.

    ``tick(now)`` is the one entry point (hooked to the sampler via
    ``install()``); each tick reads every target, feeds its Holt state,
    and republishes the projection + backtest gauges. Outcomes per
    (signal, tick) are counted in
    ``sparkml_forecast_ticks_total{signal,outcome}``:

    * ``fed`` — a fresh sample advanced the state;
    * ``stale`` — the series has no sample newer than the last one fed;
    * ``no_data`` — the series does not exist (yet) in the store;
    * ``disabled`` — the kill switch is off (state untouched).
    """

    def __init__(
        self,
        store: Optional[tsdb_mod.TimeSeriesStore] = None,
        registry: Optional[metrics_mod.MetricsRegistry] = None,
        *,
        alpha: Optional[float] = None,
        beta: Optional[float] = None,
        horizons: Optional[Tuple[float, ...]] = None,
        window_seconds: Optional[float] = None,
        targets: Optional[List[ForecastTarget]] = None,
        clock: Callable[[], float] = time.time,
        enabled_fn: Callable[[], bool] = enabled,
    ):
        self._store = store
        self._registry = registry
        self.alpha = float(alpha if alpha is not None
                           else _env_float(ALPHA_ENV, _DEFAULT_ALPHA))
        self.beta = float(beta if beta is not None
                          else _env_float(BETA_ENV, _DEFAULT_BETA))
        self.horizons = tuple(horizons) if horizons else horizons_from_env()
        self.window_seconds = float(
            window_seconds if window_seconds is not None
            else _env_float(WINDOW_ENV, _DEFAULT_WINDOW_S))
        self.targets = (list(targets) if targets is not None
                        else default_targets())
        self.clock = clock
        self._enabled_fn = enabled_fn
        self._lock = threading.Lock()
        self._states: Dict[str, HoltState] = {
            t.signal: HoltState(self.alpha, self.beta)
            for t in self.targets
        }
        self._ticks = 0
        reg = self._reg()
        self._m_ticks = reg.counter(
            "sparkml_forecast_ticks_total",
            "forecaster feed outcomes per signal per tick",
            ("signal", "outcome"),
        )
        self._m_abs_err = reg.gauge(
            "sparkml_forecast_abs_err",
            "mean one-step backtest |error| per forecast signal, in "
            "the signal's published units", ("signal",),
        )
        self._g_projection: Dict[str, metrics_mod.Gauge] = {}
        for target in self.targets:
            self._g_projection[target.signal] = reg.gauge(
                f"sparkml_forecast_{target.signal}",
                f"Holt projection of {target.signal} at each horizon",
                ("horizon",),
            )

    def _reg(self) -> metrics_mod.MetricsRegistry:
        return (self._registry if self._registry is not None
                else metrics_mod.get_registry())

    def store(self) -> tsdb_mod.TimeSeriesStore:
        return (self._store if self._store is not None
                else tsdb_mod.get_tsdb())

    @property
    def ticks(self) -> int:
        return self._ticks

    def state(self, signal: str) -> Optional[HoltState]:
        return self._states.get(signal)

    # -- the sweep entry point ---------------------------------------------

    def tick(self, now: Optional[float] = None) -> Dict[str, str]:
        """One forecast pass; returns {signal: outcome}. A disabled
        forecaster is inert: no reads, no state changes, no gauge
        writes — only the ``disabled`` outcome counters move."""
        ts = self.clock() if now is None else float(now)
        outcomes: Dict[str, str] = {}
        if not self._enabled_fn():
            for target in self.targets:
                outcomes[target.signal] = "disabled"
                self._m_ticks.inc(signal=target.signal,
                                  outcome="disabled")
            return outcomes
        store = self.store()
        with self._lock:
            self._ticks += 1
            for target in self.targets:
                outcome = self._feed_target(store, target, ts)
                outcomes[target.signal] = outcome
                self._m_ticks.inc(signal=target.signal, outcome=outcome)
        return outcomes

    def _feed_target(self, store: tsdb_mod.TimeSeriesStore,
                     target: ForecastTarget, now: float) -> str:
        """Read one target out of the store and advance its Holt state.
        Caller holds the lock and counts the returned outcome."""
        series = store.range_query(
            target.series, target.labels, self.window_seconds, now=now)
        if not series:
            return "no_data"
        state = self._states[target.signal]
        if target.mode == "rate":
            value = store.rate(target.series, target.labels,
                               window=self.window_seconds, now=now)
            sample_ts = now
        else:
            # latest sample across children, summed at the max timestamp
            sample_ts = None
            value = 0.0
            for child in series:
                if not child["points"]:
                    continue
                pt_ts, pt_v = child["points"][-1]
                value += pt_v
                sample_ts = pt_ts if sample_ts is None else max(
                    sample_ts, pt_ts)
            if sample_ts is None:
                return "no_data"
        if state.last_ts is not None and sample_ts <= state.last_ts:
            return "stale"
        state.update(sample_ts, value * target.scale)
        self._publish_target(target, state)
        return "fed"

    def _publish_target(self, target: ForecastTarget,
                        state: HoltState) -> None:
        gauge = self._g_projection[target.signal]
        for horizon in self.horizons:
            projection = state.project(horizon)
            if projection is not None:
                gauge.set(projection, horizon=horizon_label(horizon))
        err = state.abs_err_mean()
        if err is not None:
            self._m_abs_err.set(err, signal=target.signal)

    # -- sampler hook -------------------------------------------------------

    def install(self, sampler: tsdb_mod.MetricsSampler) -> None:
        """Forecast after every sampler sweep, on the sampler thread —
        idempotent (bound methods of one forecaster compare equal)."""
        sampler.register_post_sweep(self._post_sweep)

    def uninstall(self, sampler: tsdb_mod.MetricsSampler) -> None:
        sampler.unregister_post_sweep(self._post_sweep)

    def _post_sweep(self, ts: float) -> None:
        try:
            self.tick(now=ts)
        except Exception:
            _log.warning("forecast tick failed", exc_info=True)

    # -- introspection ------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The ``/debug/fleet`` forecast panel."""
        with self._lock:
            signals: Dict[str, Any] = {}
            for target in self.targets:
                state = self._states[target.signal]
                doc = state.as_dict()
                doc["series"] = target.series
                doc["mode"] = target.mode
                doc["projections"] = {
                    horizon_label(h): state.project(h)
                    for h in self.horizons
                }
                signals[target.signal] = doc
            return {
                "enabled": self._enabled_fn(),
                "alpha": self.alpha,
                "beta": self.beta,
                "horizons_s": list(self.horizons),
                "window_seconds": self.window_seconds,
                "ticks": self._ticks,
                "signals": signals,
            }


class PredictiveAutoscaler:
    """The autoscaler's forecast consult: shadow first, actuate by flag.

    Wired via ``controller.attach_predictive(pred.tick)`` — the reactive
    ``evaluate_once`` consults it only on HOLD decisions, so the
    predictive path can never fight an in-flight reactive action. A
    would-scale tick in shadow mode counts
    ``sparkml_serve_autoscale_total{decision="predictive_shadow"}``; with
    ``SPARK_RAPIDS_ML_TPU_AUTOSCALE_PREDICTIVE=1`` it calls
    ``controller.predictive_scale_up`` (which re-checks cooldown and
    max_replicas under the controller's own lock).
    """

    MIN_UPDATES = 3  # an unseeded trend must not move replicas

    def __init__(
        self,
        controller,
        forecaster: Forecaster,
        *,
        signal: str = "queue_wait_ms",
        horizon_s: Optional[float] = None,
        threshold_ms: Optional[float] = None,
        registry: Optional[metrics_mod.MetricsRegistry] = None,
        actuate_fn: Callable[[], bool] = predictive_actuation_enabled,
    ):
        self.controller = controller
        self.forecaster = forecaster
        self.signal = signal
        self.horizon_s = float(
            horizon_s if horizon_s is not None
            else _env_float(PREDICTIVE_HORIZON_ENV,
                            _DEFAULT_PREDICTIVE_HORIZON_S))
        # breach the SAME bar the reactive path scales on
        self.threshold_ms = float(
            threshold_ms if threshold_ms is not None
            else controller.up_queue_wait_s * 1000.0)
        self._actuate_fn = actuate_fn
        reg = (registry if registry is not None
               else metrics_mod.get_registry())
        self._m_decisions = reg.counter(
            "sparkml_serve_autoscale_total",
            "autoscaler decisions, by kind", ("decision",),
        )
        self._m_decisions.inc(0, decision="predictive_shadow")
        self._m_ticks = reg.counter(
            "sparkml_forecast_predictive_total",
            "predictive-autoscale consult outcomes", ("outcome",),
        )
        self._last_outcome = "never"
        self._last_projection: Optional[float] = None

    def tick(self) -> str:
        """One consult (called from ``evaluate_once`` on HOLD). Returns
        and counts the outcome: ``cold`` (trend unseeded), ``below``
        (projection under threshold), ``at_max``, ``shadow``, or
        ``actuated``."""
        t0 = time.perf_counter()
        state = self.forecaster.state(self.signal)
        if state is None or state.updates < self.MIN_UPDATES:
            return self._count("cold", None)
        projection = state.project(self.horizon_s)
        if projection is None:
            return self._count("cold", None)
        if projection < self.threshold_ms:
            return self._count("below", projection)
        if self.controller.replicas() >= self.controller.max_replicas:
            return self._count("at_max", projection)
        if not self._actuate_fn():
            # shadow: the action we WOULD have taken, visible in the
            # same decision family the real actions count in
            self._m_decisions.inc(decision="predictive_shadow")
            spans_mod.record_event(
                "serve:autoscale:predictive_shadow", t0,
                time.perf_counter(),
                signal=self.signal, projection=projection,
                threshold_ms=self.threshold_ms,
                horizon_s=self.horizon_s,
            )
            return self._count("shadow", projection)
        acted = self.controller.predictive_scale_up({
            "signal": self.signal,
            "projection": projection,
            "threshold_ms": self.threshold_ms,
            "horizon_s": self.horizon_s,
        })
        return self._count("actuated" if acted else "held", projection)

    def _count(self, outcome: str, projection: Optional[float]) -> str:
        self._last_outcome = outcome
        self._last_projection = projection
        self._m_ticks.inc(outcome=outcome)
        return outcome

    def snapshot(self) -> Dict[str, Any]:
        return {
            "signal": self.signal,
            "horizon_s": self.horizon_s,
            "threshold_ms": self.threshold_ms,
            "actuation_enabled": self._actuate_fn(),
            "last_outcome": self._last_outcome,
            "last_projection": self._last_projection,
        }


# -- the process-wide forecaster ----------------------------------------------

_singleton_lock = threading.Lock()
_forecaster: Optional[Forecaster] = None


def get_forecaster() -> Forecaster:
    """The process-wide forecaster ``serve.server`` installs on the
    sampler (get-or-create)."""
    global _forecaster
    with _singleton_lock:
        if _forecaster is None:
            _forecaster = Forecaster()
        return _forecaster


def reset_forecaster() -> None:
    """Drop the process-wide forecaster (tests). Unhooks it from the
    current sampler."""
    global _forecaster
    with _singleton_lock:
        forecaster = _forecaster
        _forecaster = None
    if forecaster is not None:
        try:
            forecaster.uninstall(tsdb_mod.get_sampler())
        except Exception:  # noqa: BLE001 - teardown is best-effort
            pass


__all__ = [
    "ALPHA_ENV",
    "BETA_ENV",
    "ENABLED_ENV",
    "ForecastTarget",
    "Forecaster",
    "HORIZONS_ENV",
    "HoltState",
    "PREDICTIVE_ENV",
    "PREDICTIVE_HORIZON_ENV",
    "PredictiveAutoscaler",
    "QUEUE_WAIT_SERIES",
    "WINDOW_ENV",
    "default_targets",
    "enabled",
    "get_forecaster",
    "horizon_label",
    "predictive_actuation_enabled",
    "reset_forecaster",
]
