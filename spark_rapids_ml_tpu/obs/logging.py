"""Structured JSON logging for library code.

Library modules must never ``print(`` (rule 7 of
``scripts/check_instrumentation.py``): a bare print is invisible to log
shippers, carries no severity, and loses the request identity that the
tracing layer worked to thread through every queue. This module is the
sanctioned spelling — one JSON object per line, machine-parseable, with
the active ``TraceContext``'s trace id stamped automatically so a log
line lands next to its request's spans in whatever aggregator reads the
stream:

    {"ts": "...", "level": "info", "logger": "obs.flight",
     "message": "flight dump written", "trace_id": "…", "path": "…"}

Design constraints:

* stdlib only, and **never raises into the caller** — a logger that can
  crash a dying error path is worse than silence;
* the stream is resolved at emit time (default ``sys.stderr``), so
  pytest's capture and stream redirection both just work;
* level gate via ``SPARK_RAPIDS_ML_TPU_LOG_LEVEL``
  (``debug``/``info``/``warning``/``error``, default ``info``);
* every emitted line is counted in ``sparkml_log_lines_total{level}``
  — log volume is itself a metric the history sampler can watch.
"""

from __future__ import annotations

import json
import os
import sys
import threading
from typing import Any, Dict, Optional

LEVEL_ENV = "SPARK_RAPIDS_ML_TPU_LOG_LEVEL"

_LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40}
_DEFAULT_LEVEL = "info"


def _threshold() -> int:
    raw = os.environ.get(LEVEL_ENV, _DEFAULT_LEVEL).strip().lower()
    return _LEVELS.get(raw, _LEVELS[_DEFAULT_LEVEL])


class StructuredLogger:
    """One named logger emitting single-line JSON records.

    ``stream=None`` (the default) resolves ``sys.stderr`` at emit time;
    pass an open file-like to redirect (tests, log files).
    """

    def __init__(self, name: str, stream=None):
        self.name = name
        self._stream = stream

    def _emit(self, level: str, message: str,
              fields: Dict[str, Any]) -> None:
        if _LEVELS[level] < _threshold():
            return
        try:
            record: Dict[str, Any] = {
                "ts": _utcnow(),
                "level": level,
                "logger": self.name,
                "message": message,
            }
            trace_id = _active_trace_id()
            if trace_id:
                record["trace_id"] = trace_id
            for key, value in fields.items():
                if key not in record:
                    record[key] = value
            line = json.dumps(record, default=str)
            stream = self._stream if self._stream is not None else sys.stderr
            stream.write(line + "\n")
            flush = getattr(stream, "flush", None)
            if callable(flush):
                flush()
            _count_line(level)
        except Exception:
            pass  # a logger must never raise into (or kill) its caller

    def debug(self, message: str, **fields) -> None:
        self._emit("debug", message, fields)

    def info(self, message: str, **fields) -> None:
        self._emit("info", message, fields)

    def warning(self, message: str, **fields) -> None:
        self._emit("warning", message, fields)

    def error(self, message: str, **fields) -> None:
        self._emit("error", message, fields)

    def log(self, level: str, message: str, **fields) -> None:
        if level not in _LEVELS:
            level = "info"
        self._emit(level, message, fields)


def _utcnow() -> str:
    from spark_rapids_ml_tpu.obs.spans import utcnow_iso

    return utcnow_iso()


def _active_trace_id() -> Optional[str]:
    """The active request's trace id (activated ``TraceContext`` first,
    then the innermost open span), or None outside any request."""
    try:
        from spark_rapids_ml_tpu.obs import tracectx

        ctx = tracectx.current_context()
        if ctx is not None:
            return ctx.trace_id
        from spark_rapids_ml_tpu.obs import spans

        return spans.current_trace_id()
    except Exception:
        return None


def _count_line(level: str) -> None:
    try:
        from spark_rapids_ml_tpu.obs.metrics import get_registry

        get_registry().counter(
            "sparkml_log_lines_total",
            "structured log lines emitted, by level", ("level",),
        ).inc(level=level)
    except Exception:
        pass


_loggers: Dict[str, StructuredLogger] = {}
_loggers_lock = threading.Lock()


def get_logger(name: str) -> StructuredLogger:
    """The process-wide logger for ``name`` (cached; one per name)."""
    with _loggers_lock:
        logger = _loggers.get(name)
        if logger is None:
            logger = StructuredLogger(name)
            _loggers[name] = logger
        return logger


__all__ = ["LEVEL_ENV", "StructuredLogger", "get_logger"]
