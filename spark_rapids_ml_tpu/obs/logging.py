"""Structured JSON logging for library code.

Library modules must never ``print(`` (rule 7 of
``scripts/check_instrumentation.py``): a bare print is invisible to log
shippers, carries no severity, and loses the request identity that the
tracing layer worked to thread through every queue. This module is the
sanctioned spelling — one JSON object per line, machine-parseable, with
the active ``TraceContext``'s trace id stamped automatically so a log
line lands next to its request's spans in whatever aggregator reads the
stream:

    {"ts": "...", "level": "info", "logger": "obs.flight",
     "message": "flight dump written", "trace_id": "…", "path": "…"}

Design constraints:

* stdlib only, and **never raises into the caller** — a logger that can
  crash a dying error path is worse than silence;
* the stream is resolved at emit time (default ``sys.stderr``), so
  pytest's capture and stream redirection both just work;
* level gate via ``SPARK_RAPIDS_ML_TPU_LOG_LEVEL``
  (``debug``/``info``/``warning``/``error``, default ``info``);
* every emitted line is counted in ``sparkml_log_lines_total{level}``
  — log volume is itself a metric the history sampler can watch;
* **per-(level, logger) token-bucket rate limiting**: an incident
  storm emitting ERROR per sweep must not flood stderr into
  uselessness. Each (level, logger) pair gets a burst of
  ``SPARK_RAPIDS_ML_TPU_LOG_BURST`` lines (default 50) refilled at
  ``SPARK_RAPIDS_ML_TPU_LOG_RATE`` lines/sec (default 10; <= 0
  disables limiting). Dropped lines are counted in
  ``sparkml_log_suppressed_total{level,logger}`` — suppression is
  itself observable — and the first line emitted after a dry spell
  carries ``suppressed_lines=N`` so a reader of the raw stream sees
  the gap too.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

LEVEL_ENV = "SPARK_RAPIDS_ML_TPU_LOG_LEVEL"
RATE_ENV = "SPARK_RAPIDS_ML_TPU_LOG_RATE"
BURST_ENV = "SPARK_RAPIDS_ML_TPU_LOG_BURST"

_LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40}
_DEFAULT_LEVEL = "info"
_DEFAULT_RATE = 10.0
_DEFAULT_BURST = 50.0


def _threshold() -> int:
    raw = os.environ.get(LEVEL_ENV, _DEFAULT_LEVEL).strip().lower()
    return _LEVELS.get(raw, _LEVELS[_DEFAULT_LEVEL])


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class _TokenBucket:
    """One (level, logger)'s admission state: ``tokens`` refill at
    ``rate``/sec up to ``burst``; each emitted line spends one.
    ``dropped`` accumulates between admissions so the next emitted
    line can report the gap."""

    __slots__ = ("tokens", "last_refill", "dropped")

    def __init__(self, burst: float, now: float):
        self.tokens = burst
        self.last_refill = now
        self.dropped = 0

    def admit(self, rate: float, burst: float, now: float) -> bool:
        elapsed = max(now - self.last_refill, 0.0)
        self.last_refill = now
        self.tokens = min(self.tokens + elapsed * rate, burst)
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        self.dropped += 1
        return False


class StructuredLogger:
    """One named logger emitting single-line JSON records.

    ``stream=None`` (the default) resolves ``sys.stderr`` at emit time;
    pass an open file-like to redirect (tests, log files).
    """

    def __init__(self, name: str, stream=None,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self._stream = stream
        self._clock = clock
        self._buckets: Dict[str, _TokenBucket] = {}
        self._buckets_lock = threading.Lock()

    def _admit(self, level: str) -> Tuple[bool, int]:
        """Token-bucket gate per (level, this logger): (emit?, lines
        suppressed since the last emitted one)."""
        rate = _env_float(RATE_ENV, _DEFAULT_RATE)
        if rate <= 0:
            return True, 0
        burst = max(_env_float(BURST_ENV, _DEFAULT_BURST), 1.0)
        now = self._clock()
        with self._buckets_lock:
            bucket = self._buckets.get(level)
            if bucket is None:
                bucket = _TokenBucket(burst, now)
                self._buckets[level] = bucket
            if bucket.admit(rate, burst, now):
                suppressed, bucket.dropped = bucket.dropped, 0
                return True, suppressed
        _count_suppressed(level, self.name)
        return False, 0

    def _emit(self, level: str, message: str,
              fields: Dict[str, Any]) -> None:
        if _LEVELS[level] < _threshold():
            return
        try:
            admitted, suppressed = self._admit(level)
            if not admitted:
                return
            record: Dict[str, Any] = {
                "ts": _utcnow(),
                "level": level,
                "logger": self.name,
                "message": message,
            }
            if suppressed:
                record["suppressed_lines"] = suppressed
            trace_id = _active_trace_id()
            if trace_id:
                record["trace_id"] = trace_id
            for key, value in fields.items():
                if key not in record:
                    record[key] = value
            line = json.dumps(record, default=str)
            stream = self._stream if self._stream is not None else sys.stderr
            stream.write(line + "\n")
            flush = getattr(stream, "flush", None)
            if callable(flush):
                flush()
            _count_line(level)
        except Exception:
            pass  # a logger must never raise into (or kill) its caller

    def debug(self, message: str, **fields) -> None:
        self._emit("debug", message, fields)

    def info(self, message: str, **fields) -> None:
        self._emit("info", message, fields)

    def warning(self, message: str, **fields) -> None:
        self._emit("warning", message, fields)

    def error(self, message: str, **fields) -> None:
        self._emit("error", message, fields)

    def log(self, level: str, message: str, **fields) -> None:
        if level not in _LEVELS:
            level = "info"
        self._emit(level, message, fields)


def _utcnow() -> str:
    from spark_rapids_ml_tpu.obs.spans import utcnow_iso

    return utcnow_iso()


def _active_trace_id() -> Optional[str]:
    """The active request's trace id (activated ``TraceContext`` first,
    then the innermost open span), or None outside any request."""
    try:
        from spark_rapids_ml_tpu.obs import tracectx

        ctx = tracectx.current_context()
        if ctx is not None:
            return ctx.trace_id
        from spark_rapids_ml_tpu.obs import spans

        return spans.current_trace_id()
    except Exception:
        return None


def _count_line(level: str) -> None:
    try:
        from spark_rapids_ml_tpu.obs.metrics import get_registry

        get_registry().counter(
            "sparkml_log_lines_total",
            "structured log lines emitted, by level", ("level",),
        ).inc(level=level)
    except Exception:
        pass


def _count_suppressed(level: str, logger_name: str) -> None:
    try:
        from spark_rapids_ml_tpu.obs.metrics import get_registry

        get_registry().counter(
            "sparkml_log_suppressed_total",
            "structured log lines dropped by the per-(level,logger) "
            "token bucket (raise SPARK_RAPIDS_ML_TPU_LOG_RATE/"
            "_LOG_BURST, or fix the storm)", ("level", "logger"),
        ).inc(level=level, logger=logger_name)
    except Exception:
        pass


_loggers: Dict[str, StructuredLogger] = {}
_loggers_lock = threading.Lock()


def get_logger(name: str) -> StructuredLogger:
    """The process-wide logger for ``name`` (cached; one per name)."""
    with _loggers_lock:
        logger = _loggers.get(name)
        if logger is None:
            logger = StructuredLogger(name)
            _loggers[name] = logger
        return logger


__all__ = ["BURST_ENV", "LEVEL_ENV", "RATE_ENV", "StructuredLogger",
           "get_logger"]
