"""Online anomaly detectors over the embedded time-series store.

The TSDB (``obs.tsdb``) remembers five minutes of every serving metric;
until now a human had to be looking at ``/dashboard`` to notice a p99
ramp or a queue-depth cliff. This module is the noticing: a registry of
small detectors evaluated against ``TimeSeriesStore.range_query`` once
per sampler sweep (``obs.incidents`` wires them in — no new thread, the
cost lands in the sampler's own ``sparkml_obs_overhead_seconds_total``).

Detector family (each evaluates PER CHILD SERIES, so a finding names
the implicated labels — "p99 spiked" arrives as "p99 spiked for
model=pca_embedder"):

* ``MadSpikeDetector`` — the latest sample vs a robust MAD baseline of
  the series' own trailing window (``obs.robust``, the same math the
  perf sentinel judges bench records with). Right for true gauges that
  recover (queue depth, device memory in use): noisy-but-flat series
  widen their own band and stay quiet; a step change fires.
* ``RateOfChangeDetector`` — the increase across a trailing lookback.
  Right for cumulative-sketch signals like the sampled p99 quantile
  series, which only ever *converge* after an incident (a DDSketch
  never forgets its slow observations): the detector fires on the jump
  and goes quiet once the tail stabilizes, instead of paging forever
  on a level that mathematically cannot come back down.
* ``ThresholdDetector`` — latest sample vs a fixed bound (SLO fast-burn
  gauge > 14.4, the page_fast factor).
* ``RatioDetector`` — windowed delta of a labeled counter child over
  the windowed delta of all its siblings (error fraction of
  ``sparkml_serve_requests_total``), with a min-traffic floor so one
  failure among three requests cannot read as a 33% outage.
* ``DeltaDetector`` — reset-aware counter increase over a window
  (breaker FLAPS: ≥ 3 opens — one legitimate open is self-healing
  working, three is a breaker oscillating against a sick backend).

``builtin_detectors()`` is the shipped catalog: serve p99, queue depth,
error rate, device memory in use, breaker flaps, SLO fast-burn. Short
windows scale with ``SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_WINDOW_S``
(default 60) so a chaos drill can compress the whole detect→resolve
loop into seconds.

Everything here is pure arithmetic over ``(timestamp, value)`` points
plus the caller-provided ``now`` — no wall-clock reads (enforced by
``scripts/check_instrumentation.py`` rule 8), so tests drive hours of
detection through an injected clock with zero sleeps.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from spark_rapids_ml_tpu.obs import tsdb as tsdb_mod
from spark_rapids_ml_tpu.obs.robust import (
    baseline_stats,
    robust_zscore,
)

WINDOW_ENV = "SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_WINDOW_S"
_DEFAULT_WINDOW_S = 60.0

# Severity ladder shared with obs.incidents (burn-rate escalation).
SEVERITIES = ("info", "warning", "serious", "critical")


def short_window_seconds() -> float:
    """The catalog's short window (spike/lookback/error-rate horizon)
    from ``SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_WINDOW_S``."""
    try:
        w = float(os.environ.get(WINDOW_ENV, _DEFAULT_WINDOW_S))
    except ValueError:
        return _DEFAULT_WINDOW_S
    return w if w > 0 else _DEFAULT_WINDOW_S


@dataclass(frozen=True)
class Finding:
    """One detector firing on one series, for one sweep."""

    detector: str
    kind: str  # latency | saturation | errors | memory | breaker | slo
    severity: str
    metric: str
    labels: Dict[str, Any] = field(default_factory=dict)
    value: float = 0.0
    baseline: Optional[float] = None
    reason: str = ""

    @property
    def key(self) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
        """The dedup identity: one incident per (detector, series)."""
        return (self.detector,
                tuple(sorted((str(k), str(v))
                             for k, v in self.labels.items())))

    def as_dict(self) -> Dict[str, Any]:
        return {
            "detector": self.detector,
            "kind": self.kind,
            "severity": self.severity,
            "metric": self.metric,
            "labels": dict(self.labels),
            "value": self.value,
            "baseline": self.baseline,
            "reason": self.reason,
        }


class Detector:
    """Base detector: named, typed, evaluated per matching child series.

    Subclasses implement ``_judge(points, now)`` → ``(value, baseline,
    reason) | None`` over ONE series' ascending ``[ts, value]`` points.
    """

    #: how wide a history slice the detector needs per evaluation
    query_window: float = 300.0

    def __init__(self, name: str, metric: str, *,
                 labels: Optional[Dict[str, str]] = None,
                 kind: str = "anomaly", severity: str = "warning"):
        self.name = name
        self.metric = metric
        self.labels = dict(labels or {})
        self.kind = kind
        self.severity = severity

    def describe(self) -> Dict[str, Any]:
        """Catalog entry for ``/debug/incidents`` and the README."""
        return {
            "name": self.name,
            "metric": self.metric,
            "labels": dict(self.labels),
            "kind": self.kind,
            "severity": self.severity,
            "type": type(self).__name__,
        }

    def evaluate(self, store: tsdb_mod.TimeSeriesStore,
                 now: float) -> List[Finding]:
        findings: List[Finding] = []
        for series in store.range_query(self.metric, self.labels or None,
                                        self.query_window, now=now):
            points = series["points"]
            if not points:
                continue
            verdict = self._judge(points, now, series)
            if verdict is None:
                continue
            value, baseline, reason = verdict
            findings.append(Finding(
                detector=self.name, kind=self.kind,
                severity=self.severity, metric=self.metric,
                labels=dict(series["labels"]), value=value,
                baseline=baseline, reason=reason,
            ))
        return findings

    def _judge(self, points: Sequence[Sequence[float]], now: float,
               series: Optional[Dict[str, Any]] = None):
        raise NotImplementedError


class MadSpikeDetector(Detector):
    """Latest sample vs the MAD noise band of its own trailing baseline.

    The baseline is every point older than ``spike_window``; the value
    is the newest point. Fires when ALL of:

    * the robust z-score exceeds ``z_threshold`` (a noisy-but-flat
      series has a wide MAD and stays quiet);
    * the step clears ``min_relative·|median| + min_step`` (a constant
      baseline has MAD 0 and an infinite z — the absolute guard keeps a
      0.1% wiggle off a flat line from paging);
    * the value is at least ``min_value`` (an empty queue going 0 → 3
      is not saturation).
    """

    def __init__(self, name: str, metric: str, *,
                 labels: Optional[Dict[str, str]] = None,
                 kind: str = "anomaly", severity: str = "warning",
                 baseline_window: float = 300.0,
                 spike_window: float = 15.0,
                 z_threshold: float = 4.0,
                 min_relative: float = 0.5,
                 min_step: float = 0.0,
                 min_value: float = 0.0,
                 min_points: int = 8):
        super().__init__(name, metric, labels=labels, kind=kind,
                         severity=severity)
        self.query_window = float(baseline_window)
        self.spike_window = float(spike_window)
        self.z_threshold = float(z_threshold)
        self.min_relative = float(min_relative)
        self.min_step = float(min_step)
        self.min_value = float(min_value)
        self.min_points = int(min_points)

    def describe(self) -> Dict[str, Any]:
        doc = super().describe()
        doc.update(baseline_window=self.query_window,
                   spike_window=self.spike_window,
                   z_threshold=self.z_threshold,
                   min_relative=self.min_relative,
                   min_step=self.min_step, min_value=self.min_value)
        return doc

    def _judge(self, points, now, series=None):
        cutoff = now - self.spike_window
        baseline = [v for ts, v in points if ts <= cutoff]
        if len(baseline) < self.min_points:
            return None
        value = points[-1][1]
        if value < self.min_value:
            return None
        stats = baseline_stats(baseline)
        med = stats["median"]
        step = value - med
        if step < self.min_relative * abs(med) + self.min_step:
            return None
        z = robust_zscore(value, baseline)
        if z < self.z_threshold:
            return None
        return value, med, (
            f"{self.metric} at {value:g} vs robust baseline median "
            f"{med:g} (MAD {stats['mad']:g}, z {z:g} >= "
            f"{self.z_threshold:g} over {len(baseline)} samples)"
        )


class RateOfChangeDetector(Detector):
    """Increase across a trailing lookback window.

    Fires while the newest sample sits ``min_step`` AND
    ``min_relative×`` above the oldest sample in the lookback — i.e.
    while the jump is still inside the window. Once the series
    plateaus (the step ages out), the detector goes quiet, which is
    what resolves an incident on a cumulative-sketch signal whose
    level can never return to baseline.
    """

    def __init__(self, name: str, metric: str, *,
                 labels: Optional[Dict[str, str]] = None,
                 kind: str = "anomaly", severity: str = "warning",
                 lookback: float = 60.0,
                 min_relative: float = 1.0,
                 min_step: float = 0.0,
                 min_points: int = 4):
        super().__init__(name, metric, labels=labels, kind=kind,
                         severity=severity)
        self.query_window = float(lookback)
        self.min_relative = float(min_relative)
        self.min_step = float(min_step)
        self.min_points = int(min_points)

    def describe(self) -> Dict[str, Any]:
        doc = super().describe()
        doc.update(lookback=self.query_window,
                   min_relative=self.min_relative,
                   min_step=self.min_step)
        return doc

    def _judge(self, points, now, series=None):
        if len(points) < self.min_points:
            return None
        old = points[0][1]
        value = points[-1][1]
        increase = value - old
        if increase < self.min_step:
            return None
        if old > 0 and increase < self.min_relative * abs(old):
            return None
        return value, old, (
            f"{self.metric} rose {increase:g} (from {old:g} to "
            f"{value:g}) inside the {self.query_window:g}s lookback"
        )


class ThresholdDetector(Detector):
    """Latest sample vs a fixed bound (direction ``\">\"`` or
    ``\"<\"``). A series with no sample newer than ``stale_after`` is
    skipped — a gauge nobody updates is absence of signal, not an
    anomaly."""

    def __init__(self, name: str, metric: str, *,
                 threshold: float,
                 labels: Optional[Dict[str, str]] = None,
                 direction: str = ">",
                 kind: str = "anomaly", severity: str = "warning",
                 stale_after: float = 120.0):
        if direction not in (">", "<"):
            raise ValueError(f"direction must be '>' or '<', "
                             f"got {direction!r}")
        super().__init__(name, metric, labels=labels, kind=kind,
                         severity=severity)
        self.threshold = float(threshold)
        self.direction = direction
        self.query_window = float(stale_after)

    def describe(self) -> Dict[str, Any]:
        doc = super().describe()
        doc.update(threshold=self.threshold, direction=self.direction)
        return doc

    def _judge(self, points, now, series=None):
        ts, value = points[-1]
        if now - ts > self.query_window:
            return None
        fired = (value > self.threshold if self.direction == ">"
                 else value < self.threshold)
        if not fired:
            return None
        return value, self.threshold, (
            f"{self.metric} at {value:g} {self.direction} threshold "
            f"{self.threshold:g}"
        )


class RatioDetector(Detector):
    """Windowed delta of one labeled child over the delta of ALL
    children sharing the remaining labels (error fraction of a
    requests-by-outcome counter). Fires per group — the finding's
    labels are the group labels (e.g. ``model=...``), never the
    selector's."""

    def __init__(self, name: str, metric: str, *,
                 select: Dict[str, str],
                 threshold: float,
                 window: float = 60.0,
                 min_total: float = 10.0,
                 kind: str = "errors", severity: str = "serious"):
        super().__init__(name, metric, labels=None, kind=kind,
                         severity=severity)
        self.select = dict(select)
        self.threshold = float(threshold)
        self.query_window = float(window)
        self.min_total = float(min_total)

    def describe(self) -> Dict[str, Any]:
        doc = super().describe()
        doc.update(select=dict(self.select), threshold=self.threshold,
                   window=self.query_window, min_total=self.min_total)
        return doc

    def evaluate(self, store, now):
        groups: Dict[Tuple[Tuple[str, str], ...],
                     List[float]] = {}  # key -> [selected, total]
        select_keys = set(self.select)
        for series in store.range_query(self.metric, None,
                                        self.query_window, now=now):
            labels = dict(series["labels"])
            group = tuple(sorted(
                (k, str(v)) for k, v in labels.items()
                if k not in select_keys
            ))
            # birth-aware: the first error of a storm usually MINTS the
            # outcome="error" child between two samples — its first
            # sampled value must count as increase or the detector is
            # blind to exactly the burst it watches for
            inc = tsdb_mod.windowed_increase(
                series, now - self.query_window)
            bucket = groups.setdefault(group, [0.0, 0.0])
            if all(str(labels.get(k)) == str(v)
                   for k, v in self.select.items()):
                bucket[0] += inc
            bucket[1] += inc
        findings: List[Finding] = []
        for group, (selected, total) in sorted(groups.items()):
            if total < self.min_total:
                continue
            ratio = selected / total
            if ratio <= self.threshold:
                continue
            sel = ",".join(f"{k}={v}" for k, v in self.select.items())
            findings.append(Finding(
                detector=self.name, kind=self.kind,
                severity=self.severity, metric=self.metric,
                labels=dict(group), value=ratio,
                baseline=self.threshold,
                reason=(
                    f"{sel} fraction of {self.metric} is "
                    f"{ratio:.1%} ({selected:g}/{total:g}) over "
                    f"{self.query_window:g}s, above "
                    f"{self.threshold:.1%}"
                ),
            ))
        return findings

    def _judge(self, points, now, series=None):  # pragma: no cover - unused
        raise NotImplementedError("RatioDetector overrides evaluate")


class DeltaDetector(Detector):
    """Reset-aware counter increase over a window ≥ ``min_delta``
    (breaker-flap counting)."""

    def __init__(self, name: str, metric: str, *,
                 min_delta: float,
                 labels: Optional[Dict[str, str]] = None,
                 window: float = 300.0,
                 kind: str = "breaker", severity: str = "serious"):
        super().__init__(name, metric, labels=labels, kind=kind,
                         severity=severity)
        self.min_delta = float(min_delta)
        self.query_window = float(window)

    def describe(self) -> Dict[str, Any]:
        doc = super().describe()
        doc.update(min_delta=self.min_delta, window=self.query_window)
        return doc

    def _judge(self, points, now, series=None):
        # birth-aware like RatioDetector: the first breaker open mints
        # the state="open" child — its first sampled value is a real
        # transition, not ring history that aged out
        delta = (tsdb_mod.windowed_increase(series,
                                            now - self.query_window)
                 if series is not None
                 else tsdb_mod.counter_increase(points))
        if delta < self.min_delta:
            return None
        return delta, self.min_delta, (
            f"{self.metric} increased {delta:g} times in "
            f"{self.query_window:g}s (flap threshold "
            f"{self.min_delta:g})"
        )


def builtin_detectors(
        short_window: Optional[float] = None) -> List[Detector]:
    """The shipped catalog over the serving tier's key series.

    ``short_window`` (default ``SPARK_RAPIDS_ML_TPU_OBS_INCIDENT_
    WINDOW_S``, 60 s) scales the fast horizons — spike windows,
    p99 lookback, the error-rate window — so drills can compress the
    whole loop; baselines stay at the TSDB's 5-minute tier.
    """
    w = float(short_window if short_window is not None
              else short_window_seconds())
    return [
        # p99 rides the cumulative latency sketch: watch for jumps,
        # resolve on plateau (the level itself can never come back).
        RateOfChangeDetector(
            "serve_p99_spike",
            "sparkml_serve_request_latency_seconds",
            labels={"quantile": "0.99"},
            kind="latency", severity="serious",
            lookback=w, min_relative=1.0, min_step=0.02,
        ),
        MadSpikeDetector(
            "serve_queue_depth",
            "sparkml_serve_queue_depth",
            kind="saturation", severity="warning",
            baseline_window=max(5 * w, 300.0), spike_window=w / 4.0,
            z_threshold=4.0, min_relative=0.5, min_step=4.0,
            min_value=8.0,
        ),
        RatioDetector(
            "serve_error_rate",
            "sparkml_serve_requests_total",
            select={"outcome": "error"},
            threshold=0.05, window=w, min_total=10.0,
            kind="errors", severity="serious",
        ),
        MadSpikeDetector(
            "device_mem_in_use",
            "sparkml_device_mem_bytes_in_use",
            kind="memory", severity="warning",
            baseline_window=max(5 * w, 300.0), spike_window=w / 4.0,
            z_threshold=4.0, min_relative=0.25,
            min_step=16 * 1024 * 1024, min_value=64 * 1024 * 1024,
        ),
        DeltaDetector(
            "breaker_flap",
            "sparkml_serve_breaker_transitions_total",
            labels={"state": "open"},
            min_delta=3.0, window=max(6 * w, 120.0),
            kind="breaker", severity="serious",
        ),
        # page_fast factor from the SRE-workbook ladder; the gauge is
        # republished every sweep by the engine's sampler collector.
        ThresholdDetector(
            "slo_fast_burn",
            "sparkml_slo_burn_rate",
            labels={"window": "5m"},
            threshold=14.4, direction=">",
            kind="slo", severity="critical",
            stale_after=max(2 * w, 120.0),
        ),
        # The replica tier (serve/placement.py): 0 serving, 1 draining,
        # 2 dead — any replica above 0 means a device left the
        # placement set and its traffic is shedding onto siblings. The
        # incident auto-resolves when the half-open probe re-enters the
        # replica (the gauge drops back to 0).
        ThresholdDetector(
            "serve_replica_degraded",
            "sparkml_serve_replica_state",
            threshold=0.5, direction=">",
            kind="replica", severity="serious",
            stale_after=max(2 * w, 120.0),
        ),
        # The rollout tier (serve/rollout.py): the canary controller
        # raises this gauge to 1 when an experiment auto-rolls back —
        # the series labels {model, candidate} NAME the regressed
        # candidate version, so the incident (and its evidence bundle)
        # carries exactly which version burned the canary. The
        # controller clears the gauge after ROLLOUT_REGRESSED_HOLD_S,
        # which is what lets the incident auto-resolve.
        ThresholdDetector(
            "serve_canary_regressed",
            "sparkml_serve_canary_regressed",
            threshold=0.5, direction=">",
            kind="rollout", severity="critical",
            stale_after=max(2 * w, 120.0),
        ),
        # The fit-path backend watchdog (obs/fitmon.py): the gauge drops
        # to 0 when the resolved JAX platform silently differs from the
        # configured expectation or the canary dispatch wedges — the r04
        # failure mode, where every bench round fell back to CPU and
        # nobody noticed until the perf sentinel read the records. The
        # unlabeled gauge is a single series, so the dedup key yields
        # exactly ONE incident, auto-resolving when the watchdog's next
        # check publishes 1 again.
        ThresholdDetector(
            "fit_backend_degraded",
            "sparkml_fit_backend_ok",
            threshold=0.5, direction="<",
            kind="backend", severity="critical",
            stale_after=max(2 * w, 120.0),
        ),
        # Fleet federation (obs/federation.py): the aggregator holds
        # sparkml_fleet_host_up{host} at 1 while a peer's export
        # endpoint answers within the staleness grace and drops it to 0
        # when the peer goes silent. Per-host labels make the dedup key
        # per-host, so a dead peer is exactly ONE incident that
        # auto-resolves when the (respawned) peer answers again under
        # the SAME SPARK_RAPIDS_ML_TPU_FLEET_HOST identity.
        ThresholdDetector(
            "fleet_host_down",
            "sparkml_fleet_host_up",
            threshold=0.5, direction="<",
            kind="fleet", severity="critical",
            stale_after=max(2 * w, 120.0),
        ),
    ]


__all__ = [
    "DeltaDetector",
    "Detector",
    "Finding",
    "MadSpikeDetector",
    "RateOfChangeDetector",
    "RatioDetector",
    "SEVERITIES",
    "ThresholdDetector",
    "WINDOW_ENV",
    "builtin_detectors",
    "short_window_seconds",
]
