"""Request-scoped trace context: W3C traceparent in, spans out.

The serving tier (PR 4) carries one request across four thread boundaries
— HTTP handler → engine → batcher queue → batcher worker → response latch
— and until now each hop minted its own span trace id, so a slow request
could not be followed across the queue/batch/compile seams. This module is
the Dapper-style propagation layer (PAPERS.md: "Dapper, a Large-Scale
Distributed Systems Tracing Infrastructure"):

* ``TraceContext`` — an immutable ``(trace_id, span_id, sampled, baggage)``
  tuple. ``trace_id`` is the W3C 32-hex request identity; ``span_id`` is
  the 16-hex id of the context's current span (the parent of anything
  started under it).
* ``parse_traceparent`` / ``TraceContext.traceparent()`` — the W3C Trace
  Context header format (``00-<trace>-<span>-<flags>``), so an inbound
  ``traceparent`` header continues an external trace and responses hand
  the id back.
* ``current_context()`` / ``activate(ctx)`` / ``capture()`` — the
  contextvar plumbing. ``capture()`` at an enqueue site and
  ``activate(ctx)`` on the far side of the handoff is the contract
  ``scripts/check_instrumentation.py`` rule 5 statically enforces inside
  ``serve/``: a queue or thread may never launder a request's identity
  away.
* ``traced_thread(...)`` — the only sanctioned way to start a thread in
  ``serve/``: it snapshots the caller's ``contextvars`` (or starts from a
  fresh root with ``fresh=True`` for long-lived workers) so spans opened
  in the child attribute correctly.
* ``track_request(...)`` / ``inflight_requests()`` — a cross-thread table
  of in-flight requests (trace id, model, elapsed) the flight recorder
  embeds in every dump: a watchdog dump now names WHICH requests were on
  the device when the process wedged.

``obs.spans`` consults ``current_context()`` when a root span opens, so
every span (and every ``TransformReport``) under an activated context
carries the request's trace id without any call-site changes.
"""

from __future__ import annotations

import contextlib
import contextvars
import re
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Mapping, Optional

TRACEPARENT_HEADER = "traceparent"
_TRACEPARENT_RE = re.compile(
    r"^(?P<version>[0-9a-f]{2})-(?P<trace_id>[0-9a-f]{32})-"
    r"(?P<span_id>[0-9a-f]{16})-(?P<flags>[0-9a-f]{2})$"
)


def new_trace_id() -> str:
    """A 32-hex W3C trace id (never all-zero)."""
    return uuid.uuid4().hex


def new_span_id() -> str:
    """A 16-hex W3C span id (never all-zero)."""
    return uuid.uuid4().hex[:16]


@dataclass(frozen=True)
class TraceContext:
    """One request's identity as it crosses queue/thread boundaries."""

    trace_id: str
    span_id: str
    sampled: bool = True
    baggage: Mapping[str, str] = field(default_factory=dict)

    def child(self, **baggage) -> "TraceContext":
        """A new context in the SAME trace with a fresh span id — what a
        hop activates so its spans parent under the captured one."""
        merged = dict(self.baggage)
        merged.update(baggage)
        return TraceContext(
            trace_id=self.trace_id,
            span_id=new_span_id(),
            sampled=self.sampled,
            baggage=merged,
        )

    def traceparent(self) -> str:
        """The W3C ``traceparent`` header value for this context."""
        flags = "01" if self.sampled else "00"
        return f"00-{self.trace_id}-{self.span_id}-{flags}"

    def as_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "sampled": self.sampled,
            "baggage": dict(self.baggage),
        }


def new_context(**baggage) -> TraceContext:
    """Mint a fresh root context (no inbound traceparent)."""
    return TraceContext(
        trace_id=new_trace_id(), span_id=new_span_id(), baggage=baggage
    )


def parse_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """A ``TraceContext`` from a W3C ``traceparent`` header, or None for a
    missing/malformed/all-zero header (the spec says ignore and restart)."""
    if not header or not isinstance(header, str):
        return None
    match = _TRACEPARENT_RE.match(header.strip().lower())
    if not match:
        return None
    trace_id = match.group("trace_id")
    span_id = match.group("span_id")
    if match.group("version") == "ff":
        return None  # forbidden version
    if set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None  # all-zero ids are invalid per spec
    sampled = bool(int(match.group("flags"), 16) & 0x01)
    return TraceContext(trace_id=trace_id, span_id=span_id, sampled=sampled)


# -- the contextvar plumbing -------------------------------------------------


_current: contextvars.ContextVar = contextvars.ContextVar(
    "sparkml_trace_context", default=None
)


def current_context() -> Optional[TraceContext]:
    """The active request's context in THIS thread/task, or None."""
    return _current.get()


def capture() -> Optional[TraceContext]:
    """Capture the active context for a queue/thread handoff (the enqueue
    half of the rule-5 contract). Returns None outside any request —
    callers hand the value to ``activate`` verbatim either way."""
    return _current.get()


@contextlib.contextmanager
def activate(ctx: Optional[TraceContext]):
    """Re-activate a captured context on the far side of a handoff (the
    dequeue half of the rule-5 contract). ``activate(None)`` is a no-op
    context so call sites never need to branch."""
    if ctx is None:
        yield None
        return
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def ensure_context(**baggage) -> TraceContext:
    """The active context, or a freshly minted root (for entry points —
    ``ServeEngine.predict`` called directly, benches, tests — that must
    always produce an attributable trace)."""
    ctx = _current.get()
    return ctx if ctx is not None else new_context(**baggage)


def traced_thread(
    target: Callable,
    *,
    name: Optional[str] = None,
    daemon: bool = True,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    fresh: bool = False,
) -> threading.Thread:
    """A ``threading.Thread`` whose target runs under a contextvars
    snapshot: ``fresh=False`` copies the caller's context (one-shot
    handoffs inherit the live request), ``fresh=True`` starts from an
    empty root context (long-lived workers — a batcher worker created
    during request A must not attribute request B's idle time to A).
    Rule 5 rejects raw ``threading.Thread`` construction in ``serve/``;
    this is the sanctioned spelling."""
    run_ctx = (contextvars.Context() if fresh
               else contextvars.copy_context())
    kwargs = kwargs or {}

    def _run():
        run_ctx.run(target, *args, **kwargs)

    return threading.Thread(target=_run, name=name, daemon=daemon)


# -- the in-flight request table ---------------------------------------------

_inflight_lock = threading.Lock()
_inflight: Dict[int, Dict[str, Any]] = {}
_inflight_seq = 0


def track_request(ctx: TraceContext, **info) -> int:
    """Register an in-flight request (flight dumps embed the table);
    returns the handle ``untrack_request`` takes."""
    global _inflight_seq
    with _inflight_lock:
        _inflight_seq += 1
        handle = _inflight_seq
        _inflight[handle] = {
            "seq": handle,
            "trace_id": ctx.trace_id,
            "span_id": ctx.span_id,
            "t0": time.monotonic(),
            "info": dict(info),
        }
    return handle


def untrack_request(handle: int) -> None:
    with _inflight_lock:
        _inflight.pop(handle, None)


@contextlib.contextmanager
def inflight_request(ctx: TraceContext, **info):
    """Track one request for the duration of a block (the engine wraps
    every ``predict`` in this, so a watchdog dump shows which requests
    were in flight and for how long)."""
    handle = track_request(ctx, **info)
    try:
        yield handle
    finally:
        untrack_request(handle)


def inflight_requests() -> List[Dict[str, Any]]:
    """The active trace table, oldest first: ``{trace_id, span_id,
    elapsed_seconds, info}`` per in-flight request."""
    now = time.monotonic()
    with _inflight_lock:
        entries = sorted(_inflight.values(), key=lambda e: e["seq"])
        return [
            {
                "trace_id": e["trace_id"],
                "span_id": e["span_id"],
                "elapsed_seconds": now - e["t0"],
                "info": dict(e["info"]),
            }
            for e in entries
        ]


__all__ = [
    "TRACEPARENT_HEADER",
    "TraceContext",
    "activate",
    "capture",
    "current_context",
    "ensure_context",
    "inflight_request",
    "inflight_requests",
    "new_context",
    "new_span_id",
    "new_trace_id",
    "parse_traceparent",
    "traced_thread",
    "track_request",
    "untrack_request",
]
