"""SLO objectives and multi-window burn-rate alerting for the serving tier.

The metrics registry answers "what is the p99 right now"; this module
answers the operator question on top of it: *is the service meeting its
objective, and should anyone be paged?* — the Google SRE workbook's
multi-window multi-burn-rate method, applied to the serve/ request stream:

* ``SLO`` — one declarative objective: **availability** (fraction of
  requests that succeed) or **latency** (fraction of requests faster than
  a threshold), with a target like 99.9% over a rolling budget window.
  Every served request is ``record()``-ed good/bad; counts land in a
  time-bucketed ring (``WindowedCounts``) so any trailing window's error
  rate is O(buckets) to read and memory stays bounded.
* **burn rate** — error-rate ÷ error-budget for a trailing window: burn 1
  means exactly spending the budget, burn 14.4 means the 30-day budget is
  gone in 2 days. Alert policies pair a long window (is it sustained?)
  with a short one (is it still happening?), both of which must exceed
  the factor to fire:

  - ``page_fast``: 5 m AND 1 h above **14.4**;
  - ``page_slow``: 30 m AND 6 h above **6.0**.

  A short latency spike therefore flips ``page_fast`` while the 6 h
  window stays quiet, and a recovered outage stops paging as soon as the
  short window clears — exactly the workbook semantics.
* ``SloSet`` — the engine-facing bundle: ``record_request(ok, latency)``
  feeds every objective, ``snapshot()`` is the ``GET /debug/slo``
  document, ``publish()`` mirrors burn rates / budget remaining / firing
  alerts into the metrics registry (``sparkml_slo_*`` gauges) so the
  Prometheus surface carries the verdict too.

Wall-clock is injectable everywhere (``clock=``): tests drive hours of
traffic through a fake clock with zero real sleeps. ``ServeEngine`` wires
``default_slos()`` in by default; knobs:

* ``SPARK_RAPIDS_ML_TPU_SLO_AVAILABILITY_TARGET`` (default ``0.999``;
  ``0`` disables the availability objective);
* ``SPARK_RAPIDS_ML_TPU_SLO_LATENCY_TARGET`` (default ``0.99``; ``0``
  disables the latency objective);
* ``SPARK_RAPIDS_ML_TPU_SLO_LATENCY_THRESHOLD_MS`` (default ``250``);
* ``SPARK_RAPIDS_ML_TPU_SLO_WINDOW_HOURS`` — the budget-remaining window
  (default ``6``, the longest alert window).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

ENV_PREFIX = "SPARK_RAPIDS_ML_TPU_SLO_"

# The SRE-workbook two-policy ladder. Both windows of a policy must burn
# above the factor: the long window proves it is sustained, the short one
# proves it is still happening (so recovered incidents stop paging).
BURN_POLICIES: Tuple[Dict[str, Any], ...] = (
    {"severity": "page_fast", "factor": 14.4,
     "short_seconds": 300.0, "long_seconds": 3600.0},
    {"severity": "page_slow", "factor": 6.0,
     "short_seconds": 1800.0, "long_seconds": 21600.0},
)

_WINDOW_LABELS = {300.0: "5m", 1800.0: "30m", 3600.0: "1h", 21600.0: "6h"}


def _window_label(seconds: float) -> str:
    label = _WINDOW_LABELS.get(float(seconds))
    if label:
        return label
    if seconds % 3600 == 0:
        return f"{int(seconds // 3600)}h"
    if seconds % 60 == 0:
        return f"{int(seconds // 60)}m"
    return f"{int(seconds)}s"


class WindowedCounts:
    """Good/total event counts in fixed time buckets over a bounded
    horizon — any trailing window's counts in O(window/bucket) with
    O(horizon/bucket) memory, regardless of traffic volume."""

    def __init__(
        self,
        horizon_seconds: float = 6 * 3600.0 + 1800.0,
        bucket_seconds: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if bucket_seconds <= 0:
            raise ValueError("bucket_seconds must be > 0")
        self.bucket_seconds = float(bucket_seconds)
        self.horizon_seconds = float(horizon_seconds)
        self.clock = clock
        self._lock = threading.Lock()
        self._buckets: Dict[int, List[float]] = {}  # idx -> [good, total]

    def _prune(self, now: float) -> None:
        cap = int(self.horizon_seconds / self.bucket_seconds) + 2
        if len(self._buckets) <= cap:
            return
        floor = int((now - self.horizon_seconds) // self.bucket_seconds)
        self._buckets = {
            idx: counts for idx, counts in self._buckets.items()
            if idx >= floor
        }

    def record(self, good: bool, n: int = 1,
               now: Optional[float] = None) -> None:
        now = self.clock() if now is None else now
        idx = int(now // self.bucket_seconds)
        with self._lock:
            bucket = self._buckets.get(idx)
            if bucket is None:
                bucket = [0.0, 0.0]
                self._buckets[idx] = bucket
                self._prune(now)
            if good:
                bucket[0] += n
            bucket[1] += n

    def counts(self, window_seconds: float,
               now: Optional[float] = None) -> Tuple[float, float]:
        """(good, total) over the trailing window ending at ``now``.

        The boundary bucket is INCLUDED (the effective window rounds up
        to a whole bucket) — for alerting, slightly over-counting old
        badness is the conservative direction."""
        now = self.clock() if now is None else now
        floor = int((now - window_seconds) // self.bucket_seconds)
        ceil = int(now // self.bucket_seconds)
        good = total = 0.0
        with self._lock:
            for idx, (g, t) in self._buckets.items():
                if floor <= idx <= ceil:
                    good += g
                    total += t
        return good, total


class SLO:
    """One declarative objective over the serving request stream."""

    def __init__(
        self,
        name: str,
        target: float = 0.999,
        kind: str = "availability",
        latency_threshold_seconds: Optional[float] = None,
        window_seconds: float = 6 * 3600.0,
        bucket_seconds: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
        policies: Sequence[Dict[str, Any]] = BURN_POLICIES,
    ):
        if not 0.0 < target < 1.0:
            raise ValueError(f"target must be in (0, 1), got {target}")
        if kind not in ("availability", "latency"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        if kind == "latency" and not latency_threshold_seconds:
            raise ValueError("latency SLO needs latency_threshold_seconds")
        self.name = name
        self.target = float(target)
        self.kind = kind
        self.latency_threshold_seconds = (
            float(latency_threshold_seconds)
            if latency_threshold_seconds else None
        )
        self.window_seconds = float(window_seconds)
        self.clock = clock
        self.policies = tuple(dict(p) for p in policies)
        horizon = max(
            [self.window_seconds]
            + [p["long_seconds"] for p in self.policies]
        ) + 2 * bucket_seconds
        self._counts = WindowedCounts(
            horizon_seconds=horizon, bucket_seconds=bucket_seconds,
            clock=clock,
        )

    @property
    def error_budget(self) -> float:
        return 1.0 - self.target

    def is_good(self, ok: bool,
                latency_seconds: Optional[float] = None) -> bool:
        if not ok:
            return False
        if self.kind == "latency":
            return (latency_seconds is not None
                    and latency_seconds <= self.latency_threshold_seconds)
        return True

    def record(self, ok: bool, latency_seconds: Optional[float] = None,
               n: int = 1, now: Optional[float] = None) -> None:
        self._counts.record(self.is_good(ok, latency_seconds), n=n, now=now)

    def _rate(self, good: float, total: float) -> float:
        """Error-rate ÷ error budget from window counts (0.0 with no
        traffic: an idle service burns nothing). THE burn arithmetic —
        ``burn_rate`` and ``SloSet.fast_burn_rate`` both go through it,
        so the breaker trip signal can never diverge from alerting."""
        if total <= 0:
            return 0.0
        return ((total - good) / total) / self.error_budget

    def burn_rate(self, window_seconds: float,
                  now: Optional[float] = None) -> float:
        """Error-rate over the window ÷ error budget."""
        good, total = self._counts.counts(window_seconds, now=now)
        return self._rate(good, total)

    def burn_rates(self, now: Optional[float] = None
                   ) -> Dict[str, float]:
        windows = sorted({
            w for p in self.policies
            for w in (p["short_seconds"], p["long_seconds"])
        })
        return {
            _window_label(w): self.burn_rate(w, now=now) for w in windows
        }

    def budget_remaining(self, now: Optional[float] = None) -> float:
        """Fraction of the error budget left over ``window_seconds``
        (1.0 = untouched, 0.0 = spent, negative = blown)."""
        return 1.0 - self.burn_rate(self.window_seconds, now=now)

    def firing(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Alert dicts for every policy whose BOTH windows burn above its
        factor — multi-window AND semantics."""
        now = self.clock() if now is None else now
        alerts = []
        for policy in self.policies:
            short = self.burn_rate(policy["short_seconds"], now=now)
            long_ = self.burn_rate(policy["long_seconds"], now=now)
            if short > policy["factor"] and long_ > policy["factor"]:
                alerts.append({
                    "slo": self.name,
                    "severity": policy["severity"],
                    "factor": policy["factor"],
                    "short_window": _window_label(policy["short_seconds"]),
                    "short_burn_rate": short,
                    "long_window": _window_label(policy["long_seconds"]),
                    "long_burn_rate": long_,
                })
        return alerts

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = self.clock() if now is None else now
        good, total = self._counts.counts(self.window_seconds, now=now)
        objective = (
            f"{self.target:.6g} of requests succeed"
            if self.kind == "availability" else
            f"{self.target:.6g} of requests faster than "
            f"{self.latency_threshold_seconds * 1000:g} ms"
        )
        return {
            "name": self.name,
            "kind": self.kind,
            "target": self.target,
            "objective": objective,
            "latency_threshold_seconds": self.latency_threshold_seconds,
            "window": _window_label(self.window_seconds),
            "window_good": good,
            "window_total": total,
            "burn_rates": self.burn_rates(now=now),
            "budget_remaining": self.budget_remaining(now=now),
            "alerts": self.firing(now=now),
        }


class SloSet:
    """The engine-facing bundle of objectives sharing one request feed."""

    def __init__(self, slos: Sequence[SLO] = (),
                 clock: Callable[[], float] = time.monotonic):
        self.slos: List[SLO] = list(slos)
        self.clock = clock

    def __iter__(self):
        return iter(self.slos)

    def __len__(self):
        return len(self.slos)

    def get(self, name: str) -> Optional[SLO]:
        for slo in self.slos:
            if slo.name == name:
                return slo
        return None

    def record_request(self, ok: bool,
                       latency_seconds: Optional[float] = None,
                       n: int = 1, now: Optional[float] = None) -> None:
        for slo in self.slos:
            slo.record(ok, latency_seconds, n=n, now=now)

    def firing(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        alerts: List[Dict[str, Any]] = []
        for slo in self.slos:
            alerts.extend(slo.firing(now=now))
        return alerts

    def fast_burn_rate(self, window_seconds: float = 300.0,
                       min_total: float = 20.0,
                       now: Optional[float] = None) -> float:
        """The worst short-window burn rate across the set — the
        serving tier's circuit-breaker trip signal.

        ``min_total`` guards the low-traffic pathology: with 2 requests
        in the window, one failure reads as burn 500 and a naive trip
        wire would open the breaker on a single blip. Below the floor
        this reports 0.0 (the consecutive-failure threshold still
        protects low-traffic models)."""
        worst = 0.0
        for slo in self.slos:
            t = slo.clock() if now is None else now
            good, total = slo._counts.counts(window_seconds, now=t)
            if total < min_total:
                continue
            # one window scan per SLO (this runs on the failure path,
            # during exactly the bursts it exists for); the arithmetic
            # is burn_rate's own, shared via _rate
            worst = max(worst, slo._rate(good, total))
        return worst

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = self.clock() if now is None else now
        slos = [slo.snapshot(now=now) for slo in self.slos]
        return {
            "slos": slos,
            "alerts": [a for s in slos for a in s["alerts"]],
        }

    def publish(self, registry=None,
                now: Optional[float] = None) -> Dict[str, Any]:
        """Evaluate AND mirror the verdict into the metrics registry, so
        ``/metrics`` scrapes carry burn rates, budget remaining, and
        firing alerts as ``sparkml_slo_*`` gauges. Returns the snapshot."""
        if registry is None:
            from spark_rapids_ml_tpu.obs.metrics import get_registry

            registry = get_registry()
        snap = self.snapshot(now=now)
        burn = registry.gauge(
            "sparkml_slo_burn_rate",
            "SLO error-budget burn rate per trailing window "
            "(1.0 = spending exactly the budget)", ("slo", "window"),
        )
        budget = registry.gauge(
            "sparkml_slo_budget_remaining",
            "fraction of the SLO error budget remaining over the budget "
            "window (1 untouched, 0 spent, negative blown)", ("slo",),
        )
        alert = registry.gauge(
            "sparkml_slo_alert_firing",
            "1 when the multi-window burn-rate alert fires", ("slo",
                                                              "severity"),
        )
        for slo_snap in snap["slos"]:
            name = slo_snap["name"]
            for window, rate in slo_snap["burn_rates"].items():
                burn.set(rate, slo=name, window=window)
            budget.set(slo_snap["budget_remaining"], slo=name)
            firing = {a["severity"] for a in slo_snap["alerts"]}
            for policy in BURN_POLICIES:
                alert.set(
                    1.0 if policy["severity"] in firing else 0.0,
                    slo=name, severity=policy["severity"],
                )
        return snap


def severity_for_burn(burn_rate: float) -> Optional[str]:
    """Incident severity implied by a short-window burn rate, on the
    same SRE-workbook ladder the alert policies page with: the
    page_fast factor (14.4) is critical, page_slow (6.0) serious, any
    budget overspend (>= 1.0) a warning, and an idle/healthy service
    (None) implies nothing. ``obs.incidents`` escalates every opening
    incident through this, so "severity" means the same thing on a
    page and on an incident record."""
    if burn_rate >= BURN_POLICIES[0]["factor"]:
        return "critical"
    if burn_rate >= BURN_POLICIES[1]["factor"]:
        return "serious"
    if burn_rate >= 1.0:
        return "warning"
    return None


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(ENV_PREFIX + name, default))
    except ValueError:
        return default


def default_slos(clock: Callable[[], float] = time.monotonic) -> SloSet:
    """The serving tier's default objectives from ``SPARK_RAPIDS_ML_TPU_
    SLO_*`` env knobs (a target of 0 disables that objective)."""
    window_seconds = _env_float("WINDOW_HOURS", 6.0) * 3600.0
    slos: List[SLO] = []
    availability_target = _env_float("AVAILABILITY_TARGET", 0.999)
    if 0.0 < availability_target < 1.0:
        slos.append(SLO(
            "serve_availability", target=availability_target,
            kind="availability", window_seconds=window_seconds, clock=clock,
        ))
    latency_target = _env_float("LATENCY_TARGET", 0.99)
    threshold_ms = _env_float("LATENCY_THRESHOLD_MS", 250.0)
    if 0.0 < latency_target < 1.0 and threshold_ms > 0:
        slos.append(SLO(
            "serve_latency", target=latency_target, kind="latency",
            latency_threshold_seconds=threshold_ms / 1000.0,
            window_seconds=window_seconds, clock=clock,
        ))
    return SloSet(slos, clock=clock)


__all__ = [
    "BURN_POLICIES",
    "ENV_PREFIX",
    "SLO",
    "SloSet",
    "WindowedCounts",
    "default_slos",
    "severity_for_burn",
]
