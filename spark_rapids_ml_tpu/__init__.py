"""spark_rapids_ml_tpu — a TPU-native ML accelerator framework.

A from-scratch, TPU-first re-design of the capabilities of NVIDIA's
``spark-rapids-ml`` (the 21.12 "RAPIDS Accelerator for Apache Spark ML"
snapshot): drop-in-style Estimator/Model APIs whose heavy linear algebra runs
on TPU through JAX/XLA instead of cuBLAS/cuSolver through JNI.

Architecture (vs. the reference's six layers, see SURVEY.md §1):

* the user-facing API keeps Spark ML Estimator/Model/Params semantics
  (``models/``), including model persistence in Spark ML's on-disk format
  (``io/``);
* distributed covariance/gram assembly is a sharded XLA program over a
  ``jax.sharding.Mesh`` — per-device partial Gram matrices are combined with
  an on-device ``psum`` over ICI rather than a driver-side reduce
  (``parallel/``);
* the device kernels are jit-compiled XLA programs (MXU matmuls, fused
  center+scale+gram Pallas kernel, ``eigh`` eigensolver) instead of per-call
  JNI → cudaMalloc → cublas round trips (``ops/``);
* the native runtime layer is a C++ host library (``native/``) exposing the
  same six-call surface the reference's ``librapidsml_jni.so`` had, used for
  the CPU fallback paths and host-side buffer management, bound via ctypes
  (no JNI / no CUDA toolkit anywhere in the build).
"""

__version__ = "0.1.0"

from spark_rapids_ml_tpu.models.pca import PCA, PCAModel  # noqa: F401
from spark_rapids_ml_tpu.models.kmeans import KMeans, KMeansModel  # noqa: F401
from spark_rapids_ml_tpu.models.nearest_neighbors import (  # noqa: F401
    NearestNeighbors,
    NearestNeighborsModel,
)
from spark_rapids_ml_tpu.models.linear_regression import (  # noqa: F401
    LinearRegression,
    LinearRegressionModel,
)
from spark_rapids_ml_tpu.models.logistic_regression import (  # noqa: F401
    LogisticRegression,
    LogisticRegressionModel,
)
from spark_rapids_ml_tpu.models.linear_svc import (  # noqa: F401
    LinearSVC,
    LinearSVCModel,
)
from spark_rapids_ml_tpu.models.glm import (  # noqa: F401
    GeneralizedLinearRegression,
    GeneralizedLinearRegressionModel,
)
from spark_rapids_ml_tpu.models.gaussian_mixture import (  # noqa: F401
    GaussianMixture,
    GaussianMixtureModel,
)
from spark_rapids_ml_tpu.models.mlp import (  # noqa: F401
    MultilayerPerceptronClassifier,
    MultilayerPerceptronModel,
)
from spark_rapids_ml_tpu.models.feature_transformers import (  # noqa: F401
    Bucketizer,
    ChiSqSelector,
    ChiSqSelectorModel,
    ElementwiseProduct,
    IndexToString,
    OneHotEncoder,
    OneHotEncoderModel,
    PolynomialExpansion,
    QuantileDiscretizer,
    StringIndexer,
    StringIndexerModel,
    VarianceThresholdSelector,
    VarianceThresholdSelectorModel,
    VectorAssembler,
    VectorSlicer,
)
from spark_rapids_ml_tpu.models.survival_regression import (  # noqa: F401
    AFTSurvivalRegression,
    AFTSurvivalRegressionModel,
    IsotonicRegression,
    IsotonicRegressionModel,
)
from spark_rapids_ml_tpu.models.bisecting_kmeans import (  # noqa: F401
    BisectingKMeans,
    BisectingKMeansModel,
)
from spark_rapids_ml_tpu.models.fm import (  # noqa: F401
    FMClassificationModel,
    FMClassifier,
    FMRegressionModel,
    FMRegressor,
)
from spark_rapids_ml_tpu.models.als import ALS, ALSModel  # noqa: F401
from spark_rapids_ml_tpu.models.lda import LDA, LDAModel  # noqa: F401
from spark_rapids_ml_tpu.models.word2vec import (  # noqa: F401
    Word2Vec,
    Word2VecModel,
)
from spark_rapids_ml_tpu.models.decision_tree import (  # noqa: F401
    DecisionTreeClassificationModel,
    DecisionTreeClassifier,
    DecisionTreeRegressionModel,
    DecisionTreeRegressor,
)
from spark_rapids_ml_tpu.models.pic import (  # noqa: F401
    PowerIterationClustering,
)
from spark_rapids_ml_tpu.models.feature_transformers2 import (  # noqa: F401
    DCT,
    FeatureHasher,
    Interaction,
    RFormula,
    RFormulaModel,
    UnivariateFeatureSelector,
    UnivariateFeatureSelectorModel,
    SQLTransformer,
    VectorIndexer,
    VectorIndexerModel,
    VectorSizeHint,
)
from spark_rapids_ml_tpu.models.fpm import (  # noqa: F401
    FPGrowth,
    FPGrowthModel,
    PrefixSpan,
)
from spark_rapids_ml_tpu.models.lsh import (  # noqa: F401
    BucketedRandomProjectionLSH,
    BucketedRandomProjectionLSHModel,
    MinHashLSH,
    MinHashLSHModel,
)
from spark_rapids_ml_tpu.models.text import (  # noqa: F401
    CountVectorizer,
    CountVectorizerModel,
    HashingTF,
    IDF,
    IDFModel,
    NGram,
    RegexTokenizer,
    StopWordsRemover,
    Tokenizer,
)
from spark_rapids_ml_tpu.stat import (  # noqa: F401
    ANOVATest,
    ChiSquareTest,
    Correlation,
    FValueTest,
    KolmogorovSmirnovTest,
    Summarizer,
)
from spark_rapids_ml_tpu.models.dbscan import DBSCAN, DBSCANModel  # noqa: F401
from spark_rapids_ml_tpu.models.naive_bayes import (  # noqa: F401
    NaiveBayes,
    NaiveBayesModel,
)
from spark_rapids_ml_tpu.models.ovr import OneVsRest, OneVsRestModel  # noqa: F401
from spark_rapids_ml_tpu.models.umap import UMAP, UMAPModel  # noqa: F401
from spark_rapids_ml_tpu.models.feature_scalers import (  # noqa: F401
    MaxAbsScaler,
    MaxAbsScalerModel,
    MinMaxScaler,
    MinMaxScalerModel,
    Normalizer,
)
from spark_rapids_ml_tpu.models.gbt import (  # noqa: F401
    GBTClassificationModel,
    GBTClassifier,
    GBTRegressionModel,
    GBTRegressor,
)
from spark_rapids_ml_tpu.models.random_forest import (  # noqa: F401
    RandomForestClassificationModel,
    RandomForestClassifier,
    RandomForestRegressionModel,
    RandomForestRegressor,
)
from spark_rapids_ml_tpu.models.feature_scalers import (  # noqa: F401
    Binarizer,
    RobustScaler,
    RobustScalerModel,
)
from spark_rapids_ml_tpu.models.imputer import Imputer, ImputerModel  # noqa: F401
from spark_rapids_ml_tpu.models.pipeline import Pipeline, PipelineModel  # noqa: F401
from spark_rapids_ml_tpu.models.evaluation import (  # noqa: F401
    BinaryClassificationEvaluator,
    ClusteringEvaluator,
    MulticlassClassificationEvaluator,
    MultilabelClassificationEvaluator,
    RankingEvaluator,
    RegressionEvaluator,
)
from spark_rapids_ml_tpu.models.tuning import (  # noqa: F401
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    TrainValidationSplit,
    TrainValidationSplitModel,
)
from spark_rapids_ml_tpu.models.svd import TruncatedSVD, TruncatedSVDModel  # noqa: F401
from spark_rapids_ml_tpu.models.scaler import StandardScaler, StandardScalerModel  # noqa: F401
from spark_rapids_ml_tpu.data.vector import DenseVector, SparseVector, Vectors  # noqa: F401

__all__ = [
    "PCA",
    "PCAModel",
    "KMeans",
    "KMeansModel",
    "DBSCAN",
    "DBSCANModel",
    "NearestNeighbors",
    "NearestNeighborsModel",
    "LinearRegression",
    "LinearRegressionModel",
    "LogisticRegression",
    "LogisticRegressionModel",
    "LinearSVC",
    "LinearSVCModel",
    "GeneralizedLinearRegression",
    "GeneralizedLinearRegressionModel",
    "GaussianMixture",
    "GaussianMixtureModel",
    "Correlation",
    "KolmogorovSmirnovTest",
    "ANOVATest",
    "FValueTest",
    "ClusteringEvaluator",
    "RankingEvaluator",
    "ChiSquareTest",
    "Summarizer",
    "MultilayerPerceptronClassifier",
    "MultilayerPerceptronModel",
    "StringIndexer",
    "StringIndexerModel",
    "IndexToString",
    "OneHotEncoder",
    "OneHotEncoderModel",
    "VectorAssembler",
    "Bucketizer",
    "QuantileDiscretizer",
    "ElementwiseProduct",
    "VectorSlicer",
    "PolynomialExpansion",
    "VarianceThresholdSelector",
    "VarianceThresholdSelectorModel",
    "ChiSqSelector",
    "ChiSqSelectorModel",
    "AFTSurvivalRegression",
    "AFTSurvivalRegressionModel",
    "IsotonicRegression",
    "IsotonicRegressionModel",
    "Tokenizer",
    "RegexTokenizer",
    "StopWordsRemover",
    "NGram",
    "HashingTF",
    "CountVectorizer",
    "CountVectorizerModel",
    "IDF",
    "IDFModel",
    "FMRegressor",
    "ALS",
    "ALSModel",
    "LDA",
    "LDAModel",
    "Word2Vec",
    "Word2VecModel",
    "DecisionTreeClassifier",
    "DecisionTreeClassificationModel",
    "DecisionTreeRegressor",
    "DecisionTreeRegressionModel",
    "PowerIterationClustering",
    "BucketedRandomProjectionLSH",
    "BucketedRandomProjectionLSHModel",
    "MinHashLSH",
    "MinHashLSHModel",
    "FPGrowth",
    "FPGrowthModel",
    "PrefixSpan",
    "DCT",
    "Interaction",
    "FeatureHasher",
    "VectorIndexer",
    "VectorIndexerModel",
    "VectorSizeHint",
    "SQLTransformer",
    "MultilabelClassificationEvaluator",
    "UnivariateFeatureSelector",
    "UnivariateFeatureSelectorModel",
    "RFormula",
    "RFormulaModel",
    "FMRegressionModel",
    "FMClassifier",
    "FMClassificationModel",
    "BisectingKMeans",
    "BisectingKMeansModel",
    "NaiveBayes",
    "NaiveBayesModel",
    "OneVsRest",
    "MinMaxScaler",
    "MinMaxScalerModel",
    "MaxAbsScaler",
    "MaxAbsScalerModel",
    "Normalizer",
    "GBTClassifier",
    "GBTClassificationModel",
    "GBTRegressor",
    "GBTRegressionModel",
    "RandomForestClassifier",
    "RandomForestClassificationModel",
    "RandomForestRegressor",
    "RandomForestRegressionModel",
    "UMAP",
    "UMAPModel",
    "OneVsRestModel",
    "Pipeline",
    "PipelineModel",
    "RegressionEvaluator",
    "BinaryClassificationEvaluator",
    "MulticlassClassificationEvaluator",
    "Binarizer",
    "RobustScaler",
    "RobustScalerModel",
    "Imputer",
    "ImputerModel",
    "ParamGridBuilder",
    "CrossValidator",
    "CrossValidatorModel",
    "TrainValidationSplit",
    "TrainValidationSplitModel",
    "TruncatedSVD",
    "TruncatedSVDModel",
    "StandardScaler",
    "StandardScalerModel",
    "DenseVector",
    "SparseVector",
    "Vectors",
    "__version__",
]
