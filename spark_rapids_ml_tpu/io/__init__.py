from spark_rapids_ml_tpu.io.persistence import (
    load_model,
    load_pca_model,
    save_pca_model,
    load_params,
    save_params,
)

__all__ = [
    "load_model",
    "load_pca_model",
    "save_pca_model",
    "load_params",
    "save_params",
]
